"""Rejection-sampling fine-tuning (RFT) trainer.

Parity: /root/reference/trlx/trainer/accelerate_rft_trainer.py:46-197 —
every `n_improve_steps` epochs, sample `n_generations_per_prompt`
continuations per prompt, score them with the reward_fn, keep samples
above a per-prompt score percentile that rises from `start_percentile`
to `end_percentile` across the improve window, dedup, and fine-tune on
the survivors with full-sequence LM loss.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List

import jax
import numpy as np

from trlx_tpu.data import SFTBatch
from trlx_tpu.data.method_configs import RFTConfig
from trlx_tpu.models.wrappers import CausalLM
from trlx_tpu.parallel import shard_params
from trlx_tpu.parallel import multihost as mh
from trlx_tpu.pipeline.offline_pipeline import DialogStore, tokenize_dialogue
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUBaseTrainer
from trlx_tpu.trainer.sft import sft_loss
from trlx_tpu.utils import logging
from trlx_tpu.ops.remat import resolve_remat

logger = logging.get_logger(__name__)


def compute_thresholds(per_prompt_scores: List[List[float]], percentile: float) -> np.ndarray:
    """Per-prompt selection thresholds at the given score percentile.

    Quantized rewards: nudge each threshold above the prompt's minimum so
    exact-min scores are excluded, but cap it at the prompt's maximum so
    the best sample always survives (selection uses `score >= threshold`).
    The reference clips against the *global* min/max of the thresholds
    array, which both inverts when every score is equal (np.clip then
    returns the upper bound, deselecting everything) and can push a
    constant-score prompt's threshold above its own maximum; per-prompt
    bounds avoid both failure modes.
    """
    thresholds = np.array(
        [np.quantile(np.asarray(s), percentile) for s in per_prompt_scores]
    )
    mins = np.array([min(s) for s in per_prompt_scores])
    maxs = np.array([max(s) for s in per_prompt_scores])
    return np.minimum(np.maximum(thresholds, mins + 1e-3), maxs)


@register_trainer("TPURFTTrainer")
class TPURFTTrainer(TPUBaseTrainer):
    def __init__(self, config, **kwargs):
        if not isinstance(config.method, RFTConfig):
            raise ValueError("config.method must be RFTConfig")
        super().__init__(config, **kwargs)
        self.generations_per_prompt: Dict[str, List[dict]] = defaultdict(list)
        self.epoch_count = 0

    def setup_model(self) -> None:
        if self.config.model.model_arch_type == "seq2seq":
            raise NotImplementedError("seq2seq RFT is not implemented (causal only)")
        cfg, base_params, self.model_type = self.load_base_model()
        self.model = CausalLM(cfg)
        self.rng, key = jax.random.split(self.rng)
        params = self.attach_lora(self.model.init_params(key, base_params))
        self.params = shard_params(self.mesh, params)

    def trainable_mask(self):
        return self.lora_freeze_mask(self.params) or self.make_freeze_mask(self.params)

    def loss(self, params, batch: SFTBatch):
        # full-sequence LM loss: every non-pad token is a label (parity:
        # reference loss :82-87 labels=input_ids)
        import jax.numpy as jnp

        chunks = self.config.train.logit_chunks
        out = self.model.forward(
            params, batch.input_ids, batch.attention_mask,
            remat=resolve_remat(self.config.train.remat_policy),
            compute_logits=chunks == 0,
        )
        labels = jnp.where(batch.attention_mask > 0, batch.input_ids, -100)
        if chunks:
            from trlx_tpu.trainer.sft import sft_loss_from_hidden

            return sft_loss_from_hidden(
                out["hidden_states"], self.model.logit_project_fn(params),
                labels, chunks,
            )
        return sft_loss(out["logits"], labels)

    def add_prompt_pipeline(self, pipeline) -> None:
        # multi-host: each process generates/scores its strided slice;
        # selection happens on the all-gathered pool below
        pipeline = mh.shard_pipeline(pipeline, self.mesh)
        self.prompt_dataloader = pipeline.create_loader(
            max(self.config.train.batch_size // mh.data_group_count(self.mesh), 1)
        )

    def make_experience(self, samples=None, rewards=None, seq_length=None) -> None:
        """Regenerate + rescore + reselect the training set (parity:
        reference make_experience :117-197)."""
        method = self.config.method
        if self.epoch_count % method.n_improve_steps == 0:
            # hang doctor: RFT's generate+score sweep is its rollout
            # phase — heartbeat per generation so a wedged sampler (or
            # reward call, which has its own phase) trips the deadline
            self.watchdog.beat("rollout", "start", step=self.iter_count)
            generations = []
            for batch in self.prompt_dataloader:
                for _ in range(method.n_generations_per_prompt):
                    self.watchdog.beat("rollout", step=self.iter_count)
                    # memory-doctor envelope: a prefill OOM in the
                    # sweep walks the shrink_pool rung and retries
                    out = self._generate_rollout(
                        batch.input_ids, batch.attention_mask
                    )
                    sequences = mh.local_rows(out["sequences"])
                    # ragged multi-host batches come back padded with
                    # real_rows marking this group's real count
                    sequences = sequences[: out.get("real_rows", len(sequences))]
                    _, str_prompts, str_outputs = self.decode(
                        np.asarray(batch.input_ids), sequences,
                        [np.shape(batch.input_ids)[1]] * len(sequences),
                        append_eos_token=True,
                    )
                    generations.extend(
                        {"prompt": p, "output": o}
                        for p, o in zip(str_prompts, str_outputs)
                    )

            scores = self._call_reward_fn(
                samples=[g["prompt"] + g["output"] for g in generations],
                prompts=[g["prompt"] for g in generations],
                outputs=[g["output"] for g in generations],
            )
            scored = [
                {"prompt": g["prompt"], "output": g["output"], "score": float(s)}
                for g, s in zip(generations, scores)
            ]
            # multi-host: pool every DATA GROUP's generations so threshold
            # selection sees the full set (reference all_gather_object,
            # accelerate_rft_trainer.py:127-144). Processes on other pp
            # stages of the same rows contribute replicas — keep one
            # representative per group to avoid double-counting.
            keep = set(mh.group_representatives(self.mesh))
            for proc, part in enumerate(mh.allgather_object(scored)):
                if proc not in keep:
                    continue
                for g in part:
                    self.generations_per_prompt[g["prompt"]].append(
                        {"output": g["output"], "score": g["score"]}
                    )
            self.watchdog.beat("rollout", "end", step=self.iter_count)

        per_prompt_scores = [
            [x["score"] for x in self.generations_per_prompt[p]]
            for p in self.generations_per_prompt
        ]
        percentile_delta = (
            method.end_percentile - method.start_percentile
        ) / method.n_improve_steps
        percentile = method.start_percentile + percentile_delta * (
            self.epoch_count % method.n_improve_steps
        )
        thresholds = compute_thresholds(per_prompt_scores, percentile)

        samples_selected = []
        for prompt, threshold in zip(self.generations_per_prompt, thresholds):
            for x in self.generations_per_prompt[prompt]:
                if x["score"] >= threshold:
                    samples_selected.append((prompt, x["output"]))
        samples_selected = sorted(set(samples_selected))

        self._tracker_log(
            {
                "scores_mean": float(np.mean(np.hstack(per_prompt_scores))),
                "len_samples_selected": len(samples_selected),
                "percentile": float(percentile),
            },
            step=self.iter_count,
        )

        if samples_selected:
            # wrap-pad to a full multiple of the global batch so every
            # train batch is rectangular and divides the mesh's data ways
            # (a ragged final batch cannot be sharded)
            bs = self.config.train.batch_size
            target = -(-len(samples_selected) // bs) * bs
            i = 0
            while len(samples_selected) < target:
                samples_selected.append(samples_selected[i])
                i += 1
            dialogs = [
                tokenize_dialogue(list(pair), self.tokenizer, self.config.train.seq_length)
                for pair in samples_selected
            ]
            # fixed width across improve rounds: one compiled train step
            self.store = DialogStore(
                dialogs, self.tokenizer, max_length=self.config.train.seq_length
            )

    def prepare_learning(self) -> None:
        self.eval_dataloader = self.eval_pipeline.create_loader(
            self.config.train.batch_size
        )
        self.n_inner_epochs = 1
        self.total_steps = self.config.train.total_steps
        self.epoch_count = 0
        self.make_experience()

    def create_train_dataloader(self):
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True,
            seed=self.config.train.seed + self.iter_count,
        )

    def post_epoch_callback(self) -> None:
        self.epoch_count += 1
        self.make_experience()
