"""DPO trainer: offline direct preference optimization
(Rafailov et al., arXiv:2305.18290).

Offline like ILQL/SFT: `trlx_tpu.train(samples=[(prompt, chosen,
rejected), ...], config=...)` builds a pairwise store
(pipeline/dpo_pipeline.py) and the per-step loop (or the fused scan)
minimizes the sigmoid preference loss over policy-vs-frozen-reference
logprob margins (ops/dpo.py). The frozen reference is a deep copy of
the INITIAL policy (with LoRA, the adapter-disabled base — the peft
DPO convention), captured at setup so the train step's buffer donation
can never alias it.

Each step runs chosen and rejected rows as ONE stacked forward (the
pair storage collates both sides to a shared static width), plus one
reference forward of the same shape whose gradient is never taken.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from trlx_tpu.data import DPOBatch
from trlx_tpu.data.method_configs import DPOConfig
from trlx_tpu.models.transformer import logit_projection
from trlx_tpu.models.wrappers import CausalLM
from trlx_tpu.ops.common import chunked_logprobs, logprobs_of_labels
from trlx_tpu.ops.dpo import dpo_loss
from trlx_tpu.ops.remat import resolve_remat
from trlx_tpu.parallel import shard_params
from trlx_tpu.pipeline.dpo_pipeline import DPOPairStorage
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUBaseTrainer
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


@register_trainer("TPUDPOTrainer")
class TPUDPOTrainer(TPUBaseTrainer):
    def __init__(self, config, **kwargs):
        if not isinstance(config.method, DPOConfig):
            raise ValueError("config.method must be DPOConfig")
        super().__init__(config, **kwargs)

    def setup_model(self) -> None:
        if self.config.model.model_arch_type == "seq2seq":
            raise NotImplementedError("seq2seq DPO is not implemented (causal only)")
        self.seq2seq = False
        cfg, base_params, self.model_type = self.load_base_model()
        self.model = CausalLM(cfg)
        self.rng, key = jax.random.split(self.rng)
        params = self.attach_lora(self.model.init_params(key, base_params))
        self.params = shard_params(self.mesh, params)
        # frozen reference = the initial policy's base tree, DEEP-COPIED:
        # the train step donates self.params buffers every step, so the
        # reference must not alias them. With LoRA the adapter-disabled
        # base IS the reference (peft DPO convention) and stays frozen
        # for free — the copy still guards against donation.
        self.ref_params = jax.tree_util.tree_map(jnp.copy, self.params["base"])

    def trainable_mask(self):
        return self.lora_freeze_mask(self.params) or self.make_freeze_mask(self.params)

    def _sequence_logprobs(self, params, ref_params, ids, mask, resp_mask, remat):
        """Policy and frozen-reference summed response logprobs for one
        stacked [chosen; rejected] row block."""
        chunks = self.config.train.logit_chunks
        resp = resp_mask[:, 1:].astype(jnp.float32)
        out = self.model.forward(
            params, ids, mask, remat=remat, compute_logits=chunks == 0
        )
        ref_out = self.model.lm(
            ref_params, ids, mask, remat=remat, compute_logits=chunks == 0
        )
        if chunks:
            lp = chunked_logprobs(
                self.model.logit_project_fn(params),
                out["hidden_states"][:, :-1], ids[:, 1:], chunks,
            )
            ref_lp = chunked_logprobs(
                logit_projection(ref_params),
                ref_out["hidden_states"][:, :-1], ids[:, 1:], chunks,
            )
        else:
            lp = logprobs_of_labels(out["logits"][:, :-1], ids[:, 1:])
            ref_lp = logprobs_of_labels(ref_out["logits"][:, :-1], ids[:, 1:])
        return (lp * resp).sum(axis=-1), (ref_lp * resp).sum(axis=-1)

    def loss(self, params, batch: DPOBatch):
        method = self.config.method
        remat = resolve_remat(self.config.train.remat_policy)
        B = batch.chosen_ids.shape[0]
        ids = jnp.concatenate([batch.chosen_ids, batch.rejected_ids], axis=0)
        mask = jnp.concatenate(
            [batch.chosen_attention_mask, batch.rejected_attention_mask], axis=0
        )
        resp = jnp.concatenate(
            [batch.chosen_response_mask, batch.rejected_response_mask], axis=0
        )
        seq_lp, ref_seq_lp = self._sequence_logprobs(
            params, self.ref_params, ids, mask, resp, remat
        )
        return dpo_loss(
            seq_lp[:B], seq_lp[B:], ref_seq_lp[:B], ref_seq_lp[B:],
            beta=method.beta, label_smoothing=method.label_smoothing,
        )

    def make_experience(
        self,
        samples: List,
        rewards: Optional[List[float]] = None,
        seq_length: int = 1024,
    ) -> None:
        """Build the pairwise store from (prompt, chosen, rejected)
        triples. ``rewards`` must be None — DPO's signal is the pair
        ordering itself (pass preference pairs, not scored samples)."""
        if rewards is not None:
            raise ValueError(
                "DPO takes no rewards: pass samples as (prompt, chosen, "
                "rejected) triples — the preference ordering IS the signal"
            )
        # hang doctor: tokenization is host-bound but can still wedge on
        # a slow/remote tokenizer backend — heartbeat it as its own phase
        with self.watchdog.phase("experience"):
            self.store = DPOPairStorage(
                samples, self.tokenizer, max_length=seq_length
            )

    def prepare_learning(self) -> None:
        self.eval_dataloader = self.eval_pipeline.create_loader(
            self.config.train.batch_size
        )
        self.n_inner_epochs = 1
        n_batches = len(self.store) // self.config.train.batch_size
        self.total_steps = min(
            self.config.train.epochs * max(n_batches, 1),
            self.config.train.total_steps,
        )

    def create_train_dataloader(self):
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True, drop_last=True,
            seed=self.config.train.seed + self.iter_count,
        )
