"""Supervised fine-tuning trainer.

Parity: /root/reference/trlx/trainer/accelerate_sft_trainer.py:29-97 —
causal-LM cross-entropy with -100 masking of prompt/padding tokens; the
store is a DialogStore over (prompt, output) pairs or plain strings.
"""

from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp

from trlx_tpu.data import SFTBatch
from trlx_tpu.data.method_configs import SFTConfig
from trlx_tpu.models.wrappers import CausalLM
from trlx_tpu.parallel import shard_params
from trlx_tpu.pipeline.offline_pipeline import DialogStore, tokenize_dialogue
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUBaseTrainer
from trlx_tpu.utils import logging
from trlx_tpu.ops.remat import resolve_remat

logger = logging.get_logger(__name__)


def sft_loss(logits: jnp.ndarray, labels: jnp.ndarray):
    """Shifted cross-entropy; label -100 = ignored (HF convention)."""
    logits = logits[:, :-1].astype(jnp.float32)
    labels = labels[:, 1:]
    mask = (labels != -100).astype(jnp.float32)
    safe_labels = jnp.where(labels == -100, 0, labels)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    n = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / n
    return loss, {"losses/loss": loss, "perplexity": jnp.exp(loss)}


def sft_loss_from_hidden(hidden, project_fn, labels, n_chunks: int):
    """`sft_loss` without materializing [B, T, V] logits: per-token
    logprobs come from a checkpointed chunk scan over the sequence
    (ops.common.chunked_logprobs) — the train.logit_chunks path."""
    from trlx_tpu.ops.common import chunked_logprobs

    labels = labels[:, 1:]
    mask = (labels != -100).astype(jnp.float32)
    safe_labels = jnp.where(labels == -100, 0, labels)
    lp = chunked_logprobs(project_fn, hidden[:, :-1], safe_labels, n_chunks)
    n = jnp.maximum(mask.sum(), 1.0)
    loss = -(lp * mask).sum() / n
    return loss, {"losses/loss": loss, "perplexity": jnp.exp(loss)}


@register_trainer("TPUSFTTrainer")
class TPUSFTTrainer(TPUBaseTrainer):
    def __init__(self, config, **kwargs):
        if not isinstance(config.method, SFTConfig):
            raise ValueError("config.method must be SFTConfig")
        super().__init__(config, **kwargs)

    def setup_model(self) -> None:
        if self.config.model.model_arch_type == "seq2seq":
            raise NotImplementedError("seq2seq SFT is not implemented (causal only)")
        cfg, base_params, self.model_type = self.load_base_model()
        self.model = CausalLM(cfg)
        self.rng, key = jax.random.split(self.rng)
        params = self.attach_lora(self.model.init_params(key, base_params))
        self.params = shard_params(self.mesh, params)

    def trainable_mask(self):
        return self.lora_freeze_mask(self.params) or self.make_freeze_mask(self.params)

    def loss(self, params, batch: SFTBatch):
        chunks = self.config.train.logit_chunks
        out = self.model.forward(
            params, batch.input_ids, batch.attention_mask,
            remat=resolve_remat(self.config.train.remat_policy),
            compute_logits=chunks == 0,
        )
        if chunks:
            return sft_loss_from_hidden(
                out["hidden_states"], self.model.logit_project_fn(params),
                batch.labels, chunks,
            )
        return sft_loss(out["logits"], batch.labels)

    def make_experience(
        self,
        samples: Union[List[str], List[tuple], List[list]],
        rewards: Optional[List[float]] = None,
        seq_length: int = 1024,
    ) -> None:
        del rewards  # SFT ignores rewards (parity: reference :80-88)
        # hang doctor: tokenization is host-bound but can still wedge on
        # a slow/remote tokenizer backend — heartbeat it as its own phase
        with self.watchdog.phase("experience"):
            dialogs = [
                tokenize_dialogue(s, self.tokenizer, seq_length)
                for s in samples
            ]
            self.store = DialogStore(
                dialogs, self.tokenizer, max_length=seq_length
            )

    def prepare_learning(self) -> None:
        self.eval_dataloader = self.eval_pipeline.create_loader(
            self.config.train.batch_size
        )
        self.n_inner_epochs = 1
        n_batches = len(self.store) // self.config.train.batch_size
        self.total_steps = min(
            self.config.train.epochs * max(n_batches, 1),
            self.config.train.total_steps,
        )

    def create_train_dataloader(self):
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True,
            seed=self.config.train.seed + self.iter_count,
        )
