"""TPU trainer base: model/optimizer setup, generation, evaluation, the
`learn()` loop, and checkpointing.

Parity: /root/reference/trlx/trainer/accelerate_base_trainer.py:40-682
(AccelerateRLTrainer) — same hook surface (`get_arch` / `loss` /
`prepare_learning` / `create_train_dataloader` / `post_backward_callback`
/ `post_epoch_callback`), the same loop structure (epochs -> inner epochs
-> batches with gradient accumulation), the same checkpoint layout
(`checkpoint_{step}` + `best_checkpoint`, each containing `hf_model/`)
and the same metric keys (`time/step`, `reward/mean`,
`learning_rate_group_0`, ...; `time/forward`/`time/backward` are emitted
when `train.timing_split` is on — the fused jitted step has no per-step
split, so those keys come from a one-shot measured forward probe).

TPU re-design:
- One trainer covers what the reference splits across the Accelerate and
  NeMo backends: DP/FSDP/TP are mesh-axis sizes in `TrainConfig.mesh`.
- Gradient accumulation is a `lax.scan` over microbatches inside ONE
  jitted train step (the reference's `_accumulate`/no_sync dance exists
  to suppress per-microbatch NCCL allreduce — under jit the grads are
  reduced exactly once by construction).
- The optimizer step, freeze masking and LR schedule live in the same
  jitted function; params/opt-state are donated (no HBM copies).
"""

from __future__ import annotations

import dataclasses
import json
import os
from abc import abstractmethod
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from trlx_tpu.data import PromptBatch
from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.exp import ExpConfig, ExperienceTransport
from trlx_tpu.exp import transport as exp_transport
from trlx_tpu.fleet.config import FleetConfig
from trlx_tpu.ops.common import running_moments_init, running_moments_update
from trlx_tpu.models.generation import (
    HF_GEN_KWARGS_UNIMPLEMENTED,
    SamplerSettings,
    generate,
)
from trlx_tpu.models.hf import load_pretrained, save_pretrained_hf
from trlx_tpu.models.transformer import TransformerConfig, TransformerLM
from trlx_tpu.parallel import (
    data_sharding,
    init_sharded_opt_state,
    make_mesh,
)
from trlx_tpu.parallel import multihost as mh
from trlx_tpu.parallel.mesh import replicated_sharding, vector_sharding
from trlx_tpu.pipeline import DataLoader
from trlx_tpu.trainer import BaseRLTrainer
from trlx_tpu.utils import (
    Clock,
    build_optimizer,
    infinite_loader,
    logging,
    significant,
    to_scalar,
)
from trlx_tpu.utils.chaos import build_chaos, poison_batch
from trlx_tpu.utils.checkpointing import (
    TOPOLOGY_MANIFEST,
    CheckpointCorruptError,
    CheckpointManager,
    ElasticConfig,
    PreemptionHandler,
    atomic_json_write,
    verify_or_quarantine,
)
from trlx_tpu.utils.guardrails import (
    FLEET_SIGNAL,
    MEMORY_SIGNAL,
    STALENESS_SIGNAL,
    STALL_SIGNAL,
    build_monitor,
)
from trlx_tpu.obs import build_observer
from trlx_tpu.obs.telemetry import tree_param_count
from trlx_tpu.utils.memdoctor import (
    MemoryAbortError,
    MemoryPlanError,
    build_memdoctor,
    classify_oom,
    estimate_plan,
    is_degraded_record,
    is_oom,
    remat_strength,
)
from trlx_tpu.utils.resilient import (
    ChaosFault,
    CircuitBreaker,
    ResilientCaller,
    ResilientIOConfig,
    retry_call,
)
from trlx_tpu.utils.tokenizers import load_tokenizer
from trlx_tpu.utils.trackers import DeferredStats, Tracker
from trlx_tpu.utils.watchdog import StallReport, build_watchdog

logger = logging.get_logger(__name__)

# TransformerConfig knobs that tune EXECUTION, not architecture. Mesh
# presets ship these in model_extra_configs["transformer"]; they apply
# on top of whatever checkpoint is loaded, and their presence alone
# must not trigger the random-init path (architecture keys do).
_RUNTIME_TRANSFORMER_KEYS = frozenset({
    "attention_impl", "kv_cache_quant", "decode_weights_quant",
    "pp_microbatches", "pp_schedule",
})


def _apply_runtime_overrides(cfg, extra_dict):
    """Apply _RUNTIME_TRANSFORMER_KEYS present in a model_extra_configs
    sub-dict onto a loaded model config (only the fields the config
    actually has — seq2seq has decode_weights_quant but not
    kv_cache_quant, for instance)."""
    names = {f.name for f in dataclasses.fields(cfg)}
    ov = {
        k: v
        for k, v in extra_dict.items()
        if k in _RUNTIME_TRANSFORMER_KEYS and k in names
    }
    return cfg.replace(**ov) if ov else cfg

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def _batch_shape_key(device_batch) -> tuple:
    """Hashable leaf-shape signature of a batch pytree; keys both the
    timing-split probe cache and the compile-step gate."""
    return tuple(tuple(x.shape) for x in jax.tree_util.tree_leaves(device_batch))


class TPUBaseTrainer(BaseRLTrainer):
    """Shared trainer machinery; subclasses provide the algorithm."""

    def __init__(
        self,
        config: TRLConfig,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        stop_sequences: Optional[List[str]] = None,
        **kwargs: Any,
    ):
        super().__init__(config, reward_fn, metric_fn, stop_sequences)
        train = config.train
        self.mesh = make_mesh(train.mesh)
        if mh.is_multihost():
            # validates the process->row-block mapping up front (raises on
            # layouts where batch rows can't be distributed consistently,
            # e.g. a process straddling partial data shards) and warms the
            # data-group cache: with pp>1 spanning processes, stages are
            # REPLICAS of the same rows and every row helper keys on data
            # groups, not processes
            mh.data_group_info(self.mesh)
        self.compute_dtype = _DTYPES[train.compute_dtype]
        self.param_dtype = _DTYPES[train.param_dtype]
        self.tokenizer = load_tokenizer(config.tokenizer)
        self.rng = jax.random.PRNGKey(train.seed)

        # subclass hook: builds self.model (wrapper), self.params and any
        # auxiliary trees (e.g. PPO's frozen reference branch)
        self.setup_model()
        # context parallelism (ring attention over `sp`) and pipeline
        # parallelism (layer stack over `pp`) both run teacher-forced
        # forwards through shard_map and need the mesh on the model
        if self.mesh.shape["sp"] > 1 or self.mesh.shape["pp"] > 1:
            self._lm().mesh = self.mesh

        self._update_mask = self.trainable_mask()
        self.tx, self.schedule = self._assemble_optimizer(
            config.optimizer, config.scheduler
        )
        with self.mesh:
            self.opt_state = init_sharded_opt_state(self.mesh, self.tx, self.params)

        gen_kwargs = dict(config.method.gen_kwargs)
        self.generate_sweep_kwarg = None
        for k, v in gen_kwargs.items():
            if isinstance(v, list):
                self.generate_sweep_kwarg = (k, v)
        if self.generate_sweep_kwarg:
            gen_kwargs.pop(self.generate_sweep_kwarg[0])
        eos = getattr(self.tokenizer, "eos_token_id", None)
        pad = getattr(self.tokenizer, "pad_token_id", None)
        if pad is None:  # NOT `or`: pad_token_id == 0 is legitimate (T5)
            pad = eos
        self.generate_settings = SamplerSettings.from_gen_kwargs(
            gen_kwargs, eos_token_id=eos, pad_token_id=pad
        )
        exp_kwargs = getattr(config.method, "gen_experience_kwargs", None)
        self.generate_experience_settings = (
            SamplerSettings.from_gen_kwargs(exp_kwargs, eos_token_id=eos, pad_token_id=pad)
            if exp_kwargs
            else self.generate_settings
        )

        self.tracker = Tracker(config)
        self.iter_count = 0
        self.nth_evaluation = 0
        self.best_reward = -float("inf")
        self.total_steps = train.total_steps
        # elastic recovery: integrity manifests + topology-change resume
        self.elastic = ElasticConfig.from_dict(train.elastic)
        self.ckpt_manager = CheckpointManager(
            train.checkpoint_dir, keep_last_n=train.keep_last_n,
            integrity=self.elastic.integrity,
        )
        self.preemption = PreemptionHandler()
        self._bad_steps = 0  # consecutive non-finite-loss steps
        self._preempt_sync_counter = 0  # multihost any_flag cadence
        # tracker outage circuit: open after _TRACKER_CIRCUIT_LIMIT
        # consecutive exhausted-retry failures; reset_timeout=0 allows
        # one un-retried probe per step while open
        self._tracker_breaker = CircuitBreaker(
            failure_threshold=self._TRACKER_CIRCUIT_LIMIT, reset_timeout=0.0
        )
        self._rollout_abandoned = False  # preemption truncated the store
        # run guardrails (divergence watchdog) + chaos harness +
        # resilient reward I/O — all default-off / behavior-preserving
        self.guardrails = build_monitor(train)
        self.chaos = build_chaos(train)
        # hang doctor: phase heartbeats + stall monitor thread (armed
        # for the duration of learn(); default-off = free beats, no
        # thread). Escalation on trip: guardrails `stall` record ->
        # emergency snapshot from the host-RAM shadow -> stalled abort.
        self.watchdog = build_watchdog(train)
        self.watchdog.on_stall(self._on_watchdog_stall)
        self._warned_shadow_skip = False
        # memory doctor (train.memory.*): preflight HBM admission
        # control, runtime watermark sampling (feeding the `memory`
        # guardrail signal), and the OOM recovery ladder (shrink pool
        # -> split microbatch -> remat -> rollback -> itemized abort).
        # Default-off = behavior-preserving: no preflight, no sampler
        # thread, RESOURCE_EXHAUSTED propagates raw.
        self.memdoctor = build_memdoctor(train)
        # per-phase peak attribution keys off the hang doctor's
        # heartbeat registry (enable train.watchdog for phase-resolved
        # peaks; otherwise everything lands under "run")
        self.memdoctor.sampler.set_phase_fn(self.watchdog.current_phase)
        self._hbm_plan = None  # preflight plan, kept for the abort report
        # flight recorder (train.obs.*, trlx_tpu/obs/ — DEFAULT ON):
        # span tracer riding the watchdog's beat sites, unified JSONL
        # event stream under <checkpoint_dir>/flight/ fed by the
        # guardrail/chaos listeners registered here, and continuous
        # bench-comparable telemetry committed with every checkpoint.
        # Host-side only; never raises into the loop.
        self.obs = build_observer(
            train,
            checkpoint_dir=train.checkpoint_dir,
            is_writer=mh.is_main(),
            watchdog=self.watchdog,
            guardrails=self.guardrails,
            chaos=self.chaos,
        )
        # the last cycle's async metrics must survive shutdown in any
        # order: tracker.close() drains these before backend teardown
        self.tracker.attach_pending(self._finish_rollout_stats)
        self.tracker.attach_pending(
            lambda: self._finish_train_stats(suppress_abort=True)
        )
        self._resilient_cfg = ResilientIOConfig.from_dict(train.resilient_io)
        self._reward_caller: Optional[ResilientCaller] = None  # lazy
        self._lr_scale = 1.0  # cumulative guardrail LR-cut factor
        self._ckpt_commit_failures = 0  # consecutive failed commits
        # run-derived step budget of a restored checkpoint (PPO lowers
        # total_steps from the store size inside prepare_learning, so
        # the config value alone can't tell a completed run from one
        # with steps left)
        self._restored_total_steps: Optional[int] = None
        self._restored_config_total_steps: Optional[int] = None

        mb_size = train.minibatch_size or train.batch_size
        if train.batch_size % mb_size:
            raise ValueError("batch_size must be divisible by minibatch_size")
        self.mb_size = mb_size
        self.num_mb = train.batch_size // mb_size
        data_ways = self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
        if mb_size % data_ways:
            raise ValueError(
                f"minibatch_size {mb_size} must be divisible by dp*fsdp={data_ways} "
                f"(mesh {dict(self.mesh.shape)})"
            )

        self._train_step = None  # built lazily (jitted)
        self._fused_train_step = None  # built lazily (jitted inner loop)
        self._warned_fused_cadence = False
        # fused-block metrics ride an async device->host copy and are
        # consumed one cycle later (train.async_metrics)
        self._deferred_train = DeferredStats()
        self._last_cycle_t0: Optional[float] = None  # guardrail wall signal
        self._measured_forward_times = {}  # timing_split probes by batch shape
        self._seen_step_shapes = set()  # batch shapes whose step has compiled
        self._generate_fns: Dict[Tuple, Callable] = {}
        # serving-grade rollout decode engine (ppo.gen_engine.*):
        # continuous batching + paged KV + speculative decoding behind
        # the same generate() seam; default-disabled
        from trlx_tpu.models.gen_engine import GenEngineConfig

        self._engine_cfg = GenEngineConfig.from_dict(
            getattr(config.method, "gen_engine", None)
        )
        self._engine_fns: Dict[Tuple, Callable] = {}
        self._warned_engine_fallback = False
        # live-traffic serving tier (train.serve.*): external requests
        # admitted into the same continuous-batching engine on the live
        # policy params, ticked at the lane-refill decision points.
        # Default off; built lazily at learn() start (_serve_start)
        from trlx_tpu.serve.config import ServeConfig

        self._serve_cfg = ServeConfig.from_dict(
            getattr(config.train, "serve", None)
        )
        self.serve = None  # ServeFrontend while learn() runs
        self._serve_fn = None  # jitted serving engine entry
        # cross-host consistency watchdog (guardrails.consistency_every)
        self._fingerprint_fn = None  # jitted replicated state reduction
        self._consistency_counter = 0
        # policy version: optimizer CYCLES applied to the params (one
        # fused block, or one inner epoch of the per-step loop). This is
        # the experience transport's staleness unit — every chunk
        # records the version its samples were generated at, and the
        # admission gate compares it against the version at consumption
        # (the overlap_rollouts prefetch is exactly 1 stale by
        # construction).
        self._policy_version = 0

    # ------------------------------------------------------------------
    # model setup
    # ------------------------------------------------------------------

    def load_base_model(self) -> Tuple[TransformerConfig, Dict, Optional[str]]:
        """Resolve ModelConfig -> (transformer config, base params, model_type).

        `model_path="random"` (or a "transformer" dict in
        model_extra_configs) random-initializes — the zero-egress path
        used by tests and benchmarks; otherwise an HF-layout checkpoint
        directory is loaded (parity: reference modeling_base.py:124-326).
        """
        mc = self.config.model
        extra = mc.model_extra_configs or {}
        if mc.model_arch_type == "seq2seq":
            return self._load_seq2seq_base(mc, extra)

        def finalize(tcfg):
            # runtime knobs from model_extra_configs apply to EVERY load
            # path (mesh presets ship e.g. kv_cache_quant — they must
            # tune a loaded checkpoint, not reroute it to random init)
            tcfg = _apply_runtime_overrides(tcfg, extra.get("transformer", {}))
            # mesh sp>1 means the user asked for context parallelism: switch
            # the default attention to the ring implementation (an explicit
            # attention_impl, e.g. "pallas", is respected as-is)
            if self.mesh.shape["sp"] > 1 and tcfg.attention_impl == "xla":
                tcfg = tcfg.replace(attention_impl="ring")
            # a tokenizer id >= vocab_size would silently fill the embedding
            # gather with NaN under XLA (jnp.take fill mode) — fail loudly
            for name in ("pad_token_id", "eos_token_id", "bos_token_id"):
                tid = getattr(self.tokenizer, name, None)
                if tid is not None and int(tid) >= tcfg.vocab_size:
                    raise ValueError(
                        f"tokenizer {name}={tid} is out of range for model "
                        f"vocab_size={tcfg.vocab_size}; align the model's "
                        "vocab_size with the tokenizer (the byte tokenizer "
                        "needs vocab_size>=258)"
                    )
            return tcfg

        native_cfg_fp = os.path.join(mc.model_path, "trlx_tpu_config.json")
        if os.path.isdir(mc.model_path) and os.path.exists(native_cfg_fp):
            # native checkpoint (orbax params + architecture json), the
            # deploy artifact save_pretrained writes for random-init runs
            import orbax.checkpoint as ocp

            with open(native_cfg_fp) as f:
                meta = json.load(f)
            tcfg = TransformerConfig(
                dtype=self.compute_dtype, param_dtype=self.param_dtype,
                **meta["transformer"],
            )
            params = ocp.PyTreeCheckpointer().restore(
                os.path.join(os.path.abspath(mc.model_path), "params")
            )
            aux_dir = os.path.join(os.path.abspath(mc.model_path), "aux")
            if os.path.isdir(aux_dir):
                self._loaded_aux = ocp.PyTreeCheckpointer().restore(aux_dir)
            return finalize(tcfg), params, meta.get("model_type")
        # random-init only when asked by path or by ARCHITECTURE keys —
        # a preset carrying only runtime knobs (kv_cache_quant, ...)
        # must not silently replace a pretrained model with random init
        arch_keys = set(extra.get("transformer", {})) - _RUNTIME_TRANSFORMER_KEYS
        if mc.model_path == "random" or arch_keys:
            tdict = dict(extra.get("transformer", {}))
            tdict.setdefault("vocab_size", getattr(self.tokenizer, "vocab_size", 258))
            tcfg = TransformerConfig(
                dtype=self.compute_dtype, param_dtype=self.param_dtype, **tdict
            )
            self.rng, key = jax.random.split(self.rng)
            params = TransformerLM(tcfg).init(key)
            return finalize(tcfg), params, extra.get("model_type")
        lm, params, model_type = load_pretrained(
            mc.model_path, dtype=self.compute_dtype, param_dtype=self.param_dtype
        )
        self._hf_config_path = mc.model_path
        return finalize(lm.cfg), params, model_type

    def _load_seq2seq_base(self, mc, extra):
        from trlx_tpu.models.seq2seq import Seq2SeqConfig, T5LM

        native_cfg_fp = os.path.join(mc.model_path, "trlx_tpu_config.json")
        if os.path.isdir(mc.model_path) and os.path.exists(native_cfg_fp):
            import orbax.checkpoint as ocp

            with open(native_cfg_fp) as f:
                meta = json.load(f)
            scfg = Seq2SeqConfig(
                dtype=self.compute_dtype, param_dtype=self.param_dtype,
                **meta["seq2seq"],
            )
            params = ocp.PyTreeCheckpointer().restore(
                os.path.join(os.path.abspath(mc.model_path), "params")
            )
            aux_dir = os.path.join(os.path.abspath(mc.model_path), "aux")
            if os.path.isdir(aux_dir):
                self._loaded_aux = ocp.PyTreeCheckpointer().restore(aux_dir)
            scfg = _apply_runtime_overrides(scfg, extra.get("seq2seq", {}))
            return scfg, params, meta.get("model_type", "t5")
        # same contract as the causal loader: runtime-only keys don't
        # reroute a pretrained model to random init
        if mc.model_path == "random" or (
            set(extra.get("seq2seq", {})) - _RUNTIME_TRANSFORMER_KEYS
        ):
            sdict = dict(extra.get("seq2seq", {}))
            sdict.setdefault("vocab_size", getattr(self.tokenizer, "vocab_size", 258))
            pad = getattr(self.tokenizer, "pad_token_id", None)
            if pad is not None:
                sdict.setdefault("decoder_start_token_id", int(pad))
            scfg = Seq2SeqConfig(
                dtype=self.compute_dtype, param_dtype=self.param_dtype, **sdict
            )
            self.rng, key = jax.random.split(self.rng)
            return scfg, T5LM(scfg).init(key), extra.get("model_type", "t5")
        from trlx_tpu.models.hf import load_pretrained_seq2seq

        lm, params, model_type = load_pretrained_seq2seq(
            mc.model_path, dtype=self.compute_dtype, param_dtype=self.param_dtype
        )
        self._hf_config_path = mc.model_path
        scfg = _apply_runtime_overrides(lm.cfg, extra.get("seq2seq", {}))
        return scfg, params, model_type

    @abstractmethod
    def setup_model(self) -> None:
        """Set self.model / self.params (sharded) and auxiliaries."""

    def trainable_mask(self):
        """Pytree of {0,1} update multipliers (None = all trainable).

        Freezing must mask the *updates*, not the grads: AdamW applies
        weight decay even at zero gradient (parity with
        `freeze_bottom_causal_layers`, reference
        accelerate_base_trainer.py:159-161 + utils/modeling.py:106-140).
        """
        return None

    def branch_at(self) -> Optional[int]:
        """Layer index where the trainable top starts (None = all)."""
        k = self.config.model.num_layers_unfrozen
        if k is None or k < 0:
            return None
        n_layer = self.model.cfg.n_layer
        return max(n_layer - k, 0)

    def make_freeze_mask(self, params: Dict) -> Optional[Dict]:
        """Standard causal-LM freeze mask: embeddings + bottom layers
        frozen, top-k layers + final norm + lm_head + aux heads train."""
        at = self.branch_at()
        if at is None or at == 0:
            return None
        n_layer = self.model.cfg.n_layer
        layer_mask = (jnp.arange(n_layer) >= at).astype(jnp.float32)

        def mask_leaf(path, leaf):
            keys = [getattr(p, "key", getattr(p, "idx", None)) for p in path]
            if "v_branch" in keys or "lora" in keys:
                return np.float32(1.0)  # branches/adapters always train
            if "blocks" in keys:
                return layer_mask.reshape((n_layer,) + (1,) * (np.ndim(leaf) - 1))
            if "embed" in keys:
                return np.float32(0.0)
            return np.float32(1.0)

        return jax.tree_util.tree_map_with_path(mask_leaf, params)

    def attach_lora(self, params: Dict) -> Dict:
        """Back-compat alias for attach_peft."""
        return self.attach_peft(params)

    def attach_peft(self, params: Dict) -> Dict:
        """Add the configured adapter (LoRA overlay / prompt soft tokens /
        per-layer kv prefixes) to a {"base": ...} params tree.

        `ModelConfig.peft_config` is either an HF-peft-style config dict
        (fresh adapter) or a PATH to a trained HF-peft adapter checkpoint
        (adapter_config.json + adapter_model.safetensors) — both shapes
        the reference accepts (ref modeling_base.py:124-326)."""
        from trlx_tpu.models.peft import (
            init_lora_params,
            init_prefix_params,
            init_prompt_params,
            is_peft_checkpoint,
            load_peft_adapter,
            normalize_peft_config,
        )

        if isinstance(self.config.model.peft_config, str) and not (
            is_peft_checkpoint(self.config.model.peft_config)
        ):
            raise ValueError(
                f"peft_config {self.config.model.peft_config!r} is a "
                "string but not an adapter checkpoint directory (no "
                "adapter_config.json inside); pass either a trained "
                "HF-peft adapter dir or a config dict like "
                '{"peft_type": "LORA", "r": 8}'
            )
        if is_peft_checkpoint(self.config.model.peft_config):
            pc, adapter = load_peft_adapter(
                self.config.model.peft_config, self.model.cfg
            )
            params.update(adapter)
            if pc["peft_type"] == "LORA":
                self.model.lora_scaling = pc["alpha"] / pc["r"]
            self._peft_cfg = pc
            return params
        pc = normalize_peft_config(self.config.model.peft_config)
        self._peft_cfg = pc
        if pc is None:
            return params
        self.rng, key = jax.random.split(self.rng)
        if pc["peft_type"] == "LORA":
            params["lora"] = init_lora_params(
                key, params["base"], pc["r"], pc["targets"]
            )
            self.model.lora_scaling = pc["alpha"] / pc["r"]
        elif pc["peft_type"] == "PROMPT_TUNING":
            params["prompt"] = init_prompt_params(
                key, self.model.cfg, pc["num_virtual_tokens"]
            )
        elif pc["peft_type"] == "PREFIX_TUNING":
            params["prefix"] = init_prefix_params(
                key, self.model.cfg, pc["num_virtual_tokens"]
            )
        return params

    def lora_freeze_mask(self, params: Dict) -> Optional[Dict]:
        """With any peft adapter: base frozen entirely, adapters + heads
        train (the reference peft contract)."""
        from trlx_tpu.models.peft import ADAPTER_KEYS

        if not any(k in params for k in ADAPTER_KEYS):
            return None
        mask = jax.tree_util.tree_map(lambda _: np.float32(1.0), params)
        mask["base"] = jax.tree_util.tree_map(
            lambda _: np.float32(0.0), params["base"]
        )
        return mask

    def make_seq2seq_freeze_mask(self, params: Dict) -> Optional[Dict]:
        """Seq2seq freeze: encoder + shared embedding + decoder rel-bias +
        bottom decoder layers frozen; top decoder layers, final norm,
        lm_head and aux heads train (parity: reference
        freeze_bottom_seq2seq_layers, utils/modeling.py)."""
        k = self.config.model.num_layers_unfrozen
        if k is None or k < 0:
            return None
        n_dec = self.model.cfg.n_decoder_layer
        at = max(n_dec - k, 0)
        if at == 0:
            return None
        layer_mask = (jnp.arange(n_dec) >= at).astype(jnp.float32)

        def mask_leaf(path, leaf):
            keys = [getattr(p, "key", getattr(p, "idx", None)) for p in path]
            if "encoder" in keys or "shared" in keys:
                return np.float32(0.0)
            if "rel_bias" in keys:
                return np.float32(0.0)
            if "blocks" in keys:
                return layer_mask.reshape((n_dec,) + (1,) * (np.ndim(leaf) - 1))
            return np.float32(1.0)

        return jax.tree_util.tree_map_with_path(mask_leaf, params)

    # ------------------------------------------------------------------
    # data placement
    # ------------------------------------------------------------------

    def place_batch(self, batch):
        """Host batch -> device arrays sharded batch-dim over (dp, fsdp),
        and — when the mesh has an `sp` axis — seq-dim over sp for every
        rank>=2 leaf whose dim 1 divides evenly (context parallelism).
        Rank-1 leaves (per-row scalars, e.g. GRPO's sequence-level
        advantages) shard their single dim over (dp, fsdp)."""
        sp = self.mesh.shape["sp"]
        base = data_sharding(self.mesh)
        vec = vector_sharding(self.mesh)
        seq = data_sharding(self.mesh, shard_seq=True) if sp > 1 else base

        def put(x):
            # device-resident leaves (the on-device rollout store) reshard
            # device-to-device; only host leaves pay the upload
            if not isinstance(x, jax.Array):
                x = np.asarray(x)
            if x.ndim < 2:
                return jax.device_put(x, vec)
            s = seq if (sp > 1 and x.ndim >= 2 and x.shape[1] % sp == 0) else base
            return jax.device_put(x, s)

        return jax.tree_util.tree_map(put, batch)

    def data_ways(self) -> int:
        return self.mesh.shape["dp"] * self.mesh.shape["fsdp"]

    def local_ways(self) -> int:
        """Row-divisibility requirement for THIS process's block of a
        global batch (multi-host: each DATA GROUP contributes 1/G of the
        rows; pp stages within a group replicate them; mesh layout keeps
        a group's rows on its hosts' devices)."""
        ways, gc = self.data_ways(), mh.data_group_count(self.mesh)
        if ways % gc:
            raise ValueError(
                f"dp*fsdp={ways} must be divisible by the data-group "
                f"count {gc} (each host must own whole data shards)"
            )
        return ways // gc

    @staticmethod
    def pad_rows(arr: np.ndarray, target_rows: int) -> np.ndarray:
        """Pad the leading dim to `target_rows` by repeating the last row."""
        n = target_rows - len(arr)
        if n <= 0:
            return arr
        return np.concatenate([arr, np.repeat(arr[-1:], n, axis=0)])

    # ------------------------------------------------------------------
    # generation
    # ------------------------------------------------------------------

    def _lm(self) -> TransformerLM:
        return self.model.lm

    def _get_generate_fn(
        self,
        settings: SamplerSettings,
        shape: Tuple[int, int],
        proc_kwargs: Tuple = (),
    ):
        key = (settings, shape, proc_kwargs)
        if key not in self._generate_fns:
            lm = self._lm()
            make_processor = self.generation_logits_processor
            seq2seq = self.config.model.model_arch_type == "seq2seq"

            model = self.model

            def fn(params, input_ids, attention_mask, rng):
                from trlx_tpu.models.wrappers import _effective_base

                # the processor is built from the LIVE param tree at trace
                # time (ILQL shapes logits with its current Q/V heads);
                # _effective_base merges any LoRA overlay so sampling uses
                # the ADAPTED policy, not the frozen base
                base = _effective_base(model, params)
                if seq2seq:
                    from trlx_tpu.models.seq2seq import generate_seq2seq

                    return generate_seq2seq(
                        lm, base, input_ids, attention_mask, rng,
                        settings,
                        logits_processor=make_processor(
                            params, **dict(proc_kwargs)
                        ),
                    )
                return generate(
                    lm, base, input_ids, attention_mask, rng, settings,
                    logits_processor=make_processor(params, **dict(proc_kwargs)),
                    soft_prompt=(
                        params["prompt"]["embedding"] if "prompt" in params else None
                    ),
                    kv_prefix=params.get("prefix"),
                )

            self._generate_fns[key] = jax.jit(fn)
        return self._generate_fns[key]

    def generation_logits_processor(self, params):
        """Optional logits hook for sampling, given the full param tree.

        Swept gen_kwargs that aren't `SamplerSettings` fields (e.g.
        ILQL's `beta`) arrive here as keyword arguments, so subclasses
        declare the ones they consume; `generate()` rejects names no
        processor parameter matches (the reference delegates the same
        validation to HF `generate`'s kwarg checking)."""
        return None

    def generate(self, input_ids, attention_mask=None, settings=None, **kwargs):
        """Sample continuations for experience collection (parity:
        reference generate/generate_eval :256-288)."""
        settings = settings or self.generate_experience_settings
        # kwargs the sampler doesn't implement belong to the logits
        # processor (the reference hands them to the model's custom
        # generate the same way, e.g. ILQL beta — ref modeling_ilql.py
        # generate(beta=...)); they key the compiled-fn cache because the
        # processor bakes them into the traced computation. Names neither
        # side declares are an error, not a silent drop (HF generate
        # validates its kwargs the same way).
        import inspect

        sampler_fields = {f.name for f in dataclasses.fields(SamplerSettings)}
        proc_fields = {
            name
            for name, p in inspect.signature(
                self.generation_logits_processor
            ).parameters.items()
            if name != "params" and p.kind is not inspect.Parameter.VAR_KEYWORD
        }
        unknown = set(kwargs) - sampler_fields - proc_fields
        # names HF generate knows but this sampler doesn't implement get
        # the same treatment per-call as at config load (the SAME set
        # SamplerSettings.from_gen_kwargs warns on): warn and drop — a
        # config sweeping e.g. num_beams must not load fine then crash
        # evaluate()
        hf_unimplemented = unknown & HF_GEN_KWARGS_UNIMPLEMENTED
        if hf_unimplemented:
            logger.warning(
                "generate(): ignoring HF gen_kwargs this sampler does "
                f"not implement: {sorted(hf_unimplemented)}"
            )
            unknown -= hf_unimplemented
            kwargs = {k: v for k, v in kwargs.items() if k not in hf_unimplemented}
        if unknown:
            raise TypeError(
                f"generate() got kwargs {sorted(unknown)} that neither "
                f"SamplerSettings nor {type(self).__name__}."
                "generation_logits_processor accepts"
            )
        for k, v in kwargs.items():
            # processor kwargs key the compiled-fn cache and are baked
            # into the trace: they must be hashable scalars, one value
            # per call (a swept list like beta=[0,1,100] is the config's
            # sweep axis — callers pass each value separately)
            if k in proc_fields and not (v is None or np.isscalar(v)):
                raise TypeError(
                    f"generate() kwarg {k}={v!r} must be a scalar "
                    "(int/float/bool/str); swept values are passed one "
                    "per call, not as a list"
                )
        proc_kwargs = tuple(
            sorted((k, v) for k, v in kwargs.items() if k in proc_fields)
        )
        kwargs = {k: v for k, v in kwargs.items() if k in sampler_fields}
        if kwargs:
            settings = SamplerSettings.from_gen_kwargs(
                {**settings.__dict__, **kwargs}
            )
        input_ids = np.asarray(input_ids, np.int32)
        if attention_mask is None:
            attention_mask = np.ones_like(input_ids)
        attention_mask = np.asarray(attention_mask, np.int32)

        if self._engine_cfg.enabled and not proc_kwargs:
            if self._engine_eligible():
                return self._engine_generate(input_ids, attention_mask, settings)
            if not self._warned_engine_fallback:
                self._warned_engine_fallback = True
                logger.warning(
                    "ppo.gen_engine.enabled but this run is outside the "
                    "engine's v1 envelope (causal LM, single data group, "
                    "no soft-prompt/prefix adapters): falling back to the "
                    "static sampler"
                )

        # pad the batch rows for sharding divisibility AND up to the widest
        # row count this sampler has already compiled for — a ragged final
        # eval batch then reuses the cached executable instead of
        # recompiling the whole decode loop
        B, P = input_ids.shape
        pc = mh.data_group_count(self.mesh)
        target = B + (-B) % self.local_ways()
        # cache keys hold GLOBAL row counts; compare in local terms
        compiled = [
            shape[0] // pc
            for (s, shape, pk) in self._generate_fns
            if s == settings and pk == proc_kwargs
            and shape[1] == P and shape[0] // pc >= target
        ]
        if compiled:
            target = min(compiled)
        if target != B:
            input_ids = self.pad_rows(input_ids, target)
            attention_mask = self.pad_rows(attention_mask, target)
        with self.mesh:
            # generate fns trace over GLOBAL row counts: shape keys are
            # the global batch shape
            gshape = (input_ids.shape[0] * pc, input_ids.shape[1])
            fn = self._get_generate_fn(settings, gshape, proc_kwargs)
            self.rng, key = jax.random.split(self.rng)
            sharding = data_sharding(self.mesh)
            device_mask = mh.global_from_local(attention_mask, sharding)
            out = fn(
                self.params,
                mh.global_from_local(input_ids, sharding),
                device_mask,
                key,
            )
            # ride the prompt mask along as a DEVICE array: the PPO
            # experience forward consumes it (+ sequences/response_mask)
            # straight from here, skipping a host round-trip per chunk
            out = dict(out, prompt_mask=device_mask)
        if target != B:
            if mh.is_multihost():
                # each data group's pad rows sit at the END of its own
                # block INSIDE the global batch (every group padded the
                # same B -> target, shard_list keeps groups equal-sized),
                # so a flat [:B] can't drop them — consumers trim their
                # own group's rows via `real_rows` after mh.local_rows
                # (parity: the reference pads across processes and trims
                # after gather, accelerate_ppo_trainer.py:292-300)
                out = dict(out, real_rows=B)
            else:
                out = jax.tree_util.tree_map(lambda x: x[:B], out)
        return out

    def generate_eval(self, input_ids, attention_mask=None, **kwargs):
        return self.generate(
            input_ids, attention_mask, settings=self.generate_settings, **kwargs
        )

    # ------------------------------------------------------------------
    # rollout decode engine (ppo.gen_engine.*)
    # ------------------------------------------------------------------

    def _engine_eligible(self) -> bool:
        """v1 envelope of the decode engine: causal LM, one data group
        (the rollout-worker geometry), plain sampling (no per-call
        logits processor, no soft-prompt/prefix adapters). LoRA is fine:
        the engine samples the merged effective base like the static
        sampler does."""
        if self.config.model.model_arch_type == "seq2seq":
            return False
        if mh.is_multihost() or mh.data_group_count(self.mesh) != 1:
            return False
        if self.generation_logits_processor(self.params) is not None:
            return False
        if "prompt" in self.params or "prefix" in self.params:
            return False
        return True

    def _engine_spec(self, batch: int):
        """Resolve the decode-engine spec for a call's batch width,
        with the memory doctor's pool degradation applied: each
        shrink_pool rung scales slots (and any explicit pool_pages)
        by ``train.memory.pool_shrink_factor`` — fewer lanes, smaller
        pool, same output contract (the queue just drains in more
        refill waves). Speculative decoding derives the draft's shared
        trunk depth here (hydra reference: its branch is the top-k
        layers, so the composed draft shares the other L-k with the
        policy — stored ONCE in the extended pool), which keeps the
        spec the jit traces and the bytes the memory doctor plans in
        agreement by construction."""
        spec = self._engine_cfg.resolve(batch, self._lm().cfg)
        if spec.spec_decode:
            from trlx_tpu.models.gen_engine import hydra_shared_trunk_layers

            L = self._lm().cfg.n_layer
            ref = getattr(self, "ref_params", None)
            if ref is not None and "blocks" in ref:
                kb = jax.tree_util.tree_leaves(ref["blocks"])[0].shape[0]
            else:
                kb = getattr(self.config.model, "num_layers_unfrozen", -1)
            sh = hydra_shared_trunk_layers(L, kb)
            if sh:
                spec = dataclasses.replace(spec, draft_shared_layers=sh)
        scale = self.memdoctor.pool_scale() if self.memdoctor.enabled else 1.0
        if scale < 1.0:
            spec = dataclasses.replace(
                spec,
                slots=max(1, int(spec.slots * scale)),
                pool_pages=(
                    max(1, int(spec.pool_pages * scale))
                    if spec.pool_pages else 0
                ),
            )
        return spec

    def _decode_impl(self) -> str:
        """Provenance string for the flight recorder: which decode
        implementation produces this run's rollout tokens (so a
        recorded telemetry.json says which kernel its tok/s headline
        came from)."""
        if not self._engine_cfg.enabled:
            return "static"
        if not self._engine_cfg.paged:
            impl = "engine-contiguous"
        else:
            impl = f"engine-paged-{self._engine_cfg.paged_attention_impl}"
        if self._engine_cfg.data_groups > 1:
            impl += f"-x{self._engine_cfg.data_groups}"
        return impl

    def _engine_group_sharding(self, groups: int):
        """NamedSharding that places each engine lane group's state on
        its own slice of the mesh's data axes (None when the geometry
        doesn't divide — the groups then run as one replicated stacked
        dispatch, which is still correct, just not multi-chip)."""
        from jax.sharding import NamedSharding, PartitionSpec

        for axes in (("dp", "fsdp"), ("dp",)):
            size = 1
            for ax in axes:
                size *= self.mesh.shape.get(ax, 1)
            if size > 1 and groups % size == 0:
                return NamedSharding(self.mesh, PartitionSpec(axes))
        return None

    def _get_engine_fn(self, settings: SamplerSettings, shape: Tuple[int, int]):
        from trlx_tpu.models.gen_engine import (
            compose_draft_params,
            engine_generate_grouped,
        )

        spec = self._engine_spec(shape[0])
        key = (settings, shape, spec)
        if key not in self._engine_fns:
            lm = self._lm()
            model = self.model
            gshard = (
                self._engine_group_sharding(spec.data_groups)
                if spec.data_groups > 1 else None
            )

            if spec.spec_decode:

                def fn(params, ref_params, input_ids, attention_mask, rng):
                    from trlx_tpu.models.wrappers import _effective_base

                    base = _effective_base(model, params)
                    draft = compose_draft_params(lm.cfg, base, ref_params)
                    return engine_generate_grouped(
                        lm, base, input_ids, attention_mask, rng, settings,
                        spec, draft_params=draft, group_sharding=gshard,
                    )

            else:

                def fn(params, input_ids, attention_mask, rng):
                    from trlx_tpu.models.wrappers import _effective_base

                    return engine_generate_grouped(
                        lm, _effective_base(model, params), input_ids,
                        attention_mask, rng, settings, spec,
                        group_sharding=gshard,
                    )

            self._engine_fns[key] = jax.jit(fn)
        return self._engine_fns[key], spec

    def _engine_generate(self, input_ids, attention_mask, settings):
        """Run one generate() chunk through the decode engine. The whole
        chunk is the engine's device-resident prompt queue: finished
        slots refill from it, so the step batch stays dense while the
        chunk drains. Output contract matches the static sampler, plus
        `gen_stats` (refills / real tokens / occupancy / truncation)."""
        from trlx_tpu.parallel.mesh import replicated_sharding

        B, P = input_ids.shape
        with self.mesh:
            fn, spec = self._get_engine_fn(settings, (B, P))
            self.rng, key = jax.random.split(self.rng)
            # the engine's control flow (slot refills, page allocation)
            # runs replicated; the single-replica rollout geometry is
            # the v1 target (ROADMAP item 1's inference workers)
            sharding = replicated_sharding(self.mesh)
            dev_ids = jax.device_put(input_ids, sharding)
            dev_mask = jax.device_put(attention_mask, sharding)
            if spec.spec_decode:
                ref = getattr(self, "ref_params", None)
                if ref is None:
                    raise ValueError(
                        "ppo.gen_engine.spec_decode needs a frozen "
                        "reference model (PPO) to draft from"
                    )
                out = fn(self.params, ref, dev_ids, dev_mask, key)
            else:
                out = fn(self.params, dev_ids, dev_mask, key)
            out = dict(out, prompt_mask=dev_mask)
        return out

    # ------------------------------------------------------------------
    # live-traffic serving tier (train.serve.*)
    # ------------------------------------------------------------------

    def _serve_spec(self):
        """The serving engine geometry: a FIXED spec (one compiled
        executable for the whole run) over a persistent warm pool,
        resolved like the rollout engine's but against the serve
        config's row budget instead of a chunk width."""
        import dataclasses as _dc

        from trlx_tpu.models.gen_engine import EngineSpec
        from trlx_tpu.ops import paged_kv

        cfg = self._serve_cfg
        lm_cfg = self._lm().cfg
        quant = cfg.kv_quant
        if quant is None:
            quant = "int8" if lm_cfg.kv_cache_quant in (
                "int8", "int8_kernel"
            ) else "none"
        slots = min(cfg.slots or cfg.max_batch, cfg.max_batch)
        MP = paged_kv.pages_per_slot(
            cfg.max_prompt_len, cfg.max_new_tokens, cfg.page_size
        )
        return EngineSpec(
            slots=slots,
            page_size=cfg.page_size,
            paged=True,
            pool_pages=cfg.pool_pages or (1 + slots * MP),
            refill_width=0,
            spec_decode=False,
            kv_quant=None if quant == "none" else quant,
            # serve decode rides the SAME kernel selection as rollout
            # decode: one knob (method.gen_engine.paged_attention_impl)
            # decides which attend implementation every engine call —
            # training or serving — runs on (docs/serving.md)
            paged_attention_impl=self._engine_cfg.paged_attention_impl,
        )

    def _serve_start(self) -> None:
        """Build the serving frontend at learn() start (train.serve.*).
        Serving shares the engine machinery and the LIVE policy params
        but owns its rng, pool and executables — the training stream is
        untouched by construction."""
        if not self._serve_cfg.enabled or self.serve is not None:
            return
        if not self._engine_eligible():
            raise ValueError(
                "train.serve.enabled requires the decode engine's v1 "
                "envelope: causal LM, single data group, no "
                "soft-prompt/prefix adapters"
            )
        from trlx_tpu.models.gen_engine import engine_generate
        from trlx_tpu.models.generation import SamplerSettings
        from trlx_tpu.parallel.mesh import replicated_sharding
        from trlx_tpu.serve.frontend import ServeFrontend

        spec = self._serve_spec()
        settings = SamplerSettings.from_gen_kwargs(
            {
                **self.generate_settings.__dict__,
                "max_new_tokens": self._serve_cfg.max_new_tokens,
            }
        )
        lm = self._lm()
        model = self.model

        groups = self._serve_cfg.groups
        if groups > 1:
            # sharded serve lanes: G independent warm pools/ledgers
            # (trlx_tpu/serve/frontend.py owns the grouping), served by
            # ONE stacked vmap dispatch whose group axis shards over
            # the mesh's data axes when the geometry divides — the
            # serve frontend itself becomes multi-chip. Request streams
            # are per-request-id RNG, so tokens are invariant to the
            # group count by construction.
            def fn(params, q_ids, q_mask, rng, row_budget, warm, q_pin,
                   q_ready, q_rng_row):
                from trlx_tpu.models.wrappers import _effective_base

                base = _effective_base(model, params)

                def one_group(ids, mask, budget, w, pin, ready, rngrow):
                    return engine_generate(
                        lm, base, ids, mask, rng, settings, spec,
                        row_budget=budget, warm=w, q_pin=pin,
                        q_ready=ready, q_rng_row=rngrow,
                    )

                return jax.vmap(one_group)(
                    q_ids, q_mask, row_budget, warm, q_pin, q_ready,
                    q_rng_row,
                )

        else:

            def fn(params, q_ids, q_mask, rng, row_budget, warm, q_pin,
                   q_ready, q_rng_row):
                from trlx_tpu.models.wrappers import _effective_base

                return engine_generate(
                    lm, _effective_base(model, params), q_ids, q_mask, rng,
                    settings, spec, row_budget=row_budget, warm=warm,
                    q_pin=q_pin, q_ready=q_ready, q_rng_row=q_rng_row,
                )

        jfn = jax.jit(fn)
        gshard = (
            self._engine_group_sharding(groups) if groups > 1 else None
        )

        def runner(q_ids, q_mask, rng, row_budget, warm, q_pin, q_ready,
                   q_rng_row):
            with self.mesh:
                sharding = gshard or replicated_sharding(self.mesh)
                return jfn(
                    self.params,
                    jax.device_put(q_ids, sharding),
                    jax.device_put(q_mask, sharding),
                    rng, row_budget, warm, q_pin, q_ready, q_rng_row,
                )

        lm_cfg = lm.cfg
        geom = {
            "P": self._serve_cfg.max_prompt_len,
            "N": self._serve_cfg.max_new_tokens,
            "page_size": spec.page_size,
            "pool_pages": spec.pool_pages,
            "pad_token_id": settings.pad_token_id,
            "n_layer": lm_cfg.n_layer,
            "n_kv_head": lm_cfg.n_kv_head,
            "head_dim": lm_cfg.head_dim,
            "kv_quant": spec.kv_quant,
            "dtype": lm_cfg.dtype,
            "groups": groups,
        }
        self.serve = ServeFrontend(
            self._serve_cfg, runner, geom,
            self.config.train.checkpoint_dir,
            chaos=self.chaos, obs=self.obs,
        )
        self._serve_final_summary = None

    def _serve_tick(self, iter_count: int) -> None:
        """One lane-refill decision point: pending serve requests run
        BEFORE the next training dispatch (serving outranks training
        refills; the allowance is bounded by
        serve.max_batches_per_tick, so training backfills right after
        — reported when starved, never wedged). A serving failure must
        never take the training loop down: it logs loudly and the next
        tick retries."""
        if self.serve is None:
            return
        with self.watchdog.phase("serve", step=iter_count):
            try:
                self.serve.tick(iter_count)
            except Exception:
                logger.exception(
                    "serve tick failed — serving degrades this tick, "
                    "training continues"
                )

    def _serve_close(self) -> None:
        if self.serve is None:
            return
        try:
            # close() FIRST: the final summary must include the
            # shutdown cancellations and result flush it performs
            self.serve.close()
            summary = self.serve.stats_summary()
            self._serve_final_summary = summary
            self.obs.record("serve_summary", **{
                k: v for k, v in summary.items()
                if isinstance(v, (int, float))
            })
        finally:
            self.serve = None

    # ------------------------------------------------------------------
    # decode
    # ------------------------------------------------------------------

    def decode(
        self,
        prompts,
        samples,
        prompt_sizes=None,
        append_eos_token: bool = False,
    ) -> Tuple[List[str], List[str], List[str]]:
        """Token arrays -> (str_samples, str_prompts, str_outputs), with
        stop-sequence trimming and EOS recovery (parity: reference
        accelerate_base_trainer.py:203-255)."""
        if prompt_sizes is None:
            prompt_sizes = [np.shape(p)[-1] for p in prompts]

        str_samples, str_prompts, str_outputs = [], [], []
        eos_id = getattr(self.tokenizer, "eos_token_id", None)
        pad_id = getattr(self.tokenizer, "pad_token_id", None)
        eos_token = getattr(self.tokenizer, "eos_token", "") or ""
        for prompt, sample, prompt_size in zip(prompts, samples, prompt_sizes):
            prompt, sample = np.asarray(prompt), np.asarray(sample)
            output_start = 0 if self.config.model.model_arch_type == "seq2seq" else int(prompt_size)
            str_prompt = self.tokenizer.decode(
                prompt[: int(prompt_size)], skip_special_tokens=True
            )
            str_output = self.tokenizer.decode(
                sample[output_start:], skip_special_tokens=True
            )
            trimmed = False
            for stop in self.stop_sequences:
                stop_ix = str_output.find(stop)
                if stop_ix >= 0:
                    str_output = str_output[:stop_ix].rstrip()
                    trimmed = True
            if append_eos_token and (
                trimmed or sample[-1] == eos_id or sample[-1] == pad_id
            ):
                str_output += eos_token
            str_prompts.append(str_prompt)
            str_outputs.append(str_output)
            if self.config.model.model_arch_type == "seq2seq":
                sep = getattr(self.tokenizer, "sep_token", "") or ""
                str_samples.append(str_prompt + sep + str_output)
            else:
                str_samples.append(str_prompt + str_output)
        return str_samples, str_prompts, str_outputs

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------

    def evaluate(self) -> Dict[str, Any]:
        """Sample eval prompts; score with reward_fn/metric_fn (parity:
        reference evaluate :339-505, incl. gen-kwarg sweeping)."""
        with self.watchdog.phase("eval", step=self.iter_count):
            return self._evaluate()

    def _evaluate(self) -> Dict[str, Any]:
        logger.info("Evaluating model")
        import time as _time

        if self.generate_sweep_kwarg is not None:
            sweep_arg, sweep_values = self.generate_sweep_kwarg
        else:
            sweep_arg, sweep_values = None, [None]

        stats: Dict[str, Any] = {}
        table_rows = []
        for sweep_value in sweep_values:
            suffix = f"@{sweep_arg}={sweep_value}" if sweep_value is not None else ""
            all_samples, all_prompts, all_sizes = [], [], []
            all_metadata: Dict[str, list] = {}
            generate_time = _time.time()
            for batch in self.eval_dataloader:
                # per-batch heartbeat: a long healthy eval keeps beating,
                # a single wedged generate goes silent past the deadline
                self.watchdog.beat("eval", step=self.iter_count)
                kwargs = {sweep_arg: sweep_value} if sweep_value is not None else {}
                out = self.generate_eval(batch.input_ids, batch.attention_mask, **kwargs)
                # multi-host: decode/score only this host's rows; scalar
                # stats are all-gathered below. A ragged final batch
                # comes back padded with `real_rows` marking this
                # group's real count — trim after the local extraction.
                sequences = mh.local_rows(out["sequences"])
                sequences = sequences[: out.get("real_rows", len(sequences))]
                all_samples.extend(sequences)
                all_prompts.extend(np.asarray(batch.input_ids))
                all_sizes.extend([np.shape(batch.input_ids)[1]] * len(sequences))
                for k, v in (batch.metadata or {}).items():
                    all_metadata.setdefault(k, []).extend(v)
            stats["time/generate"] = _time.time() - generate_time

            str_samples, str_prompts, str_outputs = self.decode(
                all_prompts, all_samples, all_sizes
            )
            columns = ["prompt", "output"]
            columns_data = [str_prompts, str_outputs]

            if self.reward_fn:
                rewards = self._call_reward_fn(
                    samples=str_samples,
                    prompts=str_prompts,
                    outputs=str_outputs,
                    tokenizer=self.tokenizer,
                    **all_metadata,
                )
                rewards = [
                    float(np.sum(r)) if np.ndim(r) else float(r) for r in rewards
                ]
                columns.append("reward")
                columns_data.append(rewards)
                stats[f"reward/mean{suffix}"] = float(
                    np.mean(mh.allgather(np.asarray(rewards, np.float32)))
                )
            if self.metric_fn:
                metric_time = _time.time()
                metrics = self.metric_fn(
                    samples=str_samples, prompts=str_prompts, outputs=str_outputs,
                    **all_metadata,
                )
                stats["time/metric"] = _time.time() - metric_time
                stats.update(
                    {
                        f"metrics/{k}{suffix}": float(
                            np.mean(mh.allgather(np.asarray(xs, np.float32)))
                        )
                        for k, xs in metrics.items()
                    }
                )
                for metric, values in metrics.items():
                    if isinstance(values, float):
                        continue
                    columns.append(metric)
                    columns_data.append(list(values))
            if sweep_arg is not None:
                columns.insert(0, sweep_arg)
                columns_data.insert(0, [sweep_value] * len(str_prompts))
            table_rows.extend(list(zip(*columns_data)))

        title = f"Evaluation #{self.nth_evaluation}"
        for k, x in stats.items():
            if k.startswith("reward") or k.startswith("metrics"):
                title += f" {k}: {significant(x)}"
        shown = table_rows[: max(8, len(sweep_values))]
        logger.info(
            "\n%s",
            logging.format_table(
                title, columns, [[significant(x) for x in row] for row in shown]
            ),
        )

        self.nth_evaluation += 1
        return stats

    # ------------------------------------------------------------------
    # the training loop
    # ------------------------------------------------------------------

    def _step_update(self, params, opt_state, batch):
        """Pure (jit-traceable) single optimizer step: microbatch scan ->
        mean grads -> masked optimizer update."""
        loss_fn = self.loss
        num_mb, mb_size = self.num_mb, self.mb_size
        tx = self.tx
        gd = self.config.train.grads_dtype
        grads_dtype = _DTYPES[gd] if gd else None

        def compute(p, b):
            if grads_dtype is not None:
                # differentiate through a grads_dtype view: gradients come
                # out in that dtype (e.g. bf16 = half the HBM of fp32
                # grads); `params` stays the fp32 master the optimizer
                # updates (the bench-proven 1.3B recipe, docs/benchmarks.md)
                p = jax.tree_util.tree_map(
                    lambda x: x.astype(grads_dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x,
                    p,
                )
            return jax.value_and_grad(loss_fn, has_aux=True)(p, b)

        if num_mb == 1:
            (loss, stats), grads = compute(params, batch)
        else:
            # gradient-accumulation compensation hook: batch-statistic
            # terms (PPO's advantage whitening) are precomputed over
            # the FULL minibatch here, so splitting cannot change them
            batch = self._pre_accum_batch(batch)
            mbs = jax.tree_util.tree_map(
                lambda x: x.reshape((num_mb, mb_size) + x.shape[1:]), batch
            )
            first = jax.tree_util.tree_map(lambda x: x[0], mbs)
            (l_shape, s_shape), g_shape = jax.eval_shape(compute, params, first)
            # low-precision per-microbatch grads still ACCUMULATE in fp32
            # (bf16 running sums lose mantissa against a growing total)
            zeros = jax.tree_util.tree_map(
                lambda s: jnp.zeros(
                    s.shape,
                    jnp.float32
                    if jnp.issubdtype(s.dtype, jnp.floating) else s.dtype,
                ),
                (g_shape, l_shape, s_shape),
            )

            def body(acc, mb):
                (l, s), g = compute(params, mb)
                return jax.tree_util.tree_map(
                    lambda a, x: a + x.astype(a.dtype), acc, (g, l, s)
                ), None

            (g_sum, l_sum, s_sum), _ = jax.lax.scan(body, zeros, mbs)
            grads = jax.tree_util.tree_map(lambda x: x / num_mb, g_sum)
            if grads_dtype is not None:
                grads = jax.tree_util.tree_map(
                    lambda x: x.astype(grads_dtype), grads
                )
            loss = l_sum / num_mb
            stats = jax.tree_util.tree_map(lambda x: x / num_mb, s_sum)

        if self.guardrails.enabled and self.guardrails.cfg.grad_norm_max > 0:
            # the watchdog watches the global grad norm; computed in-graph
            # (one reduction over the grads already in registers) and
            # riding the existing async stats copy — no extra host sync
            stats = dict(stats, **{"losses/grad_norm": optax.global_norm(grads)})
        guard = self.config.train.skip_nan_updates
        good = None
        if guard:
            # a poisoned update is detectable from the loss OR the grads:
            # with grads_dtype="bfloat16" a backward-pass overflow can
            # produce inf grads under a perfectly finite loss, and those
            # must not reach params (a checkpoint of poisoned params
            # would brick the relaunch loop)
            good = jnp.isfinite(loss) & jax.tree_util.tree_reduce(
                lambda a, g: a & jnp.all(jnp.isfinite(g)),
                grads,
                jnp.asarray(True),
            )
        if hasattr(tx, "fused_apply"):
            # the freeze mask streams through the fused apply itself
            # (O(chunk) extra memory); blending frozen values back after
            # the apply would hold THREE fp32 param trees at peak —
            # measured as the 0.5 GB that OOMed the 1.3B recipe. The
            # NaN guard must respect the same budget, so here it zeroes
            # the gradients BEFORE the apply instead of selecting whole
            # trees after it: a poisoned step degrades to a weight-decay
            # -only update (no NaN ever reaches params/moments), and the
            # host-side abort counter still trips on persistent NaN.
            if guard:
                # where, not multiply: NaN grads * 0 is still NaN
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(good, g, jnp.zeros_like(g)), grads
                )
            new_params, new_opt_state = tx.fused_apply(
                params, grads, opt_state, mask=self._update_mask
            )
        else:
            updates, new_opt_state = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if guard:
                # NaN/inf guard must live INSIDE the trace: params and
                # opt_state are donated, so by the time the host could
                # inspect the loss the pre-update buffers are gone. The
                # traced select commits the old state when the update is
                # poisoned; the abort counter lives in the learn loop.
                new_params = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(good, n, o), new_params, params
                )
                new_opt_state = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(good, n, o), new_opt_state, opt_state
                )
        if guard:
            # fold the skip signal into the returned loss: the host's
            # isfinite check then catches finite-loss/bad-grad skips too,
            # with zero extra device->host transfers
            loss = jnp.where(good, loss, jnp.float32(jnp.nan))
        return new_params, new_opt_state, loss, stats

    def _pre_accum_batch(self, batch):
        """Subclass hook, traced inside the jitted step when the
        minibatch is split into accumulation microbatches: precompute
        any batch-statistic-coupled terms over the FULL minibatch so
        the split step stays numerically equal to the unsplit one
        (PPO precomputes whitened GAE advantages when the memory
        doctor's split_microbatch rung is active). Default: identity."""
        return batch

    def _pinned_state_shardings(self):
        # Pin output shardings to the current (input) shardings: without
        # this, GSPMD may choose different layouts for the step-1 outputs,
        # and the changed input shardings force a full retrace+recompile of
        # the train step on step 2.
        params_sh = jax.tree_util.tree_map(lambda x: x.sharding, self.params)
        opt_sh = jax.tree_util.tree_map(lambda x: x.sharding, self.opt_state)
        return params_sh, opt_sh

    def make_train_step(self):
        """One jitted function per optimizer step. Donates params/opt_state."""
        params_sh, opt_sh = self._pinned_state_shardings()
        return jax.jit(
            self._step_update,
            donate_argnums=(0, 1),
            out_shardings=(params_sh, opt_sh, None, None),
        )

    def make_fused_train_steps(self):
        """The whole inner loop as ONE jitted call: scan the optimizer
        step over host-chosen minibatch permutations of a device-resident
        epoch batch.

        Dispatch cost is per-call, not per-step — on a remote-tunneled
        chip each dispatch costs 100ms+, and even locally the XLA launch
        overhead and the per-step host sync disappear. The reference
        pays this per minibatch by construction (torch eager loop).

        Signature: (params, opt_state, full_batch, perms[n_steps, bs])
        -> (params, opt_state, mean_loss, mean_stats)."""

        def fused(params, opt_state, full_batch, perms):
            def body(carry, perm):
                p, o = carry
                mb = jax.tree_util.tree_map(lambda x: x[perm], full_batch)
                p, o, loss, stats = self._step_update(p, o, mb)
                return (p, o), (loss, stats)

            (params, opt_state), (losses, stats) = jax.lax.scan(
                body, (params, opt_state), perms
            )
            mean_stats = jax.tree_util.tree_map(
                lambda x: jnp.mean(x, axis=0), stats
            )
            return params, opt_state, jnp.mean(losses), mean_stats

        params_sh, opt_sh = self._pinned_state_shardings()
        return jax.jit(
            fused,
            donate_argnums=(0, 1),
            out_shardings=(params_sh, opt_sh, None, None),
        )

    def _fused_epoch_batch(self):
        """Override to enable `train.fused_inner_loop`: return the full
        inner-epoch training batch as a (pytree, n_rows) pair, or None
        when the trainer cannot provide one (streaming pipelines)."""
        return None

    def _epoch_perms(self, n: int) -> np.ndarray:
        """Stacked minibatch index rows [n_steps, batch_size] covering
        every inner epoch, drawn from the SAME per-epoch seed stream the
        looped path's create_train_dataloader consumes
        (pipeline.epoch_shuffle_order with seed = train.seed + the
        iter_count each epoch's loader would be created at). The scanned
        path therefore trains on minibatches in exactly the order the
        per-step loop would — the golden-equivalence contract
        (tests/test_scanned_epochs.py)."""
        from trlx_tpu.pipeline import epoch_shuffle_order

        bs = self.config.train.batch_size
        n_batches = max(n // bs, 1)
        rows = []
        it = self.iter_count
        for _ in range(self.n_inner_epochs):
            order = epoch_shuffle_order(n, self.config.train.seed + it)
            rows.append(order[: n_batches * bs].reshape(n_batches, -1))
            it += n_batches
        return np.concatenate(rows, axis=0).astype(np.int32)

    def pre_optimization_hook(self, will_continue: bool) -> None:
        """Hook fired right before the fused optimization block is
        dispatched, with every device input for the block already
        enqueued and the param buffers still valid (the block's donation
        invalidates them for any LATER dispatch). PPO uses it to launch
        the next cycle's rollout generation ahead of the block
        (ppo.overlap_rollouts); `will_continue` is False when this block
        reaches total_steps, so nothing is prefetched for a cycle that
        will never run."""

    def _abandon_prefetch(self) -> None:
        """Hook: drop any in-flight cross-cycle prefetch and rewind its
        data cursors (the prefetched work never trains). Called when
        learn() exits."""

    def _finish_train_stats(self, log: bool = True, suppress_abort: bool = False):
        """Materialize + process deferred fused-block metrics: run the
        NaN-abort guard on each block's mean loss, attach the
        host-derived keys (time/step — quantized to the flush boundary
        under async_metrics — and the LR), and log through the tracker.
        With `log=False` the LAST block's stats dict is returned instead
        of logged, for the caller to merge eval results into (any older
        pending blocks are still logged). `suppress_abort` demotes the
        guard's abort to an error log — used on exit paths where raising
        would mask the original control flow. Idempotent."""
        import time as _time

        # the flush is the fused block's device sync point: a wedged
        # collective manifests as this read never returning, so it
        # heartbeats as part of the fused_block phase
        with self.watchdog.phase("fused_block"):
            entries = self._deferred_train.flush()
        out = None
        for i, (stats, step, meta) in enumerate(entries):
            mean_loss = stats.pop("__mean_loss__")
            n_steps = meta["n_steps"]
            # time/step is only honest at a SYNC flush (log=False: the
            # boundary path materializes right after dispatch, so
            # elapsed is the true block wall). A deferred flush happens
            # after the next rollout phase already ran — reporting that
            # wall as time/step would fabricate a multi-x slowdown, so
            # deferred blocks log only the host dispatch cost per step.
            if not log and i == len(entries) - 1:
                stats["time/step"] = (_time.time() - meta["t0"]) / n_steps
            stats["time/dispatch"] = meta["dispatch_s"] / n_steps
            # LR at the block-START step (what the block actually
            # trained with) — same convention as the per-step loop
            stats["learning_rate_group_0"] = float(
                self.schedule(step - n_steps)
            )
            # watchdog: the block's mean loss (+ grad norm / cycle wall
            # when tracked) is THE health signal the escalation ladder
            # acts on at the next safe point (_run_guardrail_ladder)
            self.guardrails.observe_train(
                step=step, loss=mean_loss,
                grad_norm=stats.get("losses/grad_norm"),
                wall=meta.get("cycle_s"),
            )
            # one fused block counts as ONE bad step for the abort
            # counter: a single poisoned (skipped) step inside the scan
            # taints the block mean even when later steps recovered
            try:
                self._guard_bad_loss(mean_loss)
            except RuntimeError:
                if not suppress_abort:
                    raise
                logger.error(
                    "NaN-abort condition reached while flushing deferred "
                    "stats on an exit path; not re-raising"
                )
            if log or i < len(entries) - 1:
                self._log_fused_block(stats, step, n_steps)
            out = stats
        return out

    def _log_fused_block(self, stats, step: int, n_steps: int) -> None:
        """Console + tracker logging for one fused block (shared by the
        deferred flush and the boundary path, so the two can't drift)."""
        if self.memdoctor.enabled:
            # per-phase HBM peak attribution (memory/peak_<phase>_mb)
            # rides the tracker alongside the block's stats
            stats.update(self.memdoctor.sampler.peak_stats())
        desc = " | ".join(
            f"{k}: {v:.2f}"
            for k, v in stats.items()
            if k.startswith("losses/") or k == "loss"
        )
        logger.info(
            "[step %d/%d] (fused x%d) %s",
            step, self.total_steps, n_steps, desc,
        )
        # pending rollout stats carry an earlier-or-equal step index:
        # flush them first so tracker steps stay monotonic
        self._finish_rollout_stats()
        self._tracker_log(stats, step=step)

    def _learn_fused(self, fused_src, results):
        """All inner epochs in one device call (see make_fused_train_steps).

        Checkpoint/eval interval checks fire when a boundary is crossed
        inside the fused block — same cadence as the unfused loop up to
        quantization to block ends. Steady-state blocks (no boundary
        crossed) keep the host dispatch-only: the block's metrics stay
        on device behind an async copy (DeferredStats) and materialize
        one cycle later, so there is no blocking device read between
        cycle boundaries (train.async_metrics). The NaN guard selects
        per-step inside the scan; host-side the block's MEAN loss is the
        abort signal, evaluated when the stats materialize (at most one
        cycle late)."""
        import time as _time

        # the previous block's metrics land first: their copy streamed
        # under the rollout phase, so this is a free read — and the
        # NaN-abort check runs before any new work is dispatched
        self._finish_train_stats()
        # memory doctor: consume a latched HBM-watermark crossing once
        # per cycle, INDEPENDENT of the guardrails gate (with guardrails
        # on it joins this cycle's trips; off, it logs loudly)
        self._check_memory_watermark()
        if self.guardrails.enabled:
            # pull the just-collected rollout stats early so KL/reward
            # trips are seen BEFORE training on a poisoned batch (the
            # tiny scalar copy was staged at rollout end and has landed
            # by now; flush order matches the logging path, so tracker
            # steps stay monotonic)
            self._finish_rollout_stats()
            if self._run_guardrail_ladder():
                # the cycle was consumed by the action (batch requeued /
                # state rolled back): skip training, let the epoch loop
                # collect fresh experience
                return results, False

        full, n = fused_src
        ways = self.local_ways()
        if n % ways:
            # a short final rollout chunk (prompt set smaller than
            # chunk_size) leaves the store with a row count that does
            # not divide this process's shard count, and device_put
            # rejects uneven batch sharding. Pad rows by tiling modulo
            # n: the perms below only ever index [0, n), so pad rows
            # never train and never touch the running moments — this
            # is placement geometry, not data.
            pad_to = -(-n // ways) * ways
            idx = np.arange(pad_to) % n
            full = jax.tree_util.tree_map(lambda x: x[idx], full)
        bs = self.config.train.batch_size
        n_batches = max(n // bs, 1)
        steps_left = max(self.total_steps - self.iter_count, 1)
        perms = self._epoch_perms(n)[:steps_left]
        n_steps = len(perms)
        # quantization is silent degradation whenever the requested eval
        # cadence doesn't land on fused-block boundaries (finer than one
        # block, or any non-multiple — evals then fire late/irregularly):
        # say so ONCE, or the tracker's eval curve is sparser than the
        # reference's for no visible reason. Judge the NOMINAL block size
        # (a final total_steps-truncated block is not a cadence problem).
        nominal_block = self.n_inner_epochs * n_batches
        if (
            not self._warned_fused_cadence
            and nominal_block > 1
            and self.config.train.eval_interval % nominal_block != 0
        ):
            logger.warning(
                "fused_inner_loop runs %d optimizer steps per device call "
                "and eval_interval=%d is not a multiple: evals quantize to "
                "block boundaries. Lower ppo_epochs or raise batch_size "
                "(fewer steps per block), or disable train.fused_inner_loop "
                "for exact cadence.",
                nominal_block, self.config.train.eval_interval,
            )
            self._warned_fused_cadence = True

        if self._fused_train_step is None:
            self._fused_train_step = self.make_fused_train_steps()
        device_full = self.place_batch(full)
        if self.chaos is not None and self.chaos.consult("nan_loss"):
            # chaos: NaN-poison THIS cycle's epoch batch (a fresh tree —
            # the store's own arrays stay clean, so the burst ends when
            # the schedule says it ends)
            device_full = poison_batch(device_full)
        # cycle-level overlap: the next cycle's rollout generation is
        # dispatched NOW, ahead of the block — device FIFO samples it
        # first, and the host decodes+scores it while the block trains
        self.pre_optimization_hook(self.iter_count + n_steps < self.total_steps)
        t0 = _time.time()
        self.watchdog.beat("fused_block", "start", step=self.iter_count)
        # memory-doctor envelope: a RESOURCE_EXHAUSTED from the block
        # walks the degradation ladder (split microbatch -> remat ->
        # rollback) and RETRIES the same cycle instead of dying — the
        # device inputs are not donated, so a degraded re-dispatch sees
        # the identical batch. Bounded by the rung budgets (the ladder
        # ends in abort, which raises).
        for _attempt in range(self._oom_retry_budget()):
            try:
                if self.chaos is not None and self.memdoctor.enabled:
                    # chaos: simulated OOM at the dispatch point (param
                    # buffers intact, like a compile-time OOM)
                    self.chaos.oom("oom_fused_block")
                if self._fused_train_step is None:
                    # a degradation rung dropped the jitted step
                    self._fused_train_step = self.make_fused_train_steps()
                with self.mesh:
                    self.params, self.opt_state, loss, stats = self._fused_train_step(
                        self.params, self.opt_state, device_full, jnp.asarray(perms)
                    )
                break
            except Exception as e:
                if not (self.memdoctor.enabled and is_oom(e)):
                    raise
                if self._handle_oom(e, "fused_block") == "skip":
                    # rollback consumed the cycle: the epoch loop
                    # collects fresh experience at the restored step
                    self.watchdog.beat("fused_block", "end", step=self.iter_count)
                    return results, False
        else:
            # the retry budget is a backstop against a rung that
            # degrades without relieving the OOM — exhausting it must
            # fail loudly, not fall through with unbound outputs
            raise RuntimeError(
                "memory doctor: fused block still RESOURCE_EXHAUSTED "
                "after exhausting the degradation retry budget"
            )
        dispatch_s = _time.time() - t0
        if self.chaos is not None:
            # chaos: the host wedges right after the block is dispatched
            # — what a stuck device collective looks like from here. The
            # fused_block phase stays silent, so the watchdog deadline
            # is what ends the run (detection -> dump -> snapshot ->
            # stalled abort), not the scheduler's wall clock.
            self.chaos.stall("stall_collective")
        self.watchdog.beat("fused_block", "end", step=self.iter_count + n_steps)
        if self.chaos is not None and self.chaos.consult("sigterm"):
            # chaos: the preemption signal lands while the device is
            # mid-fused-block (dispatch is async) — exactly the worst
            # moment a scheduler reclaim can pick
            import signal as _signal

            os.kill(os.getpid(), _signal.SIGTERM)
        # ONE async device->host copy for loss + every scalar stat,
        # consumed at the next flush point (no blocking fetch here)
        prev = self.iter_count
        self.iter_count += n_steps
        self._policy_version += 1  # one fused block = one staleness unit
        staged = {"__mean_loss__": loss}
        staged.update(
            {k: stats[k] for k in stats if np.ndim(stats[k]) == 0}
        )
        cycle_s = None if self._last_cycle_t0 is None else t0 - self._last_cycle_t0
        self._last_cycle_t0 = t0
        self._deferred_train.stage(
            staged, step=self.iter_count,
            meta={"t0": t0, "n_steps": n_steps, "dispatch_s": dispatch_s,
                  "cycle_s": cycle_s},
        )
        # flight recorder: one optimization cycle = rollout collection
        # + this fused block's host span (the block's DEVICE time
        # materializes at the next flush and lands in the next cycle's
        # fused_block phase — steady-state attribution is consistent)
        self.obs.end_cycle(
            step=self.iter_count, policy_version=self._policy_version,
            n_steps=n_steps,
        )
        for _ in range(self.n_inner_epochs):
            self.post_backward_callback()

        def crossed(interval: int) -> bool:
            return (prev // interval) != (self.iter_count // interval) or (
                self.iter_count >= self.total_steps
            )

        ckpt_cross = crossed(self.config.train.checkpoint_interval)
        eval_cross = crossed(self.config.train.eval_interval)
        done = self.iter_count >= self.total_steps
        if (
            ckpt_cross or eval_cross or done
            or not self.config.train.async_metrics
        ):
            # boundary block: materialize this block's stats now (the
            # checkpoint/eval work blocks on the device anyway) and log
            # them merged with any eval results, like the unfused loop
            stats = self._finish_train_stats(log=False)
            if ckpt_cross:
                self._save_checkpoint(self._checkpoint_tag())
            if eval_cross:
                results = self.evaluate()
                stats.update(results)
                self._maybe_save_best(stats)
            self._log_fused_block(stats, self.iter_count, n_steps)
        if not done and self._should_stop(n_steps=n_steps):
            self._preemption_exit()
            done = True
        return results, done

    def _measure_forward(self, device_batch) -> float:
        """Time a jitted loss-only (forward) pass, once per batch shape
        (`train.timing_split`): compile, then measure a second run so the
        number excludes compilation. Probes a single microbatch and scales
        by num_mb so the probe never materializes more activation memory
        than the scanned train step does."""
        import time as _time

        key = _batch_shape_key(device_batch)
        if key in self._measured_forward_times:
            return self._measured_forward_times[key]

        probe_batch = device_batch
        scale = 1.0
        if self.num_mb > 1:
            probe_batch = jax.tree_util.tree_map(
                lambda x: x[: self.mb_size], device_batch
            )
            scale = float(self.num_mb)

        fwd = jax.jit(self.loss)
        with self.mesh:
            to_scalar(fwd(self.params, probe_batch)[0])  # compile + warm
            t0 = _time.time()
            to_scalar(fwd(self.params, probe_batch)[0])
            elapsed = (_time.time() - t0) * scale
        self._measured_forward_times[key] = elapsed
        return elapsed

    @abstractmethod
    def loss(self, params, batch) -> Tuple[jnp.ndarray, Dict]:
        """Pure jittable loss: (params, device batch) -> (loss, stats)."""

    @abstractmethod
    def prepare_learning(self) -> None:
        """Build train/eval dataloaders, set self.n_inner_epochs/total_steps."""

    @abstractmethod
    def create_train_dataloader(self):
        """Fresh (reshuffled) training dataloader."""

    def post_backward_callback(self) -> None:
        pass

    def post_epoch_callback(self) -> None:
        pass

    def _finish_rollout_stats(self) -> None:
        """Hook: materialize + log any stats the rollout phase deferred
        (PPO starts its device->host stats copy asynchronously so it can
        overlap the train step). Called before train-step tracker logging
        so tracker steps stay monotonic (wandb drops backdated steps)."""

    def add_prompt_pipeline(self, pipeline) -> None:
        raise NotImplementedError

    # -- fault-tolerance helpers ----------------------------------------

    # consecutive exhausted-retry tracker failures before the circuit
    # opens: a PERMANENTLY dead tracker must not charge the full backoff
    # (seconds of sleep) to every subsequent step for the rest of the run
    _TRACKER_CIRCUIT_LIMIT = 3

    def _tracker_log(self, stats: Dict[str, Any], step: int) -> None:
        """tracker.log with retry/backoff; a tracker outage degrades to a
        logged error, never a dead run (metrics are droppable, the
        training state is not). After _TRACKER_CIRCUIT_LIMIT consecutive
        exhausted-retry failures the circuit opens (resilient.
        CircuitBreaker with reset_timeout=0): one un-retried attempt per
        step — so a recovered backend resumes logging — with failures
        swallowed silently."""
        # flight-recorder tap on the ONE stats funnel: telemetry reuses
        # the exact host scalars the run already produces (the two
        # accounting paths cannot drift), and a tracker outage below
        # never costs the flight stream its numbers
        self.obs.observe_stats(stats, step)
        train = self.config.train
        probing = not self._tracker_breaker.is_closed
        if not self._tracker_breaker.allow():  # unreachable at reset=0
            return
        try:
            if probing:
                self.tracker.log(stats, step=step)
            else:
                retry_call(
                    self.tracker.log, stats, step=step,
                    retries=train.external_retries,
                    base_delay=train.retry_base_delay,
                    description="tracker.log",
                )
        except Exception as e:
            self._tracker_breaker.record_failure()
            if not probing:
                logger.error(
                    "tracker.log failed after retries; continuing without "
                    "logging step %d: %s%s", step, e,
                    " (circuit open: further steps attempt once, no backoff)"
                    if not self._tracker_breaker.is_closed else "",
                )
            return
        if probing:
            logger.info("tracker recovered; resuming retried logging")
        self._tracker_breaker.record_success()

    def _reward_fallback_value(self) -> float:
        """Value the fallback reward substitutes per sample when the
        reward service is down and `resilient_io.fallback_reward:
        hold_mean` is configured. PPO overrides with its running-moments
        mean; the base has no reward history, so 0 (neutral after
        running-moment scaling)."""
        return 0.0

    def _chaos_wrapped_reward(self, **kwargs):
        """reward_fn with the chaos fault sites threaded around it (the
        object the ResilientCaller retries — injected timeouts/errors
        exercise the real deadline/backoff/breaker path)."""
        if self.chaos is not None:
            self.chaos.reward_fault_pre()
        out = self.reward_fn(**kwargs)
        if self.chaos is not None:
            out = self.chaos.reward_fault_post(out)
        return out

    def _build_reward_caller(self) -> ResilientCaller:
        """Compose the hardened reward path from train.resilient_io:
        per-attempt deadline, retry/backoff/jitter, circuit breaker and
        fallback. With the default (empty) config this reduces exactly
        to PR 1 semantics: plain retries, final failure propagates."""
        train = self.config.train
        rcfg = self._resilient_cfg
        breaker = None
        fallback = None
        if rcfg.has_fallback:
            if rcfg.breaker_threshold > 0:
                breaker = CircuitBreaker(
                    failure_threshold=rcfg.breaker_threshold,
                    reset_timeout=rcfg.breaker_reset_s,
                )

            def fallback(exc, kwargs):
                n = len(kwargs.get("samples") or [])
                v = (
                    self._reward_fallback_value()
                    if rcfg.fallback_reward == "hold_mean"
                    else float(rcfg.fallback_reward)
                )
                return [v] * n

        return ResilientCaller(
            fn=self._chaos_wrapped_reward,
            description="reward_fn",
            timeout=rcfg.reward_timeout,
            retries=(
                rcfg.retries
                if rcfg.retries is not None else train.external_retries
            ),
            base_delay=(
                rcfg.base_delay
                if rcfg.base_delay is not None else train.retry_base_delay
            ),
            max_delay=rcfg.max_delay,
            jitter=rcfg.jitter,
            breaker=breaker,
            fallback=fallback,
        )

    def _call_reward_fn(self, **kwargs):
        """reward_fn through the resilient caller. Without a configured
        fallback, rewards stay load-bearing: the final failure
        propagates (the preemption path still gets a chance to
        checkpoint via learn()'s finally). With one, a slow or dead
        reward service degrades the run instead of hanging or killing
        it — the overlapped rollout pipeline keeps moving."""
        if self._reward_caller is None:
            self._reward_caller = self._build_reward_caller()
        with self.watchdog.phase("reward", step=self.iter_count):
            if self.chaos is not None:
                # chaos stall_reward: the hang happens BEFORE the
                # resilient caller, so no per-attempt deadline can cut
                # it short — only the watchdog's reward-phase deadline
                # ends it (consulted once per call, not per retry)
                self.chaos.stall("stall_reward")
            return self._reward_caller(**kwargs)

    def _checkpoint_tag(self) -> str:
        return f"checkpoint_{self.iter_count:0{len(str(self.total_steps))}d}"

    # consecutive failed checkpoint commits tolerated before the failure
    # propagates: transient shared-storage flakes must not kill a run
    # whose training state is intact (the next interval retries), but a
    # permanently unwritable store should not fail silently forever
    _CKPT_FAILURE_LIMIT = 3

    def _save_checkpoint(self, name: str, final: bool = False) -> None:
        """Commit a full checkpoint (state + deploy export) atomically
        under checkpoint_dir/<name> via the CheckpointManager.
        ``final=True`` marks an exit-path save (preemption / epoch
        exhaustion): a commit failure there propagates immediately —
        "the next interval retries" does not exist on the way out.

        Health-gated: with guardrails enabled, a commit is SKIPPED while
        the watchdog considers the run unhealthy — with async metrics
        the NaN signal lands one cycle late, and an ungated boundary
        right behind a bad block would publish a poisoned "last good
        checkpoint", the exact state auto-rollback restores. The skip
        decision is process 0's view broadcast to every host (commit()
        is collective)."""
        if self.guardrails.enabled and mh.broadcast_flag(
            not self.guardrails.commit_ok()
        ):
            logger.warning(
                "guardrails: run unhealthy (%s) — skipping checkpoint "
                "commit %r so the last good checkpoint stays good",
                self.guardrails.state_summary(), name,
            )
            return
        logger.info(
            "Saving checkpoint into %s",
            os.path.join(self.config.train.checkpoint_dir, name),
        )

        def write(tmp_dir: str) -> None:
            if self.chaos is not None and self.chaos.consult("ckpt_fail"):
                raise ChaosFault("chaos: injected checkpoint write failure")
            if self.config.train.save_optimizer:
                self.save(tmp_dir)
            self.save_pretrained(os.path.join(tmp_dir, "hf_model"))
            # self-documenting perf artifact: the run's bench-comparable
            # telemetry snapshot commits atomically WITH the checkpoint
            # (same tmp+rename protocol, hashed by the same integrity
            # manifest), so every checkpointed run leaves a trajectory
            # point even when nobody runs bench.py --record
            self.obs.write_telemetry(os.path.join(tmp_dir, "telemetry.json"))

        try:
            with self.watchdog.phase("checkpoint", step=self.iter_count):
                final_path = self.ckpt_manager.commit(name, write)
        except mh.BarrierTimeout as e:
            # a peer never reached the save_pretrained barrier: the
            # abandoned worker thread is still parked in that collective,
            # so CONTINUING to train would enqueue device collectives
            # that interleave with it across hosts (the hazard the
            # barrier exists to prevent). This is a detected stall, not
            # a tolerable commit flake — take the stalled exit.
            self._stalled_exit(f"checkpoint commit {name!r}: {e}")
        except Exception as e:
            # the manager's protocol guarantees a failed commit is never
            # discoverable (torn tmp_ dir only) and aborts consistently
            # on every host, so training state is intact — log, count,
            # and continue; the next interval (or the final save) retries
            self._ckpt_commit_failures += 1
            if final or self._ckpt_commit_failures >= self._CKPT_FAILURE_LIMIT:
                raise
            logger.error(
                "checkpoint commit %r failed (%d/%d consecutive before "
                "the failure propagates): %s — training continues; the "
                "next checkpoint interval retries", name,
                self._ckpt_commit_failures, self._CKPT_FAILURE_LIMIT, e,
            )
            return
        self._ckpt_commit_failures = 0
        self.obs.record("checkpoint", name=name)
        if self.watchdog.enabled and self.watchdog.cfg.emergency_snapshot:
            # the commit was health-gated, so the state just persisted
            # is also the freshest "known good" — refresh the host-RAM
            # shadow the hang doctor's emergency snapshot writes from
            self._update_emergency_shadow()
        if self.chaos is not None and self.chaos.consult("ckpt_corrupt"):
            # chaos: silent post-commit storage corruption (a bad DCN
            # write). The consult advances on EVERY host so the
            # schedule stays deterministic; only the primary touches
            # the shared filesystem. Recovery is the integrity
            # manifest's job at the next load.
            if mh.is_main():
                self.chaos.corrupt_checkpoint(final_path)

    def _commit_final_checkpoint(self, reason: str) -> None:
        """Commit the current step's checkpoint before the run exits —
        unless it already committed (e.g. preemption right after an
        interval save; rewriting every shard would re-open the re-commit
        window for nothing). The skip decision is process 0's view
        broadcast to all hosts: commit() is collective, so a host with a
        stale filesystem view deciding differently would deadlock the
        others."""
        tag = self._checkpoint_tag()
        # compare parsed STEP numbers, not directory names: the name's
        # zero-pad width tracks run-mutable total_steps (PPO re-derives
        # it from the store), so the same step can print differently
        # across a resume
        ckpts = self.ckpt_manager.step_checkpoints()
        skip = mh.broadcast_flag(
            bool(ckpts) and ckpts[-1][0] == self.iter_count
        )
        if skip:
            logger.info(
                "%s: step %d checkpoint already committed", reason,
                self.iter_count,
            )
            return
        self._save_checkpoint(tag, final=True)
        logger.info(
            "%s: checkpoint committed at step %d", reason, self.iter_count
        )

    def _preemption_exit(self) -> None:
        self._commit_final_checkpoint("preemption; exiting cleanly")

    def _maybe_save_best(self, stats: Dict[str, Any]) -> None:
        """Track the best eval reward and commit best_checkpoint on a new
        high (shared by the fused and unfused loops)."""
        if not self.config.train.save_best:
            return
        reward = stats.get(
            "reward/mean", stats.get("metrics/reward", -float("inf"))
        )
        if reward > self.best_reward:
            self.best_reward = reward
            logger.info("Saving best checkpoint")
            self._save_checkpoint("best_checkpoint")

    # multihost: agree on preemption every N optimizer steps rather than
    # every step — the ANY-reduce is a blocking host collective, and
    # preemption grace periods (30s+) dwarf a few steps of latency
    PREEMPT_SYNC_STEPS = 8

    def _should_stop(self, n_steps: int = 1, force: bool = False) -> bool:
        """Preemption check, agreed across hosts: the signal lands on
        whichever host the scheduler chose, so this is an ANY-reduce
        (mh.any_flag), not a process-0 broadcast. Single-host reads the
        local flag directly; multihost amortizes the collective over
        PREEMPT_SYNC_STEPS steps (every process runs the same control
        flow, so the sync cadence stays in lockstep). `force=True` syncs
        unconditionally — used at coarse boundaries (epoch tops, rollout
        chunks) where the collective is cheap relative to the work."""
        if not mh.is_multihost():
            return self.preemption.requested()
        if not force:
            self._preempt_sync_counter += n_steps
            if self._preempt_sync_counter < self.PREEMPT_SYNC_STEPS:
                return False
            self._preempt_sync_counter = 0
        return mh.any_flag(self.preemption.requested())

    def _guard_bad_loss(self, loss: float) -> bool:
        """Host half of the NaN/inf guard: returns True when the update
        was skipped device-side (non-finite loss). Aborts the run after
        `max_bad_steps` CONSECUTIVE skipped steps — a persistent NaN
        means diverged state, and looping forever on it would burn the
        whole job allocation silently."""
        if not self.config.train.skip_nan_updates or np.isfinite(loss):
            self._bad_steps = 0
            return False
        self._bad_steps += 1
        logger.warning(
            "non-finite loss %s at step %d: update skipped (%d/%d "
            "consecutive bad steps before abort)",
            loss, self.iter_count, self._bad_steps,
            self.config.train.max_bad_steps,
        )
        corrective = self.guardrails.enabled and any(
            a != "log" for a in self.guardrails.cfg.ladder
        )
        if self._bad_steps >= self.config.train.max_bad_steps:
            if corrective:
                # the watchdog owns escalation now: its ladder decides
                # whether this becomes a requeue, an LR cut, a rollback
                # or an abort — raising here would pre-empt a recoverable
                # intervention with a run-fatal one. A log-only ladder
                # cannot intervene, so the legacy abort stays the
                # backstop there (otherwise a persistent NaN would train
                # forever with every checkpoint commit health-gated off).
                logger.warning(
                    "%d consecutive non-finite losses (max_bad_steps=%d); "
                    "deferring the abort to the guardrails escalation "
                    "ladder", self._bad_steps,
                    self.config.train.max_bad_steps,
                )
                return True
            raise RuntimeError(
                f"aborting: {self._bad_steps} consecutive non-finite "
                f"losses (train.max_bad_steps={self.config.train.max_bad_steps}); "
                "the model state has diverged — restart from the last "
                "committed checkpoint with a lower lr / tighter clipping"
            )
        return True

    # -- guardrails (divergence watchdog) -------------------------------

    def _run_guardrail_ladder(self) -> bool:
        """Consume this cycle's watchdog verdict and execute the ladder
        action. Returns True when the cycle must be skipped (the batch
        was requeued / state was rolled back); raises on abort. Called
        once per cycle (fused block / optimizer step) at a point where
        no new device work has been dispatched."""
        # cross-host consistency watchdog first: a detected divergence
        # must join this cycle's trips (and the any_flag agreement
        # below) rather than waiting a cycle
        self._maybe_check_consistency()
        if mh.is_multihost():
            # lockstep: most signals derive from globally-reduced stats
            # and trip identically everywhere, but per-cycle wall time
            # is host-LOCAL by design (a stuck host trips it alone) —
            # and the resulting actions are collective (rollback's
            # allgather/load) or data-divergent (requeue's stream
            # rewind). Agree on "anyone tripped" every cycle so all
            # ladders advance together; one any_flag per cycle is noise
            # next to the rollout phase's collectives.
            peer = mh.any_flag(self.guardrails.has_pending_trips)
            if peer and not self.guardrails.has_pending_trips:
                self.guardrails.peer_trip()
        action = self.guardrails.pending_action()
        if action is not None:
            # the trip rows landed via the guardrail listener as they
            # were recorded; this row is the ladder's DECISION
            self.obs.record(
                "guardrail_action", action=action,
                rung=self.guardrails.state_summary()["rung"],
            )
        if action is None or action == "log":
            return False  # pending_action already logged the trip
        if action == "requeue":
            return self._requeue_poisoned_batch()
        if action == "lr_cut":
            self._apply_lr_cut(self.guardrails.cfg.lr_cut_factor)
            return False
        if action == "rollback":
            return self._rollback_to_last_good()
        # abort: coordinated across hosts via any_flag — every host
        # computes the same verdict from the same global stats, but the
        # agreement makes a pathological divergence (one host seeing
        # different numbers) abort the pod instead of deadlocking it
        if mh.any_flag(action == "abort"):
            raise RuntimeError(
                "guardrails abort: escalation ladder exhausted "
                f"({self.guardrails.state_summary()}); the run did not "
                "recover — relaunch resumes from the last good checkpoint"
            )
        return False

    # -- hang doctor (watchdog escalation + emergency shadow) -----------

    def _on_watchdog_stall(self, report: StallReport) -> None:
        """Monitor-thread escalation (host-side only — the device may
        be wedged, which is exactly why we are here): record the stall
        in the unified guardrails trip history, then persist the
        emergency snapshot from the host-RAM shadow. The watchdog
        aborts with EXIT_STALLED right after this returns."""
        self.guardrails.trip(STALL_SIGNAL, report.summary)
        if self.watchdog.cfg.emergency_snapshot:
            self.ckpt_manager.emergency_snapshot(report={
                "summary": report.summary,
                "phase": report.phase,
                "age_s": round(report.age_s, 3),
                "deadline_s": round(report.deadline_s, 3),
                "step": report.step,
                "timeline": [
                    [round(t, 3), phase, event, step]
                    for t, phase, event, step in report.timeline
                ],
            })

    def _stalled_exit(self, summary: str) -> None:
        """A stall detected OUTSIDE the monitor thread (a timed barrier
        blowing its deadline): route through the watchdog's own
        escalation so the operator gets the identical post-mortem —
        all-thread stacks + phase timeline, the unified `stall` trip
        record, the emergency snapshot — before the stalled exit. Does
        not return under the real abort hook."""
        self.watchdog.trip_external(
            "barrier", summary, step=self.iter_count
        )

    def _update_emergency_shadow(self) -> None:
        """Refresh the CheckpointManager's host-RAM shadow with the
        just-committed (health-gated) state: full host numpy copies of
        params/opt_state plus the resume metadata and topology
        manifest, so a later emergency snapshot persists without
        touching the device. Multihost sharded state is not fully
        host-addressable — skipped with a one-time note there (the
        stall report and stalled exit still fire; each host's last
        committed checkpoint remains the recovery point)."""
        tree = self._state_tree()
        if any(
            isinstance(x, jax.Array) and not x.is_fully_addressable
            for x in jax.tree_util.tree_leaves(tree)
        ):
            if not self._warned_shadow_skip:
                logger.info(
                    "hang doctor: state is sharded across hosts — the "
                    "emergency-snapshot shadow is unavailable (stall "
                    "detection, stack dumps and the stalled exit class "
                    "still apply; recovery point is the last committed "
                    "checkpoint)"
                )
                self._warned_shadow_skip = True
            return
        host_tree = jax.tree_util.tree_map(
            # np.array (not asarray): on CPU a jax.Array view would
            # alias the device buffer, which the next train step DONATES
            lambda x: np.array(x) if isinstance(x, jax.Array) else x,
            tree,
        )
        self.ckpt_manager.update_shadow(
            host_tree,
            self._resume_state_dict(),
            manifests={TOPOLOGY_MANIFEST: self._topology_manifest()},
        )

    # -- memory doctor (preflight / watermarks / OOM ladder) ------------

    def _extra_plan_items(self) -> List:
        """Subclass hook: extra :class:`~trlx_tpu.utils.memdoctor.
        PlanItem` rows folded into the preflight HBM plan (PPO adds the
        teacher-forced experience forward's activation residency)."""
        return []

    def _memory_preflight(self) -> None:
        """Admission control, run at the top of learn() BEFORE any
        model compile: build the analytic per-phase HBM plan and check
        its peak phase against the device budget. ``enforce`` fails an
        over-budget config with the itemized report while the mistake
        still costs seconds; ``warn`` logs the same report."""
        md = self.memdoctor
        if not md.enabled or md.cfg.preflight == "off":
            return
        plan = estimate_plan(self)
        self._hbm_plan = plan
        logger.info("memory doctor preflight:\n%s", plan.report())
        if plan.over_budget():
            msg = (
                "memory doctor: preflight REJECTED this config — the "
                "analytic HBM plan exceeds the admitted budget, and "
                "compiling it would only discover the same thing the "
                "slow way:\n" + plan.report()
            )
            if md.cfg.preflight == "enforce":
                raise MemoryPlanError(msg, plan)
            logger.warning(msg)

    def _check_memory_watermark(self) -> None:
        """Consume a latched watermark trip (and run the ``hbm_creep``
        chaos site) at the once-per-cycle safe point: creeping HBM
        residency raises the ``memory`` guardrail signal and walks the
        PR 3 ladder like any other health trip."""
        if not self.memdoctor.enabled:
            return
        sampler = self.memdoctor.sampler
        if self.chaos is not None and self.chaos.consult("hbm_creep"):
            # chaos: the next readings saturate the watermark — sampled
            # inline so the trip lands THIS cycle deterministically
            sampler.inject_creep()
            for _ in range(self.memdoctor.cfg.watermark_window):
                sampler.sample()
        detail = sampler.consume_trip()
        if detail:
            # recorded directly too: with guardrails off the crossing
            # would otherwise exist only as a log line
            self.obs.record("memory_watermark", detail=detail)
            if self.guardrails.enabled:
                self.guardrails.trip(MEMORY_SIGNAL, detail)
            else:
                # no ladder to walk, but creep headed for an OOM must
                # never pass silently just because guardrails are off
                logger.warning(
                    "memory doctor: %s — logged only (enable "
                    "train.guardrails for the escalation ladder)", detail,
                )

    def _oom_retry_budget(self) -> int:
        """Attempt bound shared by every OOM-retry envelope (fused
        block / per-step / rollout): every rung the ladder could
        possibly walk, plus slack for the terminal rollback/abort —
        the ladder itself terminates (abort raises), this only stops a
        logic bug from spinning."""
        cfg = self.memdoctor.cfg
        return cfg.max_splits + cfg.max_pool_shrinks + 4

    def _oom_caps(self) -> Dict[str, bool]:
        """What the memory doctor's ladder can actually do in THIS run:
        pool shrinking needs the decode engine, a microbatch split
        needs the halved size to stay sharding-divisible, remat can
        only escalate past the configured policy."""
        half = self.mb_size // 2
        can_split = (
            self.mb_size % 2 == 0
            and half >= 1
            and half % self.data_ways() == 0
            and self.config.train.batch_size % (self.num_mb * 2) == 0
        )
        return {
            "shrink_pool": self._engine_cfg.enabled,
            "split_microbatch": can_split,
            "remat": (
                remat_strength(self.memdoctor.cfg.remat_escalation)
                > remat_strength(self.config.train.remat_policy)
            ),
            "rollback": True,  # _rollback_to_last_good degrades gracefully
        }

    def _state_buffers_valid(self) -> bool:
        """After a RUNTIME OOM the failed dispatch may already have
        consumed its donated params/opt-state buffers — retrying with
        deleted arrays would crash; only a restore can recover."""
        try:
            return not any(
                x.is_deleted()
                for x in jax.tree_util.tree_leaves(self._state_tree())
                if isinstance(x, jax.Array)
            )
        except Exception:
            return True

    def _handle_oom(self, exc: BaseException, phase: str) -> str:
        """Classify a RESOURCE_EXHAUSTED and execute one rung of the
        degradation ladder. Returns ``"retry"`` when the failed
        dispatch should be re-attempted under the degraded config,
        ``"skip"`` when the cycle was consumed by a rollback; raises
        the itemized abort when the ladder is exhausted (or the doctor
        is disabled — raw propagation is the pre-doctor behavior)."""
        md = self.memdoctor
        if not md.enabled:
            raise exc
        event = classify_oom(exc, phase)
        # unified trip accounting: the OOM joins the guardrails history
        # (and escalates that ladder too if the run stays unhealthy)
        self.guardrails.trip(MEMORY_SIGNAL, event.summary())
        action = md.decide(event, self._oom_caps())
        # flight recorder: the OOM-ladder rung, in the same correlated
        # stream as the guardrail trip above
        self.obs.record(
            "oom", phase=phase, action=action, detail=event.summary(),
        )
        if action in ("shrink_pool", "split_microbatch", "remat") and (
            not self._state_buffers_valid()
        ):
            # the failed dispatch already consumed its donated buffers:
            # in-place degradation cannot retry — only a restore can
            logger.warning(
                "memory doctor: %s, but the failed step consumed its "
                "donated state buffers — escalating to rollback",
                event.summary(),
            )
            action = "rollback"
        if action == "abort":
            md.note(event, action)
            raise MemoryAbortError(
                md.abort_report(event, self._hbm_plan)
            ) from exc
        md.note(event, action)
        if action == "shrink_pool":
            # drop the engine's compiled fns: the next generate()
            # resolves the spec with the new (smaller) pool scale
            self._engine_fns.clear()
            return "retry"
        if action == "split_microbatch":
            self._apply_accum_factor()
            return "retry"
        if action == "remat":
            self._escalate_remat(md.cfg.remat_escalation)
            return "retry"
        # rollback: restore the last health-gated checkpoint; the
        # degradation state survives it (load() merges by max)
        if self._rollback_to_last_good():
            return "skip"
        raise MemoryAbortError(
            md.abort_report(event, self._hbm_plan)
        ) from exc

    def _apply_accum_factor(self) -> None:
        """Re-derive num_mb/mb_size from the configured microbatch and
        the doctor's accumulation factor, and drop the jitted steps so
        the next dispatch traces the split in. The split is
        golden-checked equal to the unsplit step (same global batch,
        fp32 accumulation — tests/test_memdoctor.py)."""
        base_mb = self.config.train.minibatch_size or self.config.train.batch_size
        mb = max(base_mb // self.memdoctor.accum_factor, 1)
        if self.config.train.batch_size % mb or mb % self.data_ways():
            logger.error(
                "memory doctor: accumulation factor %d does not divide "
                "cleanly (batch %d, base mb %d, dp*fsdp %d) — keeping "
                "the current microbatch", self.memdoctor.accum_factor,
                self.config.train.batch_size, base_mb, self.data_ways(),
            )
            return
        if base_mb < self.config.train.batch_size:
            # the config already accumulated (train.minibatch_size):
            # its loss whitened batch-statistic terms per MICROBATCH
            # (reference parity). The compensation hook precomputes
            # them over the FULL step batch instead — the canonical,
            # num_mb-invariant scope, which further splits preserve
            # exactly — so the first split shifts the whitening
            # statistics relative to the pre-OOM steps. Unavoidable:
            # no compensation can reproduce per-64-row statistics from
            # 32-row microbatches; say so instead of drifting silently.
            logger.warning(
                "memory doctor: config already used microbatch "
                "accumulation (minibatch_size=%d) — the split switches "
                "batch-statistic loss terms (PPO advantage whitening) "
                "from per-microbatch to full-batch scope; numerics are "
                "invariant to any FURTHER splits but differ from the "
                "pre-OOM per-microbatch statistics", base_mb,
            )
        self.mb_size = mb
        self.num_mb = self.config.train.batch_size // mb
        self._train_step = None
        self._fused_train_step = None
        logger.warning(
            "memory doctor: train microbatch split to %d rows "
            "(x%d gradient accumulation; global batch unchanged)",
            mb, self.num_mb,
        )

    def _escalate_remat(self, policy: str) -> None:
        """Switch the run to a stronger activation-checkpoint policy
        (ops/remat.py) and drop every jitted fn that baked the old one
        in. Never weakens a policy the user already configured."""
        self.config.train.remat_policy = policy
        self.memdoctor.note_remat(policy)
        self._drop_traced_fns()
        logger.warning(
            "memory doctor: activation checkpointing escalated to %r — "
            "backward recomputes instead of keeping residuals", policy,
        )

    def _drop_traced_fns(self) -> None:
        """Drop every cached jitted function that traced the remat
        policy in (subclasses extend: PPO adds its experience fns)."""
        self._train_step = None
        self._fused_train_step = None
        self._generate_fns.clear()
        self._engine_fns.clear()


    def _generate_rollout(self, input_ids, attention_mask):
        """generate() under the memory doctor's envelope: a
        RESOURCE_EXHAUSTED from rollout generation (the decode engine's
        prefill is the allocation spike) walks the ladder's
        shrink_pool rung — page pool and slots scale down, the engine
        fns retrace, and the SAME chunk retries. The ``oom_prefill``
        chaos site injects here, once per rollout generate() dispatch.
        Lives on the base trainer so every experience-collecting
        trainer (the online core AND RFT's offline sweep) shares it."""
        for _attempt in range(self._oom_retry_budget()):
            try:
                if self.chaos is not None and self.memdoctor.enabled:
                    self.chaos.oom("oom_prefill")
                return self.generate(input_ids, attention_mask)
            except Exception as e:
                if not (self.memdoctor.enabled and is_oom(e)):
                    raise
                # rollout OOMs never return "skip" (rollback is not on
                # the rollout sub-ladder); "retry" loops, abort raises
                self._handle_oom(e, "rollout_prefill")
        raise RuntimeError(
            "memory doctor: rollout generation still RESOURCE_EXHAUSTED "
            "after exhausting the pool-shrink budget"
        )

    def _dispatch_experience(self, fn, *args):
        """Run a jitted teacher-forced scoring forward under the memory
        doctor's classification envelope. An OOM here has no runtime
        relief rung (the forward is inference-shaped: microbatch splits
        and remat don't apply; ``train.logit_chunks`` is the
        config-time fix) — the envelope's value is the classified,
        itemized abort instead of a raw allocator error."""
        try:
            return fn(*args)
        except Exception as e:
            if not (self.memdoctor.enabled and is_oom(e)):
                raise
            self._handle_oom(e, "experience")  # experience -> abort
            raise  # unreachable: the abort above always raises

    def _apply_degradation(self) -> None:
        """Re-apply the doctor's (restored) degradation to the live
        trainer: pool scale, accumulation factor, remat policy. Called
        after load() adopts a persisted ``memory_degrade``."""
        md = self.memdoctor
        if md.pool_shrinks:
            self._engine_fns.clear()
        if md.accum_factor > 1:
            self._apply_accum_factor()
        if md.remat_policy is not None and (
            remat_strength(md.remat_policy)
            > remat_strength(self.config.train.remat_policy)
        ):
            self.config.train.remat_policy = md.remat_policy
            self._drop_traced_fns()

    # -- cross-host consistency watchdog --------------------------------

    def _extra_fingerprint(self) -> Dict[str, float]:
        """Subclass hook: extra host-side scalars folded into the
        consistency fingerprint (PPO adds its prompt cursor and KL
        controller value). Every value must be exactly representable in
        float32 and derived from lockstep state."""
        return {}

    def _consistency_fingerprint(self) -> Dict[str, float]:
        """A few scalars that must be IDENTICAL on every host of a
        healthy SPMD run: global reductions over params + opt_state
        (computed in-graph, replicated — on multihost the all-reduce
        itself is part of the check), plus the step counter, a PRNG-key
        hash and any trainer cursors. Cheap by construction: one tiny
        jitted reduction and one small host fetch per check."""
        if self._fingerprint_fn is None:

            def fp(params, opt_state):
                def reduce_tree(tree):
                    tot = jnp.float32(0.0)
                    l1 = jnp.float32(0.0)
                    for leaf in jax.tree_util.tree_leaves(tree):
                        x = jnp.asarray(leaf)
                        if not jnp.issubdtype(x.dtype, jnp.floating):
                            continue
                        x = x.astype(jnp.float32)
                        tot = tot + jnp.sum(x)
                        l1 = l1 + jnp.sum(jnp.abs(x))
                    return tot, l1

                p_sum, p_l1 = reduce_tree(params)
                o_sum, o_l1 = reduce_tree(opt_state)
                return jnp.stack([p_sum, p_l1, o_sum, o_l1])

            from trlx_tpu.parallel.mesh import replicated_sharding

            self._fingerprint_fn = jax.jit(
                fp, out_shardings=replicated_sharding(self.mesh)
            )
        with self.mesh:
            vec = np.asarray(self._fingerprint_fn(self.params, self.opt_state))
        out = {
            "params_sum": float(vec[0]),
            "params_l1": float(vec[1]),
            "opt_sum": float(vec[2]),
            "opt_l1": float(vec[3]),
            "iter": float(self.iter_count),
            # key-data hash folded into float32's exact-integer range
            "rng": float(
                int(np.asarray(self._pack_rng(), np.uint64).sum()) % (1 << 20)
            ),
        }
        out.update(self._extra_fingerprint())
        # values ride the consensus gather as float32: fold everything
        # through it up front so local-vs-reference compares are exact
        return {k: float(np.float32(v)) for k, v in out.items()}

    def _maybe_check_consistency(self) -> None:
        """Every ``guardrails.consistency_every`` cycles: fingerprint
        the local state and compare it against the fleet consensus
        (``multihost.consensus``). Divergence — one host's values
        departing the agreed reference — trips the escalation ladder
        like any other health signal instead of letting the host drift
        until a shape error or silent reward collapse. The chaos
        ``host_divergence`` fault perturbs THIS host's view after the
        gather, so the single-host simulation detects it the same way a
        peer would in a real fleet."""
        every = self.guardrails.cfg.consistency_every
        if not self.guardrails.enabled or every <= 0:
            return
        self._consistency_counter += 1
        if self._consistency_counter % every:
            return
        straggler_detail = None
        if self.watchdog.enabled and mh.is_multihost():
            # soft stall path: while collectives still work, compare
            # heartbeat counters fleet-wide — a host whose beats lag the
            # fleet max is a straggler, named by host AND phase, and the
            # trip walks the unified guardrails ladder (the hard path —
            # a frozen loop — is the monitor thread's deadline abort)
            strag = mh.straggler_report(self.watchdog.phase_ages())
            if not strag.agree:
                straggler_detail = strag.detail
                self.guardrails.trip(
                    STALL_SIGNAL,
                    f"cross-host straggler at step {self.iter_count}: "
                    f"{strag.detail}",
                )
        local = self._consistency_fingerprint()
        result = mh.consensus(local, atol=self.guardrails.cfg.consistency_atol)
        if self.chaos is not None and self.chaos.consult("host_divergence"):
            local = self.chaos.perturb_fingerprint(local)
        detail = result.detail
        if result.agree:
            # same agreement predicate as the cross-host row compare
            # (mh.values_agree): identical-NaN state is a fleet-wide
            # health problem for the loss guards, not a divergence
            atol = self.guardrails.cfg.consistency_atol
            drifted = [
                f"{k}={local[k]!r} != consensus {result.reference[k]!r}"
                for k in sorted(local)
                if not mh.values_agree(
                    local[k], result.reference.get(k, float("nan")), atol
                )
            ]
            detail = "; ".join(drifted[:8])
        if not result.agree or detail:
            self.guardrails.trip(
                "consistency",
                f"cross-host state fingerprint diverged at step "
                f"{self.iter_count}: {detail or 'rows disagree'}",
            )
        # flight recorder: cross-host row at the consensus cadence —
        # the local phase wall/beat counters (the straggler-attribution
        # signal) land in the same correlated timeline as everything
        # else, so "which host/phase was behind" reads off one stream.
        # The straggler verdict is the REPORT's, not the fingerprint's
        # (a numeric state divergence already rides the `consistency`
        # guardrail trip above — labeling it a straggler would misname
        # state drift as slowness).
        self.obs.record_hosts(
            self.watchdog.phase_ages() if self.watchdog.enabled else {},
            straggler_detail,
        )
        # trainer-specific lockstep assertions at the same cadence (PPO:
        # the experience-transport consumer cursor via
        # multihost.cursor_consensus)
        self._extra_consistency_checks()

    def _extra_consistency_checks(self) -> None:
        """Subclass hook, run at the consistency-check cadence after the
        fingerprint consensus: extra cross-host agreement assertions
        whose disagreement should trip the ladder."""

    def _requeue_poisoned_batch(self) -> bool:
        """Hook: discard the current (poisoned) training batch and
        arrange for its source data to be replayed. Base trainers have
        no requeue-able store; PPO discards the rollout store and
        rewinds the prompt cursor."""
        return False

    def _reset_data_stream(self) -> None:
        """Hook: rebuild the training data stream from position zero so
        a subsequent load()'s cursor restore can fast-forward to an
        EARLIER position than the live one (streams only advance). PPO
        rebuilds its prompt iterator from the retained pipeline."""

    def _apply_lr_cut(self, factor: float) -> None:
        """Multiply the whole LR schedule by ``factor`` (cumulative in
        self._lr_scale, persisted in state.json). The optimizer is
        rebuilt around the scaled schedule; optimizer STATE carries over
        unchanged (same transform structure), and the jitted steps are
        dropped so the next dispatch traces the new schedule in."""
        self._lr_scale *= float(factor)
        self._rebuild_optimizer()
        logger.warning(
            "guardrails: learning rate cut by %g (cumulative scale %g)",
            factor, self._lr_scale,
        )

    def _assemble_optimizer(self, opt_cfg, sched_cfg):
        """(tx, schedule) from configs, with the freeze mask chained in
        — the ONE place the optimizer is assembled (__init__ and the
        guardrail rebuild must never drift apart)."""
        tx, schedule = build_optimizer(opt_cfg, sched_cfg)
        if hasattr(tx, "fused_apply"):
            # fused optimizers write params directly (no updates tree to
            # chain a mask into); _step_update streams the mask through
            # fused_apply instead
            pass
        elif self._update_mask is not None:
            tx = optax.chain(tx, _mask_updates(self._update_mask))
        return tx, schedule

    def _rebuild_optimizer(self) -> None:
        okw = dict(self.config.optimizer.kwargs)
        skw = dict(self.config.scheduler.kwargs)
        if self._lr_scale != 1.0:
            okw["lr"] = okw["lr"] * self._lr_scale
            for k in ("eta_min", "final_lr"):
                # scale the schedule floor too, so the cut scales the
                # whole curve instead of pinning it to the old floor
                if k in skw:
                    skw[k] = skw[k] * self._lr_scale
        self.tx, self.schedule = self._assemble_optimizer(
            dataclasses.replace(self.config.optimizer, kwargs=okw),
            dataclasses.replace(self.config.scheduler, kwargs=skw),
        )
        self._train_step = None
        self._fused_train_step = None

    def _rollback_to_last_good(self) -> bool:
        """Auto-rollback: restore the newest committed resumable
        checkpoint — params, opt state, iter_count, PRNG key, KL
        controller / running moments and the prompt cursor (untrained
        prompts replay) — exactly as a process relaunch would, but
        in-process, losing at most checkpoint_interval steps. Commits
        are health-gated, so "latest resumable" is also "last good"."""
        def discover():
            path = self.ckpt_manager.latest_resumable()
            if mh.is_multihost():
                # stale shared-filesystem views must not pick different
                # checkpoints per host: process 0's discovery wins
                path = mh.allgather_object(path)[0]
            return path

        path = discover()
        if path is None:
            # nothing to restore: leave the live data stream UNTOUCHED
            # (resetting it here would clobber the prompt cursor of a
            # run that keeps training)
            logger.error(
                "guardrails: rollback requested but no resumable "
                "checkpoint exists under %s — continuing without "
                "rollback (the ladder will escalate if the run stays "
                "unhealthy)", self.config.train.checkpoint_dir,
            )
            return False
        self._abandon_prefetch()
        self._reset_data_stream()
        while True:
            logger.warning(
                "guardrails: auto-rollback to %s (discarding the diverged "
                "live state at step %d)", path, self.iter_count,
            )
            try:
                self.load(path)
                break
            except CheckpointCorruptError as e:
                # load() already quarantined the directory (renamed
                # *.corrupt), so re-discovery cannot hand it back:
                # fall back to the previous committed step instead of
                # aborting on poison
                logger.error(
                    "guardrails: rollback target was corrupt and has "
                    "been quarantined (%s); falling back to the "
                    "previous committed checkpoint", e,
                )
                path = discover()
                if path is None:
                    # every candidate was poison: nothing restorable.
                    # The data stream was already rebuilt from zero (a
                    # load was expected to fast-forward it), so the
                    # continuing run replays prompts from the stream
                    # start — cursor and stream stay self-consistent,
                    # and the alternative was crashing on poison.
                    logger.error(
                        "guardrails: no earlier resumable checkpoint "
                        "remains after quarantine — continuing without "
                        "rollback; the prompt stream was rebuilt from "
                        "zero, so subsequent cycles replay prompts",
                    )
                    return False
        # the restored arrays are fresh buffers: drop the jitted steps
        # whose output shardings were pinned to the donated originals
        self._train_step = None
        self._fused_train_step = None
        self._bad_steps = 0
        self.guardrails.notify_rollback(self.iter_count)
        return True

    def learn(self):
        """The training loop (parity: reference learn() :518-651)."""
        # memory doctor: admission control BEFORE any compile — an
        # over-budget config dies here with an itemized per-phase plan
        # instead of after a long compile (train.memory.preflight).
        # Deliberately before preemption.install(): a rejection must
        # not leak process-global signal handlers bound to a trainer
        # that never trained.
        self._memory_preflight()
        self.preemption.install()
        # arm the hang doctor for the duration of the loop (no-op when
        # train.watchdog is unset): phase heartbeats are already flowing
        # from the beat sites; this starts the monitor thread that
        # compares them against the deadlines
        self.watchdog.start()
        # ... and the memory doctor's HBM watermark sampler (no-op on
        # backends without memory_stats; default-off = no thread)
        self.memdoctor.sampler.start()
        # flight recorder: stamp provenance + open the first cycle
        # (resume keeps the restored run_id, so the stream stays one
        # correlated timeline across relaunches)
        self.obs.set_param_count(tree_param_count(self.params))
        self.obs.start(
            trainer=type(self).__name__,
            step=self.iter_count,
            total_steps=self.config.train.total_steps,
            batch_size=self.config.train.batch_size,
            seq_length=self.config.train.seq_length,
            mesh={ax: int(s) for ax, s in self.mesh.shape.items()},
            decode_impl=self._decode_impl(),
        )
        try:
            # serving frontend (train.serve.*): external requests ride
            # the engine lanes between training dispatches from here
            # on. INSIDE the try: a failed start (ineligible model,
            # transport bind error) must not leak the signal handlers
            # and monitor threads armed above — the same bug class the
            # memory-doctor preflight hardening fixed.
            self._serve_start()
            return self._learn()
        finally:
            # serving teardown FIRST: still-queued requests get a
            # cancelled result while the transport is certainly alive.
            # GUARDED: a teardown failure (transport outage mid-close)
            # must not skip the watchdog/preemption/tracker teardowns
            # below or mask the training exception.
            try:
                self._serve_close()
            except Exception:
                logger.exception("serve teardown failed (continuing)")
            self.memdoctor.sampler.stop()
            self.watchdog.stop()
            self.preemption.uninstall()
            # rollout phases defer their stats behind an async device->host
            # copy; flush even when learn() exits straight after a rollout
            # (total_steps hit before the next train step, or an exception)
            # so the final chunk's stats always reach the tracker
            self._finish_rollout_stats()
            # a deferred fused block may still be pending on an abnormal
            # exit (preemption/exception): flush it for the tracker, but
            # don't let the NaN-abort guard mask the live control flow
            self._finish_train_stats(suppress_abort=True)
            # an in-flight cross-cycle rollout prefetch never trains once
            # learn() exits: drop it and rewind its prompt cursor so a
            # resumed run replays those prompts
            self._abandon_prefetch()
            # flight recorder: close the open cycle and refresh the
            # flight-dir telemetry snapshot (after the stat flushes
            # above, so the final cycle's numbers are in it)
            self.obs.finish()
            # tracker teardown LAST among metric consumers — close()
            # re-drains any deferred stats the flushes above missed
            # (none in this ordering; the drain is the backstop) and
            # then flushes/releases the backends
            self.tracker.close()
            # external producer fleets (ppo.fleet.*): signal clean
            # finish when the budget is done, leave the fleet attached
            # for the relaunch handshake otherwise
            self._shutdown_producers()

    def _learn(self):
        logger.info("Starting training")
        # the relaunch loop re-runs a COMPLETED job's command line: bail
        # before prepare_learning, which for PPO would pay a full rollout
        # (generation + reward scoring) for nothing. The run-derived
        # budget from state.json covers store-limited PPO runs, gated on
        # an unchanged config total (raising total_steps means the user
        # wants to continue past the old budget).
        restored_done = (
            self._restored_total_steps is not None
            and self.iter_count >= self._restored_total_steps
            and self.config.train.total_steps == self._restored_config_total_steps
        )
        if self.iter_count > 0 and (
            self.iter_count >= self.config.train.total_steps or restored_done
        ):
            logger.info(
                "restored iter_count %d already covers the step budget "
                "(total_steps=%d%s); nothing to train", self.iter_count,
                self.config.train.total_steps,
                "" if self._restored_total_steps is None
                else f", run-derived={self._restored_total_steps}",
            )
            return {}
        self.prepare_learning()
        if self._should_stop(force=True):
            # preemption landed during prepare_learning (PPO: the first
            # rollout, possibly abandoned part-way) — checkpoint and
            # exit before paying the initial evaluation
            self._preemption_exit()
            return {}

        if self.iter_count > 0:
            # resumed run: continue from the restored step — replaying
            # from 0 with a restored optimizer state was the old (silent)
            # failure mode. The initial evaluation is skipped so tracker
            # step indices stay strictly monotonic across the restart.
            logger.info(
                "Resuming training at step %d/%d (best_reward=%s)",
                self.iter_count, self.total_steps,
                significant(self.best_reward),
            )
            results: Dict[str, Any] = {}
            if self.iter_count >= self.total_steps:
                logger.info(
                    "restored iter_count %d already >= total_steps %d; "
                    "nothing to train", self.iter_count, self.total_steps,
                )
                return results
        else:
            results = self.evaluate()
            self._tracker_log(results, step=self.iter_count)

        if self._train_step is None:
            self._train_step = self.make_train_step()

        clock = Clock()
        for _ in range(self.config.train.epochs):
            # epoch-top check catches a preemption that landed during
            # rollout collection / evaluation (PPO abandons the rollout
            # and falls through to here with a short or empty store)
            if self._should_stop(force=True):
                self._preemption_exit()
                return results
            # serving tick at the cycle boundary: requests that arrived
            # during the fused optimization block are served before the
            # next training dispatch
            self._serve_tick(self.iter_count)
            fused_src = (
                self._fused_epoch_batch()
                if self.config.train.fused_inner_loop
                else None
            )
            if fused_src is not None:
                results, done = self._learn_fused(fused_src, results)
                if done:
                    return results
                self.post_epoch_callback()
                continue
            # falling back to the per-step loop (empty/streaming store):
            # a still-deferred fused block from an earlier epoch must log
            # before this loop emits newer step indices
            self._finish_train_stats()
            guard_break = False  # ladder consumed this epoch's data
            cycle_steps0 = self.iter_count  # flight-recorder cycle span
            for _ in range(self.n_inner_epochs):
                train_dataloader = self.create_train_dataloader()
                for batch in train_dataloader:
                    if self._should_stop():
                        self._preemption_exit()
                        return results
                    if self.config.train.profile_dir is not None:
                        if self.iter_count == self.config.train.profile_start:
                            jax.profiler.start_trace(self.config.train.profile_dir)
                        elif self.iter_count == self.config.train.profile_stop:
                            jax.profiler.stop_trace()
                    if self._train_step is None:
                        # a guardrail lr_cut dropped the jitted step
                        # mid-epoch (the new schedule must trace in)
                        self._train_step = self.make_train_step()
                    device_batch = self.place_batch(batch)
                    if self.chaos is not None and self.chaos.consult("nan_loss"):
                        # chaos: poison THIS step's batch (per-step loop
                        # counterpart of the fused-block site — a
                        # trainer runs exactly one of the two paths, so
                        # the consult counter stays deterministic; this
                        # is what brings the ILQL/SFT/RFT per-step
                        # trainers under the chaos umbrella)
                        device_batch = poison_batch(device_batch)
                    forward_time = clock.tick()
                    self.watchdog.beat(
                        "train_step", "start", step=self.iter_count
                    )
                    # memory-doctor envelope (per-step counterpart of
                    # the fused-block one; the oom_fused_block chaos
                    # site doubles for this path like nan_loss does —
                    # a trainer runs exactly one of the two)
                    oom_skip = False
                    for _attempt in range(self._oom_retry_budget()):
                        try:
                            if self.chaos is not None and self.memdoctor.enabled:
                                self.chaos.oom("oom_fused_block")
                            if self._train_step is None:
                                self._train_step = self.make_train_step()
                            with self.mesh:
                                self.params, self.opt_state, loss, stats = self._train_step(
                                    self.params, self.opt_state, device_batch
                                )
                            break
                        except Exception as e:
                            if not (self.memdoctor.enabled and is_oom(e)):
                                raise
                            if self._handle_oom(e, "train_step") == "skip":
                                oom_skip = True
                                break
                    else:
                        raise RuntimeError(
                            "memory doctor: train step still "
                            "RESOURCE_EXHAUSTED after exhausting the "
                            "degradation retry budget"
                        )
                    if oom_skip:
                        # rollback consumed this step's data source —
                        # restart from the epoch top like a guardrail
                        # rollback does
                        self.watchdog.beat(
                            "train_step", "end", step=self.iter_count
                        )
                        guard_break = True
                        break
                    if self.chaos is not None:
                        if self.chaos.consult("sigterm"):
                            # chaos: preemption lands while the device is
                            # mid-step (dispatch is async) — same worst
                            # moment the fused path injects
                            import signal as _signal

                            os.kill(os.getpid(), _signal.SIGTERM)
                        # chaos: host wedges in the step's device sync
                        self.chaos.stall("stall_collective")
                    loss = to_scalar(loss)  # sync point: step is done
                    self.watchdog.beat(
                        "train_step", "end", step=self.iter_count
                    )
                    step_time = clock.tick()
                    bad = self._guard_bad_loss(loss)
                    # per-step counterpart of the fused path's
                    # once-per-cycle watermark consumption
                    self._check_memory_watermark()
                    if self.guardrails.enabled:
                        # unfused loop: one step = one watchdog cycle
                        self.guardrails.observe_train(
                            step=self.iter_count, loss=loss,
                            grad_norm=(
                                to_scalar(stats["losses/grad_norm"])
                                if "losses/grad_norm" in stats else None
                            ),
                        )
                        if self._run_guardrail_ladder():
                            # rollback/requeue: this dataloader's source
                            # is gone — restart from the epoch top
                            guard_break = True
                            break
                    if bad:
                        # poisoned update was skipped device-side: the
                        # step index does not advance and nothing is
                        # logged for it (the next good step keeps the
                        # tracker's step sequence contiguous)
                        continue
                    stats = {
                        k: to_scalar(v)
                        for k, v in stats.items()
                        if np.ndim(v) == 0
                    }
                    stats["time/step"] = step_time
                    # jit fuses fwd+bwd+update, so a per-step split does not
                    # exist; optionally measure a forward-only pass once
                    # (static shapes => constant cost) to fill the
                    # reference's time/forward & time/backward keys honestly
                    # skip the split on the first step of each batch shape:
                    # that step_time includes the train-step compile, which
                    # would otherwise be booked entirely under time/backward
                    shape_key = _batch_shape_key(device_batch)
                    if self.config.train.timing_split and (
                        shape_key in self._seen_step_shapes
                    ):
                        fwd_time = self._measure_forward(device_batch)
                        stats["time/forward"] = fwd_time
                        stats["time/backward"] = max(step_time - fwd_time, 0.0)
                    self._seen_step_shapes.add(shape_key)
                    stats["learning_rate_group_0"] = float(
                        self.schedule(self.iter_count)
                    )
                    self.iter_count += 1

                    if (
                        self.iter_count % self.config.train.checkpoint_interval == 0
                        or self.iter_count >= self.total_steps
                    ):
                        self._save_checkpoint(self._checkpoint_tag())

                    if (
                        self.iter_count % self.config.train.eval_interval == 0
                        or self.iter_count >= self.total_steps
                    ):
                        results = self.evaluate()
                        stats.update(results)
                        self._maybe_save_best(stats)

                    desc = " | ".join(
                        f"{k}: {v:.2f}"
                        for k, v in stats.items()
                        if k.startswith("losses/") or k == "loss"
                    )
                    logger.info("[step %d/%d] %s", self.iter_count, self.total_steps, desc)
                    # pending rollout stats carry an earlier step index:
                    # flush them first so tracker steps stay monotonic
                    self._finish_rollout_stats()
                    self._tracker_log(stats, step=self.iter_count)

                    if self.iter_count >= self.total_steps:
                        return results
                if guard_break:
                    break
                self.post_backward_callback()
            # per-step loop: one completed optimization cycle = one
            # staleness unit (the fused path counts one per block — both
            # count one version per pass over the cycle's data)
            if not guard_break:
                self._policy_version += 1
            # flight recorder: per-step-loop counterpart of the fused
            # path's cycle boundary (one cycle per inner-epoch pass)
            self.obs.end_cycle(
                step=self.iter_count, policy_version=self._policy_version,
                n_steps=self.iter_count - cycle_steps0,
            )
            self.post_epoch_callback()
        # epoch exhaustion can end BELOW total_steps (a NaN-skipped step
        # consumes its batch without advancing iter_count, and small
        # datasets simply run out of epochs): commit whatever progress
        # exists rather than leaving up to checkpoint_interval steps of
        # training only in memory
        if self.iter_count > 0:
            self._commit_final_checkpoint("epoch budget exhausted")
        return results

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------

    def _state_tree(self) -> Dict:
        return {"params": self.params, "opt_state": self.opt_state}

    def _shutdown_producers(self) -> None:
        """Subclass hook, called from learn()'s ``finally``: tear down
        any external rollout-producer fleet when the run is over for
        good, and leave it alive for re-attach when this exit is a
        preemption / stall / crash a supervisor will relaunch."""

    def _extra_state(self) -> Dict[str, Any]:
        """Subclass hook: extra JSON-serializable resumable state (KL
        controller value, data cursors, ...) merged into state.json."""
        return {}

    def _restore_extra_state(self, state: Dict[str, Any]) -> None:
        """Subclass hook: restore what `_extra_state` saved."""

    def _pack_rng(self) -> List[int]:
        try:
            data = jax.random.key_data(self.rng)
        except Exception:  # old-style raw uint32 key array
            data = self.rng
        return np.asarray(data).astype(np.uint32).tolist()

    def _unpack_rng(self, data) -> None:
        arr = jnp.asarray(np.asarray(data, np.uint32))
        try:
            if jnp.issubdtype(self.rng.dtype, jax.dtypes.prng_key):
                arr = jax.random.wrap_key_data(arr)
        except Exception:
            pass
        self.rng = arr

    def save(self, directory: Optional[str] = None) -> None:
        """Full training state via Orbax + state.json (parity: reference
        save :309-326 / accelerator.save_state).

        state.json carries everything needed to CONTINUE the run rather
        than replay it: iter_count, best_reward, the trainer PRNG key,
        the eval counter and per-trainer cursors (_extra_state). It is
        written to a temp file and os.replace'd — a preemption mid-save
        can never leave a truncated state.json shadowing a good one."""
        import orbax.checkpoint as ocp

        directory = os.path.abspath(directory or self.config.train.checkpoint_dir)
        ckptr = ocp.PyTreeCheckpointer()
        # orbax writes distributed arrays collectively: every process
        # calls save (each persists its shards); only process 0 writes
        # the scalar metadata
        ckptr.save(
            os.path.join(directory, "state"), self._state_tree(), force=True
        )
        if mh.is_main():
            atomic_json_write(
                os.path.join(directory, "state.json"),
                self._resume_state_dict(),
            )
            self._write_topology_manifest(directory)

    def _resume_state_dict(self) -> Dict[str, Any]:
        """The state.json contents: everything needed to CONTINUE the
        run rather than replay it. The ONE builder — the checkpoint
        save and the hang doctor's host-RAM shadow both use it, so an
        emergency snapshot resumes exactly like a regular checkpoint."""
        state = {
            "iter_count": self.iter_count,
            "best_reward": (
                self.best_reward if np.isfinite(self.best_reward) else None
            ),
            "nth_evaluation": self.nth_evaluation,
            "rng_key": self._pack_rng(),
            # cumulative guardrail LR-cut factor: a resumed (or
            # rolled-back) run re-applies the cut schedule exactly
            "lr_scale": self._lr_scale,
            # run-derived budget (PPO: min of config and store size):
            # lets a same-config relaunch of a COMPLETED run bail
            # before paying a rollout. A preemption-abandoned rollout
            # truncates the store, so the just-derived total_steps
            # UNDERSTATES the real budget — persisting it would make
            # every later relaunch bail as "completed"; carry the
            # restored values forward instead.
            "total_steps": (
                self._restored_total_steps
                if self._rollout_abandoned else self.total_steps
            ),
            "config_total_steps": (
                self._restored_config_total_steps
                if self._rollout_abandoned
                else self.config.train.total_steps
            ),
        }
        if self.memdoctor.enabled:
            # memory-doctor degradation level (pool shrinks / grad-accum
            # factor / remat escalation): committed INSIDE the atomic
            # state.json so a supervise.py relaunch and trainer.load()
            # resume already-degraded instead of re-OOMing at the
            # original sizes (verify_ckpt.py reports it)
            state["memory_degrade"] = self.memdoctor.degrade_state()
        if self.obs.active:
            # flight-recorder correlation state: the run_id + telemetry
            # run totals, so a resume appends to the SAME correlated
            # stream (ids stable across restart) and the trajectory
            # point keeps covering the whole run. Omitted when obs is
            # disabled — verify_ckpt.py must not advertise a stream
            # that was never written.
            state["obs"] = self.obs.state_dict()
        if self.guardrails.enabled:
            # bounded guardrail trip tail, committed in the same atomic
            # state.json: the post-resume event stream (and
            # verify_ckpt.py) keeps the pre-restart trip record
            state["guardrail_trips"] = self.guardrails.trip_tail()
        state.update(self._extra_state())
        return state

    def _topology_manifest(self) -> Dict[str, Any]:
        """The world that saved this checkpoint: mesh axis sizes, host
        and data-group counts, the global batch, and every state leaf's
        GLOBAL shape + dtype. Global shapes are mesh-independent, so a
        resume onto a different topology validates architecture against
        them (a shape mismatch is a model change, not a topology
        change) and reshards everything else onto the current mesh."""
        leaves = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            self._state_tree()
        )[0]:
            key = jax.tree_util.keystr(path)
            shape = tuple(getattr(leaf, "shape", ()))
            dtype = str(np.asarray(leaf).dtype if not hasattr(leaf, "dtype")
                        else leaf.dtype)
            leaves[key] = {"shape": list(shape), "dtype": dtype}
        return {
            "format": 1,
            "mesh": {ax: int(s) for ax, s in self.mesh.shape.items()},
            "process_count": mh.process_count(),
            "data_group_count": mh.data_group_count(self.mesh),
            "global_batch_size": int(self.config.train.batch_size),
            "leaves": leaves,
        }

    def _write_topology_manifest(self, directory: str) -> None:
        atomic_json_write(
            os.path.join(directory, TOPOLOGY_MANIFEST),
            self._topology_manifest(),
        )

    def _validate_topology(self, directory: str) -> None:
        """Elastic-resume gate, run BEFORE the orbax restore: compare
        the checkpoint's topology manifest against the live run.

        - per-leaf GLOBAL shape/dtype mismatches are an ARCHITECTURE
          change and always a hard error (restoring would silently
          broadcast/garble leaves);
        - mesh / host-count / data-group differences are a TOPOLOGY
          change: logged and allowed (the restore reshards onto the
          current mesh; the global PRNG key restores unchanged — it is
          host-independent by construction — and the PPO prompt stream
          is re-split via the group-invariant chunk schedule) unless
          ``train.elastic.allow_topology_change`` is false.
        Pre-elastic checkpoints (no manifest) restore as before, with a
        note."""
        fp = os.path.join(directory, TOPOLOGY_MANIFEST)
        if not os.path.isfile(fp):
            logger.info(
                "checkpoint %s has no topology manifest (pre-elastic "
                "save): resuming without topology validation", directory,
            )
            return
        with open(fp) as f:
            saved = json.load(f)
        live = self._topology_manifest()
        mismatched = []
        saved_leaves = saved.get("leaves", {})
        for key, meta in live["leaves"].items():
            got = saved_leaves.get(key)
            if got is None:
                mismatched.append(f"{key}: missing from checkpoint")
            elif list(got["shape"]) != meta["shape"] or got["dtype"] != meta["dtype"]:
                mismatched.append(
                    f"{key}: checkpoint {got['shape']}/{got['dtype']} vs "
                    f"live {meta['shape']}/{meta['dtype']}"
                )
        extra = set(saved_leaves) - set(live["leaves"])
        if extra:
            mismatched.append(f"checkpoint-only leaves: {sorted(extra)[:4]}")
        if mismatched:
            raise ValueError(
                f"checkpoint {directory} does not match the live model "
                f"ARCHITECTURE ({len(mismatched)} leaf mismatches; first: "
                f"{mismatched[0]}) — topology-change resume reshards the "
                "same global arrays onto a new mesh, it cannot convert "
                "between different models/optimizers"
            )
        topo_keys = ("mesh", "process_count", "data_group_count")
        changed = {
            k: (saved.get(k), live[k])
            for k in topo_keys
            if saved.get(k) != live[k]
        }
        if changed:
            if not self.elastic.allow_topology_change:
                raise ValueError(
                    f"checkpoint {directory} was saved under a different "
                    f"topology ({changed}) and "
                    "train.elastic.allow_topology_change is false"
                )
            logger.warning(
                "elastic resume: checkpoint %s was saved under a "
                "different topology (%s) — restoring onto the current "
                "mesh (params/opt-state resharded; PRNG key restored "
                "unchanged; data cursors re-split)", directory,
                "; ".join(
                    f"{k}: {old} -> {new}" for k, (old, new) in changed.items()
                ),
            )
        if saved.get("global_batch_size") != live["global_batch_size"]:
            logger.warning(
                "elastic resume: global batch size changed (%s -> %s); "
                "iter_count-derived schedules (LR, shuffles) keep their "
                "step semantics but cover different sample counts",
                saved.get("global_batch_size"), live["global_batch_size"],
            )

    def load(
        self,
        directory: Optional[str] = None,
        quarantine_corrupt: bool = True,
    ) -> None:
        import orbax.checkpoint as ocp

        directory = os.path.abspath(directory or self.config.train.checkpoint_dir)
        # integrity gate FIRST (before any state mutation): a shard
        # flipped by bad storage must never reach params. On mismatch
        # the checkpoint is quarantined (*.corrupt) and
        # CheckpointCorruptError propagates — the auto-resume and
        # auto-rollback paths catch it and fall back to the previous
        # committed step. ``quarantine_corrupt=False`` (user-pinned
        # explicit paths) raises without the rename.
        if self.elastic.verify_integrity:
            verify_or_quarantine(directory, do_quarantine=quarantine_corrupt)
        # then the elastic-resume gate: global-shape/dtype (architecture)
        # validation and the topology-change decision, also pre-mutation
        self._validate_topology(directory)
        ckptr = ocp.PyTreeCheckpointer()
        template = self._state_tree()
        # restore WITH the live template's shardings (RestoreArgs):
        # orbax then materializes each leaf directly onto the CURRENT
        # mesh — the topology-change path — instead of reading the
        # saved run's sharding file (which references a mesh that may
        # no longer exist) and deferring the reshard to us
        from orbax.checkpoint import checkpoint_utils

        restore_args = checkpoint_utils.construct_restore_args(template)
        restored = ckptr.restore(
            os.path.join(directory, "state"), item=template,
            restore_args=restore_args,
        )

        # Re-materialize the restored leaves as fresh XLA-ALLOCATED
        # buffers on the live arrays' shardings. The train step DONATES
        # params/opt_state, and restored arrays can be host-memory
        # backed (orbax restores to numpy; device_put of host memory
        # zero-copies on CPU) — donating such a buffer hands XLA memory
        # it does not own, observed under the chaos harness as
        # post-rollback NaN params and glibc "corrupted double-linked
        # list" aborts. A jitted identity copy cannot alias its
        # (non-donated) inputs, so its outputs are genuinely
        # XLA-allocated; one extra state copy per resume/rollback is
        # the price.
        live = {"params": template["params"], "opt_state": template["opt_state"]}
        raw = {"params": restored["params"], "opt_state": restored["opt_state"]}

        def placed(tmpl, value):
            if isinstance(tmpl, jax.Array):
                if isinstance(value, jax.Array):
                    # already device-resident (restore_args placed it on
                    # the live mesh); the jitted copy below still
                    # re-materializes it into XLA-owned buffers
                    return value
                return jax.device_put(np.asarray(value), tmpl.sharding)
            return value

        with self.mesh:
            staged = jax.tree_util.tree_map(placed, live, raw)
            shardings = jax.tree_util.tree_map(
                lambda t, v: t.sharding if isinstance(t, jax.Array) else None,
                live, raw,
            )
            restored_state = jax.jit(
                lambda t: jax.tree_util.tree_map(jnp.copy, t),
                out_shardings=shardings,
            )(staged)
        self.params = restored_state["params"]
        self.opt_state = restored_state["opt_state"]
        state_fp = os.path.join(directory, "state.json")
        if not os.path.exists(state_fp):
            # a corrupt/legacy checkpoint must not masquerade as a fresh
            # run: params were restored above, but the step counter and
            # reward history are unknown — resume would restart from 0
            logger.warning(
                "checkpoint %s has no state.json: params/opt_state were "
                "restored but iter_count/best_reward are unknown — "
                "treating as step 0 (legacy layout or a corrupted save)",
                directory,
            )
            return
        with open(state_fp) as f:
            state = json.load(f)
        self.iter_count = state.get("iter_count", 0)
        best = state.get("best_reward")
        self.best_reward = float(best) if best is not None else -float("inf")
        self.nth_evaluation = state.get("nth_evaluation", 0)
        scale = float(state.get("lr_scale", 1.0))
        if scale != self._lr_scale:
            self._lr_scale = scale
            self._rebuild_optimizer()
        if state.get("rng_key") is not None:
            self._unpack_rng(state["rng_key"])
        self._restored_total_steps = state.get("total_steps")
        self._restored_config_total_steps = state.get("config_total_steps")
        self._restore_memory_degrade(state.get("memory_degrade"))
        # flight recorder: adopt the saved run_id + telemetry totals
        # (correlation ids stable across resume) and the guardrail trip
        # tail, then mark the restore in the stream itself
        self.obs.load_state_dict(state.get("obs"))
        self.guardrails.load_trip_tail(state.get("guardrail_trips"))
        self.obs.record(
            "restore", path=os.path.basename(directory),
            to_step=self.iter_count,
        )
        self._restore_extra_state(state)

    def _restore_memory_degrade(self, saved: Optional[Dict[str, Any]]) -> None:
        """Adopt a checkpoint's persisted memory-doctor degradation.
        A DEGRADED checkpoint exists because the original sizes already
        OOMed — resuming it under a config that silently un-degrades it
        (doctor disabled) would re-OOM at exactly those sizes, so that
        fails LOUDLY unless ``train.memory.accept_undegrade`` asserts
        the environment changed. The merge is by max (monotonic), so a
        guardrail rollback restoring an older state.json can never
        un-degrade the live run either."""
        if not saved or not is_degraded_record(saved):
            return
        if self.memdoctor.cfg.accept_undegrade:
            logger.warning(
                "memory doctor: checkpoint carries degradation (%s) but "
                "train.memory.accept_undegrade is set — resuming at the "
                "ORIGINAL sizes; you are asserting they fit now",
                saved,
            )
            return
        if not self.memdoctor.enabled:
            raise ValueError(
                "this checkpoint was committed DEGRADED by the memory "
                f"doctor ({saved}) — the original sizes already OOMed — "
                "but train.memory is disabled in the resuming config, "
                "which would silently un-degrade it and re-OOM. Enable "
                "train.memory.enabled to resume degraded, or set "
                "train.memory.accept_undegrade: true to assert the "
                "original sizes fit now (e.g. after moving to larger "
                "devices)"
            )
        self.memdoctor.restore(saved)
        self._apply_degradation()
        logger.warning(
            "memory doctor: resumed degraded — %s", self.memdoctor.describe()
        )

    def save_pretrained(self, directory: Optional[str] = None) -> None:
        """Deploy artifact: HF-format export of the base model when the
        architecture supports it, else an Orbax params dump (parity:
        reference save_pretrained :285-307)."""
        directory = os.path.abspath(
            directory
            or os.path.join(self.config.train.checkpoint_dir, "hf_model")
        )
        os.makedirs(directory, exist_ok=True)
        base = self.params.get("base", self.params)
        # all processes join the gather (collective); process 0 writes
        base = mh.gather_params(base)
        # auxiliary heads (value / Q) ride alongside the deploy artifact so
        # an ILQL/PPO policy reloads losslessly (the HF export itself stays
        # base-only for from_pretrained parity, reference :526-553)
        aux = {k: v for k, v in self.params.items() if k != "base"}
        if aux:
            aux = mh.gather_params(aux)
            import orbax.checkpoint as ocp

            # orbax save is COLLECTIVE (internal sync_global_devices):
            # every process must call it, even though only the primary
            # writes
            ocp.PyTreeCheckpointer().save(
                os.path.join(directory, "aux"), aux, force=True
            )
            # trained adapters ALSO export in the HF-peft layout
            # (adapter_config.json + adapter_model.safetensors), so a
            # LoRA trained here serves through HF peft and reloads via
            # ModelConfig.peft_config=<dir> (ref modeling_base.py:347-353)
            from trlx_tpu.models.peft import ADAPTER_KEYS, save_peft_adapter

            adapters = {k: aux[k] for k in ADAPTER_KEYS if k in aux}
            if adapters and getattr(self, "_peft_cfg", None) and mh.is_main():
                try:
                    save_peft_adapter(
                        directory, adapters, self._peft_cfg, self.model.cfg,
                        getattr(self, "model_type", None),
                    )
                except Exception as e:  # keep the orbax artifact authoritative
                    logger.warning("HF-peft adapter export failed: %s", e)
        model_type = getattr(self, "model_type", None)
        exported = False
        if (
            model_type is not None
            and getattr(self, "_hf_config_path", None)
            and mh.is_main()
        ):
            try:
                import transformers

                hf_config = transformers.AutoConfig.from_pretrained(self._hf_config_path)
                save_pretrained_hf(base, self.model.cfg, model_type, hf_config, directory)
                exported = True
            except Exception as e:
                logger.warning("HF export failed (%s); saving orbax params", e)
        # all processes must agree on the fallback (the orbax save below
        # is collective)
        exported = mh.broadcast_flag(exported)
        if not exported:
            import dataclasses

            import orbax.checkpoint as ocp

            ocp.PyTreeCheckpointer().save(
                os.path.join(directory, "params"), base, force=True
            )
            if mh.is_main():
                tcfg = {
                    k: v
                    for k, v in dataclasses.asdict(self.model.cfg).items()
                    if k not in ("dtype", "param_dtype") and v is not None
                }
                arch_key = (
                    "seq2seq"
                    if self.config.model.model_arch_type == "seq2seq"
                    else "transformer"
                )
                with open(os.path.join(directory, "trlx_tpu_config.json"), "w") as f:
                    json.dump({arch_key: tcfg, "model_type": model_type}, f)
        if mh.is_main() and hasattr(self.tokenizer, "save_pretrained"):
            self.tokenizer.save_pretrained(directory)
        # wait out process 0's plain-file writes: racing ahead would let
        # a process enqueue device collectives that interleave with the
        # laggard's. With the hang doctor armed the wait is bounded: a
        # dead peer raises BarrierTimeout instead of hanging forever.
        mh.timed_barrier(
            "save_pretrained",
            self.watchdog.cfg.barrier_timeout_s if self.watchdog.enabled else 0,
        )


# ---------------------------------------------------------------------------
# the trainer-agnostic online experience core
# ---------------------------------------------------------------------------


class _GroupChunkLoader(DataLoader):
    """Per-data-group view of the GLOBAL prompt-chunk order: every
    process draws the SAME shuffle stream a plain ``DataLoader`` over
    the full prompt list would (one shuffle of the global index order
    per epoch, same RNG consumption), chunks it at the global chunk
    size, then collates ONLY this group's strided rows of each chunk.

    This is what makes the prompt stream topology-invariant: the chunk
    composition is fixed by (seed, chunk_size) alone, so a checkpoint
    cursor saved under G data groups replays the exact same prompts
    under G' groups — while each host still pays only 1/G of the
    per-pull collation (the index slice happens BEFORE collate).
    Groups are padded to equal row counts by wrapping within the chunk
    (SPMD lockstep needs equal-shape pulls; the repeated row is the
    same compromise `shard_list` made)."""

    def __init__(
        self, dataset, batch_size, collate_fn, group, group_count,
        seed, shuffle=True, drop_last=True,
    ):
        super().__init__(
            dataset, batch_size, collate_fn=collate_fn, shuffle=shuffle,
            drop_last=drop_last, seed=seed,
        )
        self.group = group
        self.group_count = group_count

    def _select_rows(self, idxs) -> List[int]:
        # DataLoader.__iter__ hook: shuffle/chunking stay the base
        # class's (the parity-critical RNG stream is written ONCE);
        # only the row selection differs
        local = [int(i) for i in idxs[self.group :: self.group_count]]
        want = (len(idxs) + self.group_count - 1) // self.group_count
        i = 0
        while len(local) < want:
            local.append(int(idxs[(self.group + i * self.group_count) % len(idxs)]))
            i += 1
        return local


class TPUOnlineTrainer(TPUBaseTrainer):
    """The trainer-agnostic online experience core.

    Everything an on-policy RLHF trainer needs to COLLECT experience
    lives here, independent of the algorithm that scores it: the
    topology-invariant prompt stream + cursors, the chunked
    ``generate()`` rollout loop with one-chunk lookahead, the
    cross-cycle prefetch (``method.overlap_rollouts``), the decode
    engine seam (``method.gen_engine.*``, inherited from the base
    generate()), the resilient experience transport
    (``method.exp.*``, trlx_tpu/exp/) and the rollout fleet
    (``method.fleet.*``, trlx_tpu/fleet/), plus the reward
    running-moment machinery and the honest rollout accounting.

    Subclasses provide exactly one method-specific seam:
    ``_score_and_assemble`` — decode + reward + the algorithm's
    experience assembly for one generated chunk — and the usual
    ``loss``/``setup_model``. PPO and GRPO are both this class plus a
    seam; neither copies a line of the transport/fleet/prefetch
    machinery.
    """

    def __init__(self, config, **kwargs):
        super().__init__(config, **kwargs)

        data_ways = self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
        if config.method.chunk_size % data_ways:
            raise ValueError(
                f"method.chunk_size {config.method.chunk_size} must be divisible "
                f"by dp*fsdp={data_ways}"
            )
        self.store = self._make_store()
        self.running_moments = running_moments_init()
        self.ref_mean = getattr(config.method, "ref_mean", None)
        self.ref_std = getattr(config.method, "ref_std", None)

        self._deferred_rollout = DeferredStats()
        # rollout-data cursor: how many prompt chunks this run has pulled
        # off the (deterministically shuffled) prompt stream. Saved in
        # state.json so a resumed run fast-forwards to the exact position
        # instead of replaying the stream from its start.
        self._prompt_batches_consumed = 0
        self._resume_prompt_cursor = 0
        # cross-cycle rollout prefetch (method.overlap_rollouts): the
        # next cycle's first chunk, generated ahead of the current fused
        # optimization block, plus the prompt cursor it must rewind to
        # if it never trains (preemption / run end)
        self._prefetched_gen: Optional[Tuple] = None
        self._prefetch_cursor_start: Optional[int] = None
        self.log_rollouts = config.train.rollout_logging_dir is not None
        if self.log_rollouts:
            self.setup_rollout_logging(config)
        # resilient experience transport (method.exp.*, trlx_tpu/exp/):
        # rollout chunks travel through a leased, deduplicating queue
        # with a staleness admission gate; default off = the direct
        # rollout loop, and fault-free the transport path is golden-
        # checked bit-equal to it (tests/test_exp_queue.py)
        self._exp_cfg = ExpConfig.from_dict(getattr(config.method, "exp", None))
        self._exp: Optional[ExperienceTransport] = None
        if self._exp_cfg.enabled:
            self._exp = ExperienceTransport(
                self._exp_cfg, owner=f"proc{mh.process_index()}"
            )
        # policy version the in-flight overlap_rollouts prefetch was
        # generated at (the chunk is consumed one optimizer cycle later,
        # so its recorded version must be the generation-time one)
        self._prefetch_policy_version = 0
        # fault-tolerant rollout fleet (method.fleet.*, trlx_tpu/fleet/):
        # chunk production routed to cross-process workers behind the
        # transport seam — membership heartbeats, versioned weight
        # broadcast, degraded-mode fallback to the in-process path
        self._fleet_cfg = FleetConfig.from_dict(
            getattr(config.method, "fleet", None)
        )
        self._fleet = None
        if self._fleet_cfg.enabled:
            if self._exp is None:
                raise ValueError(
                    "method.fleet.enabled requires method.exp.enabled: the "
                    "fleet produces chunks BEHIND the experience "
                    "transport (delivery/dedup/staleness stay its job)"
                )
            if mh.process_count() > 1:
                raise NotImplementedError(
                    "method.fleet with a multi-process learner mesh is not "
                    "supported yet (run one learner process; workers "
                    "scale horizontally instead)"
                )
            from trlx_tpu.fleet.coordinator import FleetCoordinator

            self._fleet = FleetCoordinator(
                self._fleet_cfg,
                self._fleet_cfg.resolved_dir(config.train.checkpoint_dir),
                owner=f"learner-{mh.process_index()}",
            )

    # -- method-specific seams -------------------------------------------

    def _make_store(self):
        """The rollout store. Default: the rectangular device-resident
        pytree store (works for any flax.struct batch with a
        ``query_tensors`` leading field)."""
        from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage

        return PPORolloutStorage(
            pad_token_id=self.generate_settings.pad_token_id
        )

    def _inner_epochs(self) -> int:
        """Optimization epochs per collected rollout batch (PPO:
        ``method.ppo_epochs``)."""
        raise NotImplementedError

    @abstractmethod
    def _score_and_assemble(
        self, batch: PromptBatch, gen_out, stats: Dict[str, Any],
        iter_count: int, clock: Clock,
    ):
        """The method-specific half of one rollout chunk: decode +
        reward_fn, the algorithm's experience assembly (teacher-forced
        forwards, advantages, ...), running-moment update and the
        chunk's stats (mutated into ``stats``). Shared verbatim by the
        direct rollout loop, the experience-transport producer AND the
        fleet worker, so the paths cannot numerically diverge. Returns
        ``(rollout_batch, rows_local)``."""

    def _apply_staleness_clip(self, rollout_batch):
        """IMPACT-style admission correction for an over-stale chunk
        (``exp.staleness.mode: clip``): recompute behavior terms with
        the CURRENT policy and thread the mismatch into the surrogate
        as a clipped per-token importance weight. Method-specific."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement "
            "exp.staleness.mode='clip'; use mode='reject'"
        )

    def _rollout_stage_meta(self):
        """Metadata staged with each cycle's deferred rollout stats
        (PPO: the adaptive KL controller value at collection time)."""
        return None

    # -- rollout engine --------------------------------------------------

    def make_experience(self, num_rollouts: int = 1024, iter_count: int = 0) -> None:
        """Collect `num_rollouts` rollouts into the store (parity:
        reference make_experience :251-525; §3.2 call stack)."""
        # hang doctor: the rollout phase heartbeats per chunk inside the
        # loop, so a many-chunk collection stays healthy while a single
        # wedged generate/score goes silent past the rollout deadline
        with self.watchdog.phase("rollout", step=iter_count):
            self._make_experience(num_rollouts, iter_count)

    def _make_experience(self, num_rollouts: int, iter_count: int) -> None:
        from time import time

        if self._exp is not None:
            return self._make_experience_exp(num_rollouts, iter_count)
        logger.info("Collecting rollouts")
        self._rollout_abandoned = False
        # snapshot the prompt cursor: an abandoned (preempted) rollout
        # discards its partial store, so the cursor must rewind to here
        # or the resumed run would skip prompts that never trained. When
        # the cycle starts from a prefetched chunk (overlap_rollouts),
        # the rewind target is the cursor BEFORE that chunk's prompts
        # were pulled — the prefetch pull already advanced it.
        prompt_cursor_start = (
            self._prefetch_cursor_start
            if self._prefetched_gen is not None
            else self._prompt_batches_consumed
        )
        # guardrail `requeue` rewinds to here: the whole cycle's prompts
        # replay when its rollout batch turns out poisoned
        self._cycle_cursor_start = prompt_cursor_start
        self._finish_rollout_stats()  # flush any deferred previous-cycle stats
        clock = Clock()
        n_collected = 0
        accumulated_stats: List[Dict[str, float]] = []

        pbar = logging.progress(total=num_rollouts, desc="rollouts")
        # one-chunk lookahead: generation for chunk i+1 is DISPATCHED
        # before chunk i's host work (decode + reward_fn), so the device
        # samples while the host scores — the reference's rollout loop is
        # fully serial here (SURVEY §7 "host-device choreography")
        if self._prefetched_gen is not None:
            # cycle-level overlap: chunk 0 was dispatched ahead of the
            # previous cycle's fused optimization block and sampled
            # under it on-device (pre_optimization_hook)
            next_batch, next_gen, next_gen_time = self._prefetched_gen
            self._prefetched_gen = None
            self._prefetch_cursor_start = None
        else:
            next_batch = self._next_prompt_batch()
            rollout_generate_time = time()
            next_gen = self._generate_rollout(
                next_batch.input_ids, next_batch.attention_mask
            )
            next_gen_time = time() - rollout_generate_time
        chunk_rows = len(next_batch.input_ids) * mh.data_group_count(self.mesh)
        while n_collected < num_rollouts:
            self.watchdog.beat("rollout", step=iter_count)
            # lane-refill decision point: pending serve requests outrank
            # the next training chunk's dispatch (bounded allowance)
            self._serve_tick(iter_count)
            if self.chaos is not None:
                # chaos: the sampler wedges at the top of this chunk —
                # the rollout phase goes silent and the watchdog's
                # deadline (not the scheduler) must end the run
                self.chaos.stall("stall_rollout")
            # rollout collection dominates on-policy wall-clock: a
            # preemption landing here must not wait out the remaining
            # chunks (the grace period would expire before the final
            # save). Abandon the rollout — learn()'s epoch-top check
            # saves and exits. Forced sync: every host runs this loop in
            # lockstep.
            if self._should_stop(force=True):
                logger.warning(
                    "preemption during rollout collection: abandoning "
                    "after %d/%d rollouts", n_collected, num_rollouts,
                )
                # flags the store as truncated: the total_steps that
                # prepare_learning derives from it must not be persisted
                # as this run's real budget. The cursor rewinds to the
                # cycle start — this cycle's chunks never train, so the
                # resumed run must replay them.
                self._rollout_abandoned = True
                self._prompt_batches_consumed = prompt_cursor_start
                break
            stats: Dict[str, float] = {}
            batch, gen_out = next_batch, next_gen
            stats["time/rollout_generate"] = next_gen_time
            if n_collected + chunk_rows < num_rollouts:
                next_batch = self._next_prompt_batch()
                rollout_generate_time = time()
                next_gen = self._generate_rollout(
                    next_batch.input_ids, next_batch.attention_mask
                )
                next_gen_time = time() - rollout_generate_time
            else:
                next_batch, next_gen = None, None

            rollout_batch, rows_local = self._score_and_assemble(
                batch, gen_out, stats, iter_count, clock
            )
            accumulated_stats.append(stats)

            self.push_to_store(rollout_batch)
            n_collected += rows_local * mh.data_group_count(self.mesh)
            if hasattr(pbar, "update"):
                pbar.update(rows_local * mh.data_group_count(self.mesh))
            logger.info("[rollout %d / %d]", n_collected, num_rollouts)

        # flight recorder: this cycle's collected samples — the SAME
        # n_collected the trainer's own rollout accounting advances, so
        # telemetry samples/s cannot drift from it
        self.obs.note_samples(n_collected)
        if not accumulated_stats:
            # rollout abandoned before the first chunk completed
            # (preemption): nothing to log, nothing pending
            if hasattr(pbar, "close"):
                pbar.close()
            return
        agg = {
            k: sum(xs[k] for xs in accumulated_stats) / len(accumulated_stats)
            for k in accumulated_stats[-1]
        }
        # ONE packed async device->host copy for every accumulated device
        # scalar, materialized lazily (post_backward / next
        # make_experience): on a remote-tunneled chip the blocking read
        # costs a full ~100ms round trip, which this way overlaps the
        # train step instead of extending the rollout phase
        if hasattr(pbar, "close"):
            pbar.close()
        self._deferred_rollout.stage(
            agg, step=iter_count, meta=self._rollout_stage_meta()
        )

    # -- shared score/assemble helpers -----------------------------------

    def _update_reward_moments(self, scores, scores_mask, stats):
        """Fold one chunk's host-computed scores into the running reward
        moments and pick the reward-scaling divisor (``method.
        scale_reward``). Local per-row sums -> one GLOBAL vector; the
        running-moment update then reduces over every host's rows
        in-graph (the reference all-gathers scores to rank 0 instead).
        A short final chunk (prompt dataset smaller than chunk_size)
        may not divide dp*fsdp — keep the tiny vector replicated then
        (padding would bias the running reward moments). Multi-host
        replication of per-group-DIFFERENT rows needs a host-side
        allgather first, so every process places the same full vector
        (parity: the reference pads across processes,
        accelerate_ppo_trainer.py:292-300). Returns ``scale_div`` (a
        device scalar)."""
        method = self.config.method
        local_sums = (scores * scores_mask).sum(axis=1)
        rows = len(local_sums) * mh.data_group_count(self.mesh)
        if rows % self.data_ways() == 0:
            score_sums = mh.global_from_local(
                local_sums, vector_sharding(self.mesh)
            )
        elif mh.is_multihost():
            score_sums = jax.device_put(
                np.asarray(
                    mh.allgather_group_rows(
                        local_sums.astype(np.float32), self.mesh
                    ),
                    np.float32,
                ),
                replicated_sharding(self.mesh),
            )
        else:
            score_sums = mh.global_from_local(
                local_sums, replicated_sharding(self.mesh)
            )
        if self.ref_mean is None:
            self.ref_mean = float(score_sums.mean())
            self.ref_std = float(score_sums.std())
        new_moments, scores_mean, scores_std = running_moments_update(
            self.running_moments, score_sums
        )
        # a NaN-poisoned chunk must not permanently poison the
        # running reward moments (they scale every later reward and
        # persist across checkpoints): keep the pre-chunk moments
        # when the chunk's sums are non-finite. The chunk's OWN
        # stats still report the poison, so the guardrails see it.
        keep = jnp.all(jnp.isfinite(score_sums))
        self.running_moments = jax.tree_util.tree_map(
            lambda n, o: jnp.where(keep, n, o),
            new_moments, self.running_moments,
        )
        # stats stay DEVICE scalars until the single packed fetch at
        # the end of make_experience (each host read costs a full
        # round-trip on a remote-tunneled chip)
        stats["rollout_scores/mean"] = scores_mean
        stats["rollout_scores/std"] = scores_std
        stats["rollout_scores/running_mean"] = self.running_moments.mean
        stats["rollout_scores/running_std"] = self.running_moments.std

        # reward scaling happens inside the experience fn: pass the
        # divisor as a device scalar instead of fetching the running
        # std to the host
        scale_reward = getattr(method, "scale_reward", None)
        if scale_reward == "running":
            return self.running_moments.std
        if scale_reward == "ref":
            return jnp.float32(max(self.ref_std, 1e-8))
        return jnp.float32(1.0)

    def _rollout_accounting_stats(
        self, response_ids, response_mask, gen_out, stats, iter_count,
    ) -> None:
        """Honest rollout accounting: pad emissions from finished rows
        are NOT generated tokens — report mask-weighted real tokens
        plus batch occupancy, and a truncation rate (rows that ran to
        max_new_tokens without an EOS: a degenerate policy that stops
        emitting EOS shows up here, and the guardrails can trip on it
        via truncation_max). Plus the decode-engine per-chunk ledger
        when ``gen_stats`` rode along."""
        rm_np = np.asarray(response_mask)
        ri_np = np.asarray(response_ids)
        N_resp = rm_np.shape[1]
        real_toks = float(rm_np.sum())
        stats["rollout/real_tokens"] = real_toks
        # flight recorder: the honest (mask-weighted) token ledger —
        # telemetry's tokens/s numerator reuses THIS number, so pad
        # emissions can never inflate the trajectory artifact
        self.obs.note_tokens(real_toks * mh.data_group_count(self.mesh))
        stats["rollout/token_occupancy"] = real_toks / max(
            rm_np.shape[0] * N_resp, 1
        )
        eos_id = self.generate_settings.eos_token_id
        full_rows = rm_np.sum(axis=1) >= N_resp
        hit_eos = (
            ((ri_np == eos_id) & (rm_np > 0)).any(axis=1)
            if eos_id >= 0
            else np.zeros(len(full_rows), bool)
        )
        stats["rollout/truncation_rate"] = (
            float((full_rows & ~hit_eos).mean()) if len(full_rows) else 0.0
        )
        gstats = gen_out.get("gen_stats")
        if gstats is not None:
            g = {k: float(np.asarray(v)) for k, v in gstats.items()}
            # per-refill heartbeat accounting (host-side,
            # post-dispatch): with the decode engine a chunk is ONE
            # device dispatch, so the refills all land at once —
            # batch them into a single annotated beat (count=N)
            # instead of N same-instant beats that would evict the
            # other phases from the watchdog's bounded timeline
            refills = int(g.get("refills", 0))
            if refills:
                self.watchdog.beat(
                    "rollout", step=iter_count, count=refills
                )
            stats["rollout/engine_occupancy"] = g.get("occupancy", 0.0)
            stats["rollout/engine_refills"] = g.get("refills", 0.0)
            stats["rollout/engine_decode_steps"] = g.get("decode_steps", 0.0)
            # prompt-pad page compaction: pages that held nothing but
            # left-pad KV, released back to the pool at refill (lowers
            # the engine's HBM floor on ragged prompt mixes)
            stats["rollout/engine_reclaimed_pages"] = g.get(
                "reclaimed_pages", 0.0
            )
            if "drafted" in g:
                stats["rollout/spec_accept_rate"] = g["accepted"] / max(
                    g["drafted"], 1.0
                )
            if g.get("oom_truncated") or g.get("unserved"):
                logger.warning(
                    "gen_engine: page pool exhausted (%d lanes "
                    "truncated, %d prompts unserved) — raise "
                    "method.gen_engine.pool_pages",
                    int(g.get("oom_truncated", 0)),
                    int(g.get("unserved", 0)),
                )

    # -- experience transport (method.exp.*) -----------------------------

    def _exp_snapshot(self) -> Dict[str, Any]:
        """Replay state for a production lease, taken BEFORE the chunk
        touches anything: the trainer RNG key and the host-side reward
        accounting (running moments, ref stats). jax arrays are
        immutable, so holding references is free; restoring them makes
        a re-dispatched production bit-identical to the original
        attempt (same key -> same samples, same moments -> same reward
        scaling), which is what lets a producer death leave the
        consumed stream untouched. (The prompt batch itself is stashed
        on the lease at pull time — ``snap["batch"]`` — so a replay
        never re-pulls the stream.)"""
        return {
            "rng": self.rng,
            "running_moments": self.running_moments,
            "ref_mean": self.ref_mean,
            "ref_std": self.ref_std,
        }

    def _exp_restore_snapshot(self, snap: Dict[str, Any]) -> None:
        self.rng = snap["rng"]
        self.running_moments = snap["running_moments"]
        self.ref_mean = snap["ref_mean"]
        self.ref_std = snap["ref_std"]

    def _exp_wait(self, iter_count: int):
        """Bounded-wait callback for transport waits (back-pressure,
        lease expiry): beat the ``exp_wait`` watchdog phase and sleep
        one poll — a genuinely wedged queue then trips the watchdog
        deadline instead of hanging undiagnosed."""
        import time as _time

        def wait(poll_s: float) -> None:
            self.watchdog.beat("exp_wait", step=iter_count)
            _time.sleep(poll_s)

        return wait

    def _exp_produce(self, lease, iter_count: int, clock: Clock) -> None:
        """Produce one chunk under ``lease`` and deliver it: pull the
        prompt chunk (or consume the cycle's overlap prefetch), sample,
        score+assemble, then offer to the queue with the lease's
        heartbeats at each milestone. Re-dispatched leases (attempt > 1
        or a staleness re-dispatch) restore the replay snapshot first,
        so the regenerated chunk is bit-identical to the lost one."""
        from time import time

        exp = self._exp
        snap = lease.meta if lease.meta is not None else {}
        lease.meta = snap
        if snap.get("rng") is not None:
            # no-op on a fresh attempt (the snapshot IS the live state);
            # on a re-dispatch it rewinds the producer-side effects so
            # the replay is bit-identical
            self._exp_restore_snapshot(snap)
        stats: Dict[str, float] = {}
        if snap.get("gen") is not None:
            # replaying a chunk originally produced from the cycle
            # prefetch: the generation (old params, old key) cannot be
            # re-run — redeliver the retained samples wholesale
            batch, gen_out, gen_time, version = snap["gen"]
        elif self._prefetched_gen is not None:
            batch, gen_out, gen_time = self._prefetched_gen
            self._prefetched_gen = None
            self._prefetch_cursor_start = None
            version = self._prefetch_policy_version
            snap["gen"] = (batch, gen_out, gen_time, version)
        else:
            batch = snap.get("batch")
            if batch is None:
                batch = self._next_prompt_batch()
                snap["batch"] = batch
            if self._fleet is not None and self._fleet_produce(
                lease, snap, batch, iter_count
            ):
                # produced + delivered by a fleet worker (the learner
                # adopted its post-production snapshot); the transport
                # consumer loop takes it from here
                return
            exp.heartbeat(lease)
            t0 = time()
            gen_out = self._generate_rollout(
                batch.input_ids, batch.attention_mask
            )
            gen_time = time() - t0
            version = self._policy_version
        stats["time/rollout_generate"] = gen_time
        exp.heartbeat(lease)
        rollout_batch, rows_local = self._score_and_assemble(
            batch, gen_out, stats, iter_count, clock
        )
        exp.heartbeat(lease)
        if self.chaos is not None and self.chaos.consult("stale_flood"):
            # chaos: the chunk's staleness metadata is corrupted — its
            # recorded generation version lands far behind the live
            # policy, so the admission gate must reject (or clip) it
            version = version - (self._exp_cfg.staleness.max_staleness + 10)
        if self.chaos is not None and self.chaos.consult("queue_wedge"):
            # chaos: the learner stops draining — the next offers see a
            # full queue and the bounded back-pressure wait must ride
            # it out under exp_wait heartbeats
            exp.wedge()
        payload = (rollout_batch, stats, rows_local)
        with self.watchdog.phase("exp_wait", step=iter_count):
            exp.deliver(
                lease, version, payload, meta={"snapshot": snap},
                wait=self._exp_wait(iter_count),
            )
            if self.chaos is not None and self.chaos.consult(
                "duplicate_delivery"
            ):
                # chaos: the producer's retry races its own success —
                # the same finished chunk is delivered twice; consumer
                # dedup must drop the redelivery
                exp.deliver(
                    lease, version, payload, meta={"snapshot": snap},
                    wait=self._exp_wait(iter_count),
                )

    # -- rollout fleet (method.fleet.*) ----------------------------------

    def _fleet_post_publish(self, path: str) -> None:
        """Chaos seam for ``broadcast_corrupt``: fired once per landed
        weight-snapshot publish, AFTER the atomic rename — only the
        workers' manifest verification can catch the flipped bit."""
        if self.chaos is not None and self.chaos.consult("broadcast_corrupt"):
            self.chaos.corrupt_broadcast(path)

    def _fleet_degrade(self, why: str) -> bool:
        """Record a healthy->degraded transition and trip the ``fleet``
        guardrail signal (once per transition — a long outage must not
        spam the escalation ladder). Always returns False so callers
        can ``return self._fleet_degrade(...)`` out of the fleet path."""
        if self._fleet.note_degraded(why):
            self.guardrails.trip(
                FLEET_SIGNAL,
                f"rollout fleet degraded: {why} — falling back to "
                "in-process production (bit-equal to the fleet-less run)",
            )
        return False

    def _fleet_ready(self, iter_count: int) -> bool:
        """Evict silent workers, then gate on ``fleet.min_workers``.
        The FIRST production waits out ``fleet.startup_timeout_s`` for
        the fleet to register (workers launch in parallel with the
        learner's compile, so "not there yet" is the common case) — a
        fleet that never comes up degrades instead of wedging the run."""
        import time as _time

        fleet, cfg = self._fleet, self._fleet_cfg
        deadline = (
            None if fleet._waited_startup
            else _time.time() + cfg.startup_timeout_s
        )
        fleet._waited_startup = True
        while True:
            fleet.registry.evict_silent()
            if len(fleet.live_workers()) >= cfg.min_workers:
                return True
            if deadline is None or _time.time() >= deadline:
                return False
            self.watchdog.beat("rollout", step=iter_count)
            _time.sleep(cfg.poll_s)

    def _fleet_produce(
        self, lease, snap: Dict[str, Any], batch, iter_count: int
    ) -> bool:
        """Produce the leased chunk on the worker fleet: publish the
        policy snapshot if due, dispatch the prompt batch + replay
        snapshot to a live worker, watch its membership heartbeats
        while it generates, and hand the delivered payload to the
        transport under the learner's own lease. A worker that goes
        silent mid-chunk is evicted and the chunk re-dispatched with
        the SAME snapshot (bit-identical regeneration). Returns False
        — after tripping the ``fleet`` signal once per transition —
        when the fleet is below ``min_workers`` (or a dispatch timed
        out); the caller then produces the chunk in-process from the
        same snapshot, so degradation is invisible in the loss stream."""
        import time as _time

        from trlx_tpu.fleet import serde as fleet_serde

        fleet, cfg, exp = self._fleet, self._fleet_cfg, self._exp
        if self.chaos is not None and self.chaos.consult("hub_crash"):
            # chaos: the transport hub dies and is relaunched EMPTY
            # before this production — workers re-register on their
            # next beat, this chunk's dispatch gets a fresh attempt
            # number, and any in-flight delivery re-posts through the
            # dedup
            fleet.crash_hub()
        # publish before the readiness gate: workers that are still
        # attaching need the snapshot to produce anything at all. But a
        # DEGRADED fleet with no registered workers at all has no
        # consumers — skip the full-model snapshot (host copy + npz +
        # sha256 + fsync per policy version) until a registration
        # reappears, or a dead fleet taxes every remaining cycle
        if not fleet.degraded or fleet.registry.worker_records():
            fleet.ensure_published(
                self._policy_version,
                lambda: fleet_serde.params_to_arrays(self.params),
                post_publish=self._fleet_post_publish,
            )
        if not self._fleet_ready(iter_count):
            return self._fleet_degrade(
                f"{len(fleet.live_workers())} live workers < "
                f"fleet.min_workers={cfg.min_workers}"
            )
        fleet.note_recovered()
        chunk_id = lease.chunk_id

        def degrade_dispatched(why: str) -> bool:
            # abandon the outstanding dispatch: a later-rejoining
            # evicted worker must not burn a generation on a chunk the
            # learner is about to produce in-process, and its late
            # delivery must not linger to collide with a future
            # regeneration of the same id. The lease goes back to the
            # learner — IT is the producer from here on, and expiry
            # logs should say so
            fleet.clear_chunk(chunk_id)
            exp.reassign(lease, exp.owner)
            return self._fleet_degrade(why)
        # a previous incarnation/attempt may have left a delivery for
        # this seq (learner restart, staleness re-dispatch): the replay
        # contract makes a same-snapshot leftover bit-identical, but a
        # staleness regeneration must NOT consume the old samples —
        # clear and regenerate, which is correct for both
        fleet.clear_chunk(chunk_id)
        arrays, prompt_meta = fleet_serde.prompt_batch_to_arrays(batch)
        # self state == the replay snapshot at this point (a re-dispatch
        # restored it at the top of _exp_produce), so the wire snapshot
        # is exactly what an in-process production would consume
        wire_meta = {
            "iter_count": int(iter_count),
            "snapshot": fleet_serde.snapshot_to_wire(self._exp_snapshot()),
            "prompt_metadata": prompt_meta,
        }
        tried: Tuple[str, ...] = ()
        worker = fleet.select_worker()
        if worker is None:
            return self._fleet_degrade("no dispatchable worker")
        attempt = fleet.next_attempt(chunk_id)
        valid_attempts = {attempt}
        exp.reassign(lease, worker)
        if not fleet.dispatch(chunk_id, attempt, worker, wire_meta, arrays):
            return degrade_dispatched(
                f"transport outage dispatching chunk {chunk_id}"
            )
        deadline = _time.time() + cfg.dispatch_timeout_s
        # delivery is polled every tick, but the membership scan
        # (dir listing + one JSON parse per worker record) only needs
        # the TTL's resolution — on a shared/remote filesystem the
        # difference is thousands of metadata reads per chunk
        scan_every = max(cfg.worker_ttl_s / 4.0, cfg.poll_s)
        next_scan = 0.0
        while True:
            self.watchdog.beat("rollout", step=iter_count)
            exp.heartbeat(lease)
            msg = fleet.poll_delivery(chunk_id)
            if msg is not None:
                if int(msg[0].get("attempt", -1)) in valid_attempts:
                    break
                # a lingering worker's late delivery from an attempt
                # ABANDONED before this production (a staleness
                # regeneration reuses the chunk id with a NEW snapshot):
                # consuming it would replay the exact payload the gate
                # refused. Drop the payload only — the outstanding
                # assignment stays so the current worker isn't stranded
                fleet.clear_delivery(chunk_id)
                msg = None
            if _time.time() >= next_scan:
                next_scan = _time.time() + scan_every
                fleet.registry.evict_silent()
                lost = worker not in fleet.live_workers()
            else:
                lost = False
            if lost:
                # the producing worker died / partitioned / got
                # quarantined mid-chunk: re-dispatch elsewhere with the
                # same snapshot (regeneration is bit-identical, so the
                # consumed stream never sees the loss)
                tried = tried + (worker,)
                if len(fleet.live_workers()) < cfg.min_workers:
                    return degrade_dispatched(
                        f"worker {worker!r} lost mid-chunk {chunk_id} "
                        "and the live fleet fell below min_workers"
                    )
                worker = (
                    fleet.select_worker(exclude=tried)
                    or fleet.select_worker()  # all live ones tried: retry the set
                )
                if worker is None:
                    return degrade_dispatched(
                        f"no dispatchable worker for chunk {chunk_id}"
                    )
                attempt = fleet.next_attempt(chunk_id)
                valid_attempts.add(attempt)
                exp.reassign(lease, worker)
                if not fleet.dispatch(
                    chunk_id, attempt, worker, wire_meta, arrays
                ):
                    return degrade_dispatched(
                        f"transport outage re-dispatching chunk {chunk_id}"
                    )
                deadline = _time.time() + cfg.dispatch_timeout_s
                continue
            if _time.time() >= deadline:
                # alive-but-wedged worker: the membership TTL never
                # fires, so this bound is the backstop. Evict (flap-
                # tracked) and degrade; the in-process regeneration is
                # bit-identical via the replay snapshot.
                fleet.registry.evict(
                    worker,
                    f"dispatch timeout: chunk {chunk_id} undelivered "
                    f"after {cfg.dispatch_timeout_s:g}s",
                )
                return degrade_dispatched(
                    f"chunk {chunk_id} timed out on worker {worker!r}"
                )
            _time.sleep(cfg.poll_s)
        meta_d, arrays_d = msg
        # a consumed delivery breaks the producing worker's eviction
        # streak — flap quarantine means consecutive evictions, not
        # cumulative-forever
        fleet.registry.note_healthy(str(meta_d.get("worker", "")))
        rollout_batch = fleet_serde.rollout_from_arrays(arrays_d)
        stats: Dict[str, Any] = dict(meta_d.get("stats") or {})
        rows_local = int(meta_d["rows_local"])
        version = int(meta_d["policy_version"])
        # adopt the worker's post-production snapshot: the learner's
        # RNG/moments chain continues exactly as if it had produced the
        # chunk in-process — that adoption is what keeps the fleet path
        # bit-equal to method.exp.enabled
        self._exp_restore_snapshot(
            fleet_serde.snapshot_from_wire(meta_d["post_snapshot"], self.rng)
        )
        exp.heartbeat(lease)
        with self.watchdog.phase("exp_wait", step=iter_count):
            exp.deliver(
                lease, version, (rollout_batch, stats, rows_local),
                meta={"snapshot": snap}, wait=self._exp_wait(iter_count),
            )
        fleet.clear_chunk(chunk_id)
        return True

    def _shutdown_producers(self) -> None:
        """learn()-exit hook: write the fleet's clean-finish flag ONLY
        when the step budget is actually done — a preemption / stall /
        crash exit leaves the workers alive for the relaunched
        learner's membership-epoch re-attach handshake."""
        if self._fleet is None:
            return
        total = getattr(self, "total_steps", None)
        budget = self.config.train.total_steps if total is None else total
        if self.iter_count >= budget:
            self._fleet.shutdown("train budget reached")
            logger.info(
                "fleet: clean finish — %s", self._fleet.stats_summary()
            )
        else:
            logger.info(
                "fleet: learner exiting at step %d < %d with the fleet "
                "left ATTACHED (workers re-register on the relaunch's "
                "membership epoch)", self.iter_count, budget,
            )

    def _make_experience_exp(self, num_rollouts: int, iter_count: int) -> None:
        """The experience-transport rollout loop: the in-process trainer
        acting as the first producer/consumer pair behind the leased
        queue (ROADMAP item 1's remote rollout fleet plugs in behind
        the same seam). Fault-free it is bit-equal to the direct loop:
        the same prompt pulls, the same RNG splits per generate, the
        same score math (shared ``_score_and_assemble``), consumed in
        the same order (the queue is in-order by construction)."""
        import time as _time

        logger.info("Collecting rollouts (experience transport)")
        self._rollout_abandoned = False
        exp = self._exp
        prompt_cursor_start = (
            self._prefetch_cursor_start
            if self._prefetched_gen is not None
            else self._prompt_batches_consumed
        )
        self._cycle_cursor_start = prompt_cursor_start
        self._finish_rollout_stats()
        clock = Clock()
        n_collected = 0
        accumulated_stats: List[Dict[str, float]] = []
        pbar = logging.progress(total=num_rollouts, desc="rollouts")
        scfg = self._exp_cfg.staleness
        pending_redispatch = None  # a reclaimed/re-leased chunk to produce
        while n_collected < num_rollouts:
            self.watchdog.beat("rollout", step=iter_count)
            # lane-refill decision point (transport path): serve
            # requests outrank the next produce/consume step
            self._serve_tick(iter_count)
            if self.chaos is not None:
                # chaos: same wedge site as the direct loop — the
                # producer stalls at the top of a chunk and the
                # watchdog deadline must end the run
                self.chaos.stall("stall_rollout")
            if self._should_stop(force=True):
                logger.warning(
                    "preemption during rollout collection: abandoning "
                    "after %d/%d rollouts", n_collected, num_rollouts,
                )
                self._rollout_abandoned = True
                self._prompt_batches_consumed = prompt_cursor_start
                # in-flight chunks and leases never train: void them so
                # the resumed run's replayed prompts produce fresh
                # chunks under a new epoch
                exp.abort_epoch()
                break
            chunk = exp.poll()
            if chunk is None:
                lease = pending_redispatch
                pending_redispatch = None
                if lease is None:
                    gap = exp.queue.next_undelivered()
                    gap_lease = exp.leases.get((exp.queue.epoch, gap))
                    if gap_lease is not None:
                        # the next in-order chunk is leased but not
                        # delivered: its producer died (or is slow).
                        # Wait out the lease TTL under the exp_wait
                        # phase, then reclaim + re-dispatch.
                        with self.watchdog.phase("exp_wait", step=iter_count):
                            while True:
                                reclaimed = exp.reclaim_expired()
                                if reclaimed:
                                    lease = reclaimed[0]
                                    break
                                self.watchdog.beat(
                                    "exp_wait", step=iter_count
                                )
                                _time.sleep(self._exp_cfg.wait_poll_s)
                    else:
                        lease = exp.begin_chunk(snapshot=self._exp_snapshot())
                        if self.chaos is not None and self.chaos.consult(
                            "worker_death_mid_lease"
                        ):
                            # chaos: the producer dies right after
                            # taking the lease — before any side
                            # effect. Heartbeats stop; the consumer
                            # loop above waits out the TTL and
                            # re-dispatches the chunk.
                            exp.producer_died(lease)
                            continue
                self._exp_produce(lease, iter_count, clock)
                continue
            verdict, staleness = exp.admit(chunk, self._policy_version)
            if staleness > scfg.max_staleness and self.guardrails.enabled:
                self.guardrails.trip(
                    STALENESS_SIGNAL,
                    f"chunk {chunk.chunk_id} is {staleness} policy "
                    f"versions stale (> max {scfg.max_staleness}; "
                    f"verdict: {verdict}) — the rollout producers are "
                    "falling behind the learner",
                )
            if verdict == exp_transport.REJECT:
                # over-stale: drop the delivery and regenerate the
                # chunk's prompts with the current policy (the replay
                # snapshot keeps the regeneration deterministic). A
                # chunk born from the cycle prefetch retains its old
                # samples in snap["gen"] for lost-delivery replay —
                # but a staleness reject must NOT redeliver those
                # verbatim (same samples, same version -> an infinite
                # reject/redeliver loop): strip the retained
                # generation, keep its prompt batch, so the produce
                # path re-samples with the live policy and stamps the
                # live version
                snap = chunk.meta.get("snapshot")
                if snap is not None and snap.get("gen") is not None:
                    snap["batch"] = snap["gen"][0]
                    snap["gen"] = None
                pending_redispatch = exp.redispatch_rejected(chunk)
                continue
            rollout_batch, stats, rows_local = chunk.payload
            if verdict == exp_transport.ADMIT_CLIP:
                rollout_batch = self._apply_staleness_clip(rollout_batch)
                stats["exp/staleness_clipped"] = 1.0
            elif scfg.mode == "clip":
                # uniform store pytree structure: every batch of a
                # clip-mode run carries weights (fresh chunks at 1)
                rollout_batch = rollout_batch.replace(
                    is_weight=jnp.ones_like(rollout_batch.response_mask)
                )
            stats["exp/staleness"] = float(staleness)
            self.push_to_store(rollout_batch)
            exp.committed(chunk)
            accumulated_stats.append(stats)
            n_collected += rows_local * mh.data_group_count(self.mesh)
            if hasattr(pbar, "update"):
                pbar.update(rows_local * mh.data_group_count(self.mesh))
            logger.info("[rollout %d / %d]", n_collected, num_rollouts)

        # same samples accounting as the direct loop (one definition of
        # n_collected feeds both the store and the telemetry headline)
        self.obs.note_samples(n_collected)
        if not accumulated_stats:
            if hasattr(pbar, "close"):
                pbar.close()
            return
        # aggregate over the UNION of keys: conditional keys (a clip
        # admission mid-cycle) must not vanish just because the final
        # chunk was fresh — that telemetry is exactly what the
        # staleness ledger exists to surface
        all_keys = [k for xs in accumulated_stats for k in xs]
        agg = {
            k: sum(xs.get(k, 0.0) for xs in accumulated_stats) / len(accumulated_stats)
            for k in dict.fromkeys(all_keys)
        }
        # transport health ledger rides the same deferred stage as the
        # rollout stats (host ints — free)
        agg.update({
            f"exp/{k}": float(v)
            for k, v in exp.stats_summary().items()
            if isinstance(v, (int, float))
        })
        if self._fleet is not None:
            # fleet health rides the same ledger: dispatches/evictions/
            # quarantines/degradations per cycle, all host ints
            agg.update({
                f"fleet/{k}": float(v)
                for k, v in self._fleet.stats_summary().items()
                if isinstance(v, (int, float))
            })
        if hasattr(pbar, "close"):
            pbar.close()
        self._deferred_rollout.stage(
            agg, step=iter_count, meta=self._rollout_stage_meta()
        )

    def _extra_consistency_checks(self) -> None:
        """Every host must hold the SAME experience-transport consumer
        cursor — a drifted cursor means hosts silently trained
        different chunks. Asserted through ``multihost.cursor_consensus``
        at the guardrails consistency cadence; disagreement trips the
        ladder like any other divergence."""
        if self._exp is None or not self.guardrails.enabled:
            return
        result = mh.cursor_consensus(
            "exp", self._exp.queue.epoch, self._exp.queue.cursor
        )
        if not result.agree:
            self.guardrails.trip(
                "consistency",
                f"experience-transport cursor diverged at step "
                f"{self.iter_count}: {result.detail}",
            )

    def _finish_rollout_stats(self) -> None:
        """Materialize + log the deferred make_experience stats, feeding
        the guardrails the rollout-side health signals. Trainers with
        controller state riding the flush (PPO's adaptive KL) override.
        Idempotent."""
        for stats, step, meta in self._deferred_rollout.flush():
            if meta is not None:
                stats["kl_coef"] = float(meta)
            if self.guardrails.enabled:
                kl = stats.get("policy/sqrt_kl")
                self.guardrails.observe_rollout(
                    kl=None if kl is None else float(kl) ** 2,
                    kl_target=None,
                    reward_mean=stats.get("rollout_scores/mean"),
                    running_mean=stats.get("rollout_scores/running_mean"),
                    running_std=stats.get("rollout_scores/running_std"),
                    truncation_rate=stats.get("rollout/truncation_rate"),
                )
            self._tracker_log(stats, step=step)

    # -- loop hooks ------------------------------------------------------

    def setup_rollout_logging(self, config) -> None:
        import uuid

        assert os.path.isdir(config.train.rollout_logging_dir)
        self.run_id = f"run-{uuid.uuid4()}"
        self.rollout_logging_dir = os.path.join(
            config.train.rollout_logging_dir, self.run_id
        )
        os.mkdir(self.rollout_logging_dir)
        with open(os.path.join(self.rollout_logging_dir, "config.json"), "w") as f:
            f.write(json.dumps(config.to_dict(), indent=2))

    def add_prompt_pipeline(self, pipeline) -> None:
        # the pipeline is retained so guardrail interventions (requeue /
        # rollback) can rebuild the stream and replay untrained prompts
        self._prompt_pipeline = pipeline
        self._build_prompt_iterator()
        self._fast_forward_prompts()

    def _prompt_chunk_rows(self) -> int:
        """Prompts pulled from the stream per chunk (GRPO pulls
        chunk_size/group_size prompts and repeats each one)."""
        return self.config.method.chunk_size

    def _build_prompt_iterator(self) -> None:
        """(Re)create the prompt stream from position zero. The loader
        draws its shuffles from the config seed, so a rebuild replays
        the exact chunk sequence — fast-forwarding then restores any
        cursor, including one BEHIND the live position (streams only
        advance; rewind = rebuild + replay).

        TOPOLOGY-INVARIANT: the stream is one GLOBAL shuffle over the
        full prompt list, chunked at the global chunk_size; each data
        group then collates only its own rows of every global chunk
        (`_GroupChunkLoader`). The chunk sequence — and therefore the
        saved `prompt_batches_consumed` cursor — means the SAME prompts
        regardless of how many hosts/data groups the run has, so an
        elastic resume onto a different topology neither drops nor
        double-trains a prompt. (The previous scheme shuffled each
        group's strided slice independently, which re-partitioned the
        stream whenever the group count changed.) Single-group runs are
        byte-identical to the old behavior: same loader, same RNG
        stream, no slicing."""
        pipeline = self._prompt_pipeline
        # drop_last keeps chunk shapes static: one compiled sampler;
        # a prompt list smaller than one chunk degrades to a single
        # kept-ragged chunk (the historical len(loader)==0 fallback)
        chunk, drop_last = self._prompt_chunk_rows(), True
        if len(pipeline) < chunk:
            chunk, drop_last = len(pipeline), False
        group, group_count = mh.data_group_info(self.mesh)
        if group_count > 1:
            loader = _GroupChunkLoader(
                pipeline, chunk, pipeline.collate, group, group_count,
                seed=self.config.train.seed, drop_last=drop_last,
            )
        else:
            loader = pipeline.create_loader(
                chunk, shuffle=True, drop_last=drop_last,
                seed=self.config.train.seed,
            )
        self.prompt_iterator = infinite_loader(loader)
        self._prompt_batches_consumed = 0

    def _rewind_prompt_stream(self, cursor: int) -> None:
        """Rebuild the stream and advance it so the NEXT pull is chunk
        ``cursor`` — the replay path for prompts whose rollouts never
        trained (host-side batch pulls only: no generation, no scoring)."""
        self._build_prompt_iterator()
        for _ in range(cursor):
            next(self.prompt_iterator)
        self._prompt_batches_consumed = cursor

    def _reset_data_stream(self) -> None:
        """Guardrail-rollback hook: stream back to zero; the subsequent
        load() fast-forwards to the checkpoint's saved cursor."""
        if getattr(self, "_prompt_pipeline", None) is None:
            return
        self._resume_prompt_cursor = 0
        if self._exp is not None:
            # in-flight transport chunks belong to the discarded live
            # state; the load() that follows restores the committed
            # cursor on top of the bumped epoch
            self._exp.abort_epoch()
        self._build_prompt_iterator()

    def _requeue_poisoned_batch(self) -> bool:
        """Guardrail `requeue` rung: drop the poisoned rollout store and
        rewind the prompt stream to the cycle start, so the same prompts
        are re-collected with the CURRENT policy (their poisoned
        rollouts never train; recomputed importance ratios make the
        replay sound — IMPACT, arXiv:1912.00167)."""
        start = getattr(self, "_cycle_cursor_start", None)
        if len(self.store) == 0 or start is None:
            return False
        self._abandon_prefetch()
        if self._exp is not None:
            # the rebuilt stream replays this cycle's prompts: void the
            # transport's in-flight chunks/leases under a new epoch so
            # an old delivery can never shadow a replayed one
            self._exp.abort_epoch()
        self.store.clear_history()
        self._rewind_prompt_stream(start)
        logger.warning(
            "guardrails: discarded the poisoned rollout batch; prompt "
            "stream rewound to chunk %d for replay", start,
        )
        return True

    def _reward_fallback_value(self) -> float:
        """`resilient_io.fallback_reward: hold_mean` — substitute the
        running-moments mean while the reward service is down, keeping
        the reward distribution stationary instead of injecting zeros."""
        try:
            v = float(np.asarray(self.running_moments.mean))
        except Exception:
            return 0.0
        return v if np.isfinite(v) else 0.0

    def _next_prompt_batch(self) -> PromptBatch:
        batch = next(self.prompt_iterator)
        self._prompt_batches_consumed += 1
        return batch

    # -- cross-cycle rollout prefetch (method.overlap_rollouts) ----------

    def pre_optimization_hook(self, will_continue: bool) -> None:
        """Dispatch the FIRST chunk of the next cycle's generation ahead
        of the fused optimization block, with the pre-update params.
        Device FIFO runs the generation before the train scan — whose
        buffer donation invalidates these params for any LATER dispatch
        — and the host decodes+scores the chunk while the block trains.
        The samples are one policy update stale, which the clipped
        surrogate absorbs: the teacher-forced scorer recomputes
        old_logprobs with the updated params when the chunk is
        consumed, so the ratio stays self-consistent with the
        optimization epoch's start."""
        from time import time

        if not self.config.method.overlap_rollouts or not will_continue:
            return
        if self._prefetched_gen is not None or not hasattr(self, "prompt_iterator"):
            return
        cursor0 = self._prompt_batches_consumed
        batch = self._next_prompt_batch()
        t0 = time()
        with self.watchdog.phase("rollout", step=self.iter_count):
            gen = self._generate_rollout(batch.input_ids, batch.attention_mask)
        self._prefetched_gen = (batch, gen, time() - t0)
        self._prefetch_cursor_start = cursor0
        # staleness metadata: the prefetched chunk's samples belong to
        # the PRE-update policy — it is consumed one optimizer cycle
        # later at exactly staleness 1 (which the admission gate's
        # default max_staleness admits untouched)
        self._prefetch_policy_version = self._policy_version

    def _abandon_prefetch(self) -> None:
        """Drop an in-flight prefetched chunk and rewind the prompt
        cursor: its rollouts never train (run ending / preempted), so a
        resumed run must replay those prompts."""
        if self._prefetched_gen is None:
            return
        self._prefetched_gen = None
        self._prompt_batches_consumed = self._prefetch_cursor_start
        self._prefetch_cursor_start = None

    def _fast_forward_prompts(self) -> None:
        """Resume: advance the prompt stream to the saved cursor. The
        loader's shuffle RNG is stateful per epoch, so replaying `skip`
        host-side batch pulls (cheap: pre-tokenized collation, no
        generation) reproduces the exact data order the killed run would
        have continued with."""
        skip = self._resume_prompt_cursor - self._prompt_batches_consumed
        if skip <= 0 or not hasattr(self, "prompt_iterator"):
            return
        logger.info(
            "resume: fast-forwarding the prompt stream by %d chunks to "
            "restore the rollout data order", skip,
        )
        for _ in range(skip):
            next(self.prompt_iterator)
        self._prompt_batches_consumed += skip

    def _extra_fingerprint(self):
        """Consistency-watchdog extras: the rollout-data cursor (host-
        side online-trainer state that MUST advance in lockstep across
        hosts — a drifted cursor silently trains different prompts per
        host); subclasses layer their controller state on top."""
        out = {
            "prompt_cursor": float(self._prompt_batches_consumed),
        }
        if self._exp is not None:
            # the transport's committed consumer position must advance
            # in lockstep too (a drifted cursor = hosts training
            # different chunks); also asserted dedicatedly through
            # multihost.cursor_consensus in _extra_consistency_checks
            out["exp_epoch"] = float(self._exp.queue.epoch)
            out["exp_cursor"] = float(self._exp.queue.cursor)
        return out

    # -- resumable state -------------------------------------------------

    def _extra_state(self):
        rm = self.running_moments
        state = {
            "ref_mean": None if self.ref_mean is None else float(self.ref_mean),
            "ref_std": None if self.ref_std is None else float(self.ref_std),
            "running_moments": {
                "mean": float(rm.mean), "var": float(rm.var),
                "std": float(rm.std), "count": float(rm.count),
            },
            # an in-flight prefetched chunk has NOT trained: persist the
            # cursor from before its pull, so a resume from this
            # checkpoint replays those prompts instead of skipping them
            "prompt_batches_consumed": (
                self._prefetch_cursor_start
                if self._prefetched_gen is not None
                else self._prompt_batches_consumed
            ),
            # the cursor counts GLOBAL chunks of the topology-invariant
            # stream (this marker lets a restore distinguish cursors
            # saved under the old per-group-shuffle scheme)
            "prompt_stream": "global-chunks-v1",
        }
        if self._exp is not None:
            # the experience-transport consumer cursor, committed INSIDE
            # the atomic checkpoint (state.json rides the integrity
            # manifest): a resume replays exactly the unconsumed chunks
            # — produced-but-unconsumed ones regenerate from the
            # group-invariant prompt stream. Invariant (verify_ckpt.py's
            # torn-commit detector): cursor <= prompt_batches_consumed,
            # every committed chunk consumed a prompt pull.
            state["exp_queue"] = {
                **self._exp.state_dict(),
                "policy_version": self._policy_version,
                "staleness_mode": self._exp_cfg.staleness.mode,
            }
        if self._fleet is not None:
            # membership epoch + last broadcast version, committed by
            # the SAME atomic state.json write as the exp cursor —
            # verify_ckpt.py's torn-commit detector holds the pair to
            # the publish-cadence invariant (a cursor referencing a
            # policy the committed snapshot never broadcast is torn)
            state["fleet"] = self._fleet.state()
        return state

    def _restore_extra_state(self, state) -> None:
        from trlx_tpu.ops.common import RunningMoments

        self.ref_mean = state.get("ref_mean", self.ref_mean)
        self.ref_std = state.get("ref_std", self.ref_std)
        rm = state.get("running_moments")
        if rm:
            self.running_moments = RunningMoments(
                mean=jnp.float32(rm["mean"]), var=jnp.float32(rm["var"]),
                std=jnp.float32(rm["std"]), count=jnp.float32(rm["count"]),
            )
        eq = state.get("exp_queue")
        if eq and self._exp is not None:
            self._exp.load_state_dict(eq)
            self._policy_version = int(eq.get("policy_version", 0))
        if self._fleet is not None:
            # the restore may have moved _policy_version backwards
            # (rollback): drop the publish cursor so the next cycle
            # rebroadcasts the restored params — otherwise workers keep
            # the rolled-back-over weights and their chunks admit as
            # non-stale (generation version ahead of the learner's)
            self._fleet.reset_published()
        self._resume_prompt_cursor = state.get("prompt_batches_consumed", 0)
        if (
            self._resume_prompt_cursor
            and state.get("prompt_stream") != "global-chunks-v1"
            and mh.data_group_count(self.mesh) > 1
        ):
            # pre-elastic multihost checkpoints counted chunks of
            # per-group shuffled streams; the invariant stream replays
            # a (deterministic) different partitioning from the same
            # cursor — continue, but say so
            logger.warning(
                "restored prompt cursor %d predates the "
                "topology-invariant stream: the replayed chunk "
                "composition differs from the saving run's on multi-"
                "group meshes", self._resume_prompt_cursor,
            )
        self._fast_forward_prompts()

    def prepare_learning(self) -> None:
        self.eval_dataloader = mh.shard_pipeline(self.eval_pipeline, self.mesh).create_loader(
            max(self.config.method.chunk_size // mh.data_group_count(self.mesh), 1)
        )
        # the restored iter_count keys the deferred rollout-stats flush:
        # without it a resumed run logs its first rollout at step 0 and
        # breaks tracker-step monotonicity
        self.make_experience(self.config.method.num_rollouts, self.iter_count)
        self.n_inner_epochs = self._inner_epochs()
        n_batches = len(self.store) // self.config.train.batch_size
        self.total_steps = min(
            self.config.train.epochs * self.n_inner_epochs * max(n_batches, 1),
            self.config.train.total_steps,
        )

    def create_train_dataloader(self):
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True, drop_last=True,
            seed=self.config.train.seed + self.iter_count,
        )

    def post_backward_callback(self) -> None:
        # flush the deferred rollout stats (by now the async device->
        # host copy has landed under the train step: a free read)
        self._finish_rollout_stats()

    def _fused_epoch_batch(self):
        # the rollout store is a rectangular (device-resident) pytree:
        # the whole inner-epochs x minibatch loop can run as one fused scan
        return self.store.fused_epoch_source()

    def post_epoch_callback(self) -> None:
        if self.log_rollouts:
            self.store.export_history(self.rollout_logging_dir, self.tokenizer)
        self.store.clear_history()
        self.make_experience(self.config.method.num_rollouts, self.iter_count)


# ---------------------------------------------------------------------------
# update masking (layer freezing)
# ---------------------------------------------------------------------------


def _mask_updates(mask_tree) -> optax.GradientTransformation:
    """Multiply updates elementwise by a broadcastable {0,1} mask."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        masked = jax.tree_util.tree_map(
            lambda u, m: u * jnp.asarray(m, u.dtype), updates, mask_tree
        )
        return masked, state

    return optax.GradientTransformation(init_fn, update_fn)
