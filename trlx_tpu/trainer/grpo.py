"""GRPO trainer: critic-free online preference RL on the shared
experience core (Group Relative Policy Optimization, arXiv:2402.03300).

The rollout ENGINE — prompt stream + cursors, chunked generate() with
one-chunk lookahead, cross-cycle prefetch (`method.overlap_rollouts`),
the decode engine (`method.gen_engine.*`), experience transport
(`method.exp.*`) and rollout fleet (`method.fleet.*`) — is inherited
verbatim from `trainer.base.TPUOnlineTrainer`; this module contributes
only what is GRPO:

- the PROMPT TILING: each chunk pulls ``chunk_size / group_size``
  prompts off the shared stream and repeats each one ``group_size``
  times, so a group's N samples are consecutive rows of one chunk
  (sampler RNG is per-row, so the repeats decode differently);
- the score/assemble seam: teacher-forced policy+reference logprob
  forward (NO value head, no value forward), host reward scoring, and
  per-group reward z-scores as sequence-level advantages
  (ops/grpo.py `group_relative_advantages`);
- the loss: PPO's clipped surrogate with the group advantage and an
  in-loss KL regularizer against the frozen reference
  (ops/grpo.py `grpo_loss`) — no value loss, and the optimizer carries
  no critic state because there is no critic to carry;
- the IMPACT-style staleness clip recompute for the transport's
  ``exp.staleness.mode: clip`` admission.

Relative to PPO this halves the method-specific train-phase state: the
rollout store drops the `values`/`rewards` columns for one advantage
scalar per row, and the loss runs one policy forward instead of
policy+value(+GAE).
"""

from __future__ import annotations

from time import time
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data import GRPORolloutBatch, PromptBatch
from trlx_tpu.data.method_configs import GRPOConfig
from trlx_tpu.models.transformer import logit_projection
from trlx_tpu.models.wrappers import CausalLM
from trlx_tpu.ops.common import chunked_logprobs, logprobs_of_labels
from trlx_tpu.ops.grpo import group_relative_advantages, grpo_loss
from trlx_tpu.ops.remat import resolve_remat
from trlx_tpu.parallel import data_sharding, shard_params
from trlx_tpu.parallel import multihost as mh
from trlx_tpu.parallel.mesh import replicated_sharding, vector_sharding
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUOnlineTrainer
from trlx_tpu.trainer.ppo import _masked_kl_stats
from trlx_tpu.utils import Clock, logging

logger = logging.get_logger(__name__)


@register_trainer("TPUGRPOTrainer")
class TPUGRPOTrainer(TPUOnlineTrainer):
    def __init__(self, config, **kwargs):
        if not isinstance(config.method, GRPOConfig):
            raise ValueError("config.method must be GRPOConfig")
        super().__init__(config, **kwargs)
        if not config.method.gen_kwargs.get("do_sample", False):
            # a greedy group is group_size identical completions: every
            # group degenerates to zero advantage and nothing trains
            logger.warning(
                "grpo.gen_kwargs.do_sample is off — identical group "
                "members give zero group-relative advantage; enable "
                "sampling for GRPO to learn anything"
            )
        self._experience_fns: Dict[Any, Any] = {}

    def _drop_traced_fns(self) -> None:
        # the teacher-forced experience fns trace train.remat_policy in
        # — the memory doctor's remat escalation must retrace them
        super()._drop_traced_fns()
        self._experience_fns.clear()

    def _pre_accum_batch(self, batch):
        """Split-microbatch compensation for GRPO: no whitening to
        precompute (advantages are stored per-sequence), but the
        loss's mask-count normalizer is batch-coupled — fix it to
        full_total/num_mb so a doctor split reproduces the unsplit
        normalization exactly with ragged masks (same contract as
        PPO's hook)."""
        if self.memdoctor.accum_factor <= 1 or not isinstance(
            batch, GRPORolloutBatch
        ):
            return batch
        rows = batch.response_mask.shape[0]
        norm = jnp.full(
            (rows,),
            batch.response_mask.astype(jnp.float32).sum() / self.num_mb,
            jnp.float32,
        )
        return batch.replace(norm_n=norm)

    # -- model -----------------------------------------------------------

    def setup_model(self) -> None:
        if self.config.model.model_arch_type == "seq2seq":
            raise NotImplementedError("seq2seq GRPO is not implemented (causal only)")
        self.seq2seq = False
        cfg, base_params, self.model_type = self.load_base_model()
        self.model = CausalLM(cfg)
        self.rng, key = jax.random.split(self.rng)
        params = self.model.init_params(key, base_params)
        params.update(getattr(self, "_loaded_aux", None) or {})
        params = self.attach_peft(params)
        self.params = shard_params(self.mesh, params)
        # frozen reference for the in-loss KL: the initial policy's base
        # tree, DEEP-COPIED — the train step donates self.params buffers
        # every step, so the reference must not alias them. (With LoRA
        # the adapter-disabled base is the reference, peft convention.)
        self.ref_params = jax.tree_util.tree_map(jnp.copy, self.params["base"])

    def trainable_mask(self):
        return self.lora_freeze_mask(self.params) or self.make_freeze_mask(self.params)

    # -- loss ------------------------------------------------------------

    def loss(self, params, batch: GRPORolloutBatch):
        """Recompute policy logprobs on stored rollouts; clipped
        surrogate on the stored group advantage + in-loss reference KL.
        One forward — no value head, no GAE, no value loss."""
        method = self.config.method
        pad = self.generate_settings.pad_token_id
        remat = resolve_remat(self.config.train.remat_policy)
        chunks = self.config.train.logit_chunks
        P = batch.query_tensors.shape[1]
        N = batch.response_tensors.shape[1]
        tokens = jnp.concatenate([batch.query_tensors, batch.response_tensors], axis=1)
        attention_mask = (tokens != pad).astype(jnp.int32)
        # response positions count even where response==pad (mask handles it)
        attention_mask = attention_mask.at[:, P:].set(
            jnp.maximum(attention_mask[:, P:], batch.response_mask.astype(jnp.int32))
        )
        out = self.model.forward(
            params, tokens, attention_mask, remat=remat, compute_logits=chunks == 0
        )
        if chunks:
            logprobs = chunked_logprobs(
                self.model.logit_project_fn(params),
                out["hidden_states"][:, P - 1 : P + N - 1],
                tokens[:, P : P + N], chunks,
            )
        else:
            logprobs = logprobs_of_labels(
                out["logits"][:, P - 1 : P + N - 1], tokens[:, P : P + N]
            )
        return grpo_loss(
            logprobs=logprobs,
            old_logprobs=batch.logprobs,
            ref_logprobs=batch.ref_logprobs,
            advantages=batch.advantages,
            mask=batch.response_mask,
            cliprange=method.cliprange,
            kl_coef=method.kl_coef,
            # experience-transport staleness correction (exp.staleness.
            # mode: clip); None on every other path = weight 1
            is_weight=batch.is_weight,
            # split-microbatch normalizer compensation (_pre_accum_batch)
            norm_n=None if batch.norm_n is None else batch.norm_n[0],
        )

    # -- the method-specific score/assemble seam -------------------------

    def _inner_epochs(self) -> int:
        return self.config.method.grpo_epochs

    def _prompt_chunk_rows(self) -> int:
        # the stream yields PROMPTS; tiling to group_size samples per
        # prompt happens in _next_prompt_batch, so one chunk of the
        # stream is chunk_size/group_size prompts = chunk_size samples
        return self.config.method.chunk_size // self.config.method.group_size

    def _next_prompt_batch(self) -> PromptBatch:
        """Pull one chunk of prompts and tile each ``group_size`` times:
        a group's members are consecutive rows, local to this data
        group (the z-score baseline never crosses hosts). The sampler's
        RNG is per-row, so identical tiled prompts decode into
        different completions."""
        batch = super()._next_prompt_batch()
        gs = self.config.method.group_size
        metadata = None
        if batch.metadata:
            metadata = {
                k: [x for x in v for _ in range(gs)]
                for k, v in batch.metadata.items()
            }
        return PromptBatch(
            input_ids=np.repeat(np.asarray(batch.input_ids), gs, axis=0),
            attention_mask=np.repeat(np.asarray(batch.attention_mask), gs, axis=0),
            metadata=metadata,
        )

    def _get_experience_fwd_fn(self, P: int, N: int):
        """Jitted score-independent half of the experience step:
        teacher-forced policy AND frozen-reference logprob forward (no
        value head) + per-token KL stats. Dispatched right after
        generation so it overlaps decode + reward_fn, exactly like
        PPO's fast path; the advantage injection completes the batch
        once the host scores return."""
        key = ("fwd", P, N, self.config.train.logit_chunks)
        if key in self._experience_fns:
            return self._experience_fns[key]
        model = self.model
        chunks = self.config.train.logit_chunks

        def fn(params, ref_params, tokens, attention_mask, response_mask, row_valid):
            out = model.forward(
                params, tokens, attention_mask, compute_logits=chunks == 0
            )
            ref_out = model.lm(
                ref_params, tokens, attention_mask, compute_logits=chunks == 0
            )
            if chunks:
                logprobs_full = chunked_logprobs(
                    model.logit_project_fn(params),
                    out["hidden_states"][:, :-1], tokens[:, 1:], chunks,
                )
                ref_logprobs_full = chunked_logprobs(
                    logit_projection(ref_params),
                    ref_out["hidden_states"][:, :-1], tokens[:, 1:], chunks,
                )
            else:
                logprobs_full = logprobs_of_labels(out["logits"][:, :-1], tokens[:, 1:])
                ref_logprobs_full = logprobs_of_labels(
                    ref_out["logits"][:, :-1], tokens[:, 1:]
                )

            full_mask = attention_mask[:, 1:].astype(jnp.float32)
            log_ratio_full = (logprobs_full - ref_logprobs_full) * full_mask
            kl = jnp.exp(log_ratio_full) - 1 - log_ratio_full
            mean_kl, mean_kl_per_token = _masked_kl_stats(kl, row_valid)

            mask = response_mask.astype(jnp.float32)
            sl = slice(P - 1, P + N - 1)
            batch_out = GRPORolloutBatch(
                query_tensors=tokens[:, :P],
                response_tensors=tokens[:, P:],
                logprobs=logprobs_full[:, sl] * mask,
                ref_logprobs=ref_logprobs_full[:, sl] * mask,
                # advantages injected once the host scores return
                advantages=jnp.zeros((tokens.shape[0],), jnp.float32),
                response_mask=mask,
            )
            return batch_out, {
                "mean_kl": mean_kl, "mean_kl_per_token": mean_kl_per_token,
            }

        self._experience_fns[key] = jax.jit(fn)
        return self._experience_fns[key]

    def _get_adv_inject_fn(self):
        key = "adv_inject"
        if key not in self._experience_fns:
            self._experience_fns[key] = jax.jit(
                lambda batch, adv: batch.replace(advantages=adv)
            )
        return self._experience_fns[key]

    def _group_advantages(self, scores: np.ndarray, stats: Dict[str, Any]):
        """Per-group z-scores over this host's rows (groups are local by
        construction: tiling happens after the per-group stream slice).
        Degenerate all-equal groups get exactly zero advantage."""
        gs = self.config.method.group_size
        if len(scores) % gs:
            raise RuntimeError(
                f"rollout chunk of {len(scores)} rows is not whole groups "
                f"of {gs} — the prompt tiling invariant broke"
            )
        adv = np.asarray(group_relative_advantages(jnp.asarray(scores), gs))
        g = scores.reshape(-1, gs)
        group_std = g.std(axis=1)
        stats["grpo/group_reward_std"] = float(group_std.mean())
        stats["grpo/zero_adv_groups"] = float((group_std <= 1e-6).mean())
        return adv.astype(np.float32)

    def _score_and_assemble(
        self, batch: PromptBatch, gen_out, stats: Dict[str, Any],
        iter_count: int, clock: Clock,
    ):
        """The score half of one rollout chunk: decode + reward_fn, the
        teacher-forced policy+reference logprob forward, per-group
        z-score advantages, running-moment update and the chunk's stats
        (mutated into ``stats``). Shared verbatim by the direct rollout
        loop, the experience-transport producer AND the fleet worker,
        so the paths cannot numerically diverge. Returns
        ``(rollout_batch, rows_local)``."""
        method = self.config.method
        prompt_tensors = np.asarray(batch.input_ids)
        seq_w = gen_out["sequences"].shape[1]
        N = gen_out["response_ids"].shape[1]
        P_width = prompt_tensors.shape[1]
        real_local = gen_out.get("real_rows")
        B_local = (
            real_local
            if real_local is not None
            else gen_out["sequences"].shape[0] // mh.data_group_count(self.mesh)
        )

        # ONE packed device->host transfer for the generation outputs
        # (same choreography as PPO's seam — the DMA streams while the
        # experience forward below computes)
        packed_dev = mh.local_rows(
            jnp.concatenate(
                [
                    gen_out["sequences"],
                    gen_out["response_ids"],
                    gen_out["response_mask"].astype(gen_out["sequences"].dtype),
                ],
                axis=1,
            )
        )
        try:
            packed_dev.copy_to_host_async()
        except Exception:
            pass

        # fast path: the score-independent policy+ref logprob forward is
        # dispatched NOW on the sampler's device tensors; it executes
        # while the host decodes and scores. Falls back when host-side
        # token rewrites (stop sequences) or pad rows are needed.
        device_gen = (
            not self.stop_sequences
            and B_local % self.local_ways() == 0
            and real_local is None
        )
        pre_batch = pre_kl_stats = None
        if device_gen:
            with self.mesh:
                fwd_fn = self._get_experience_fwd_fn(P_width, N)
                pre_batch, pre_kl_stats = self._dispatch_experience(
                    fwd_fn,
                    self.params,
                    self.ref_params,
                    gen_out["sequences"].astype(jnp.int32),
                    jnp.concatenate(
                        [
                            gen_out["prompt_mask"].astype(jnp.int32),
                            gen_out["response_mask"].astype(jnp.int32),
                        ],
                        axis=1,
                    ),
                    gen_out["response_mask"].astype(jnp.int32),
                    jnp.ones((gen_out["sequences"].shape[0],), jnp.float32),
                )

        packed = packed_dev[:B_local]  # drop per-group pad rows
        sequences = packed[:, :seq_w]
        response_ids = packed[:, seq_w : seq_w + N]
        response_mask = packed[:, seq_w + N :]
        P = prompt_tensors.shape[1]

        prompt_sizes = [P] * len(sequences)
        str_samples, str_prompts, str_outputs = self.decode(
            prompt_tensors, sequences, prompt_sizes, append_eos_token=True
        )

        rollout_score_time = time()
        all_scores = self._call_reward_fn(
            samples=str_samples,
            prompts=str_prompts,
            outputs=str_outputs,
            tokenizer=self.tokenizer,
            **(batch.metadata or {}),
        )
        stats["time/rollout_score"] = time() - rollout_score_time

        # GRPO's baseline is per-SEQUENCE: dense reward vectors fold to
        # their sum (the group z-score needs one scalar per sample)
        scores = np.asarray(
            [float(np.asarray(s, np.float32).sum()) for s in all_scores],
            np.float32,
        )
        if method.cliprange_reward:
            scores = np.clip(
                scores, -method.cliprange_reward, method.cliprange_reward
            )

        # running reward moments ride the shared online-core helper for
        # telemetry/guardrails parity with PPO; the returned scaling
        # divisor is irrelevant here — z-scores are scale-invariant
        self._update_reward_moments(
            scores[:, None], np.ones_like(scores)[:, None], stats
        )
        advantages = self._group_advantages(scores, stats)

        if self.stop_sequences:
            # stop-sequence trimming changed the outputs: rebuild the
            # response tokens from the trimmed strings (the fallback
            # forward below recomputes logprobs on the rebuilt rows)
            outputs = self.tokenizer(str_outputs, add_special_tokens=False)["input_ids"]
            response_ids = np.full(
                (len(outputs), N), self.generate_settings.pad_token_id, np.int32
            )
            response_mask = np.zeros((len(outputs), N), np.int32)
            for i, o in enumerate(outputs):
                o = o[:N]
                response_ids[i, : len(o)] = o
                response_mask[i, : len(o)] = 1
            sequences = np.concatenate([prompt_tensors, response_ids], axis=1)

        # pad rows to the data-parallel multiple for sharding; pad rows
        # carry zero advantage and are excluded from KL stats via the
        # row-validity vector, then trimmed before the store push
        B = len(sequences)
        target = B + (-B) % self.local_ways()
        sharding = data_sharding(self.mesh)
        if device_gen:
            # the forward half has been executing since right after
            # generation; complete it with the host-computed advantages
            # (device_gen implies B % local_ways == 0, so the advantage
            # vector shards cleanly)
            with self.mesh:
                inject_fn = self._get_adv_inject_fn()
                rollout_batch = inject_fn(
                    pre_batch,
                    mh.global_from_local(advantages, vector_sharding(self.mesh)),
                )
            kl_stats = pre_kl_stats
        else:
            attention_mask = np.concatenate(
                [np.asarray(batch.attention_mask, np.int32), response_mask],
                axis=1,
            )

            def rpad(x):
                return self.pad_rows(x, target)

            adv_padded = np.concatenate(
                [advantages, np.zeros(target - B, np.float32)]
            )
            with self.mesh:
                fwd_fn = self._get_experience_fwd_fn(P, N)
                pre_batch, kl_stats = self._dispatch_experience(
                    fwd_fn,
                    self.params,
                    self.ref_params,
                    mh.global_from_local(rpad(sequences.astype(np.int32)), sharding),
                    mh.global_from_local(rpad(attention_mask), sharding),
                    mh.global_from_local(rpad(response_mask), sharding),
                    # per-ROW validity (pad rows sit inside each data
                    # group's block of the global batch)
                    mh.global_from_local(
                        np.concatenate(
                            [np.ones(B, np.float32),
                             np.zeros(target - B, np.float32)]
                        ),
                        vector_sharding(self.mesh),
                    ),
                )
                inject_fn = self._get_adv_inject_fn()
                rollout_batch = inject_fn(
                    pre_batch,
                    mh.global_from_local(adv_padded, vector_sharding(self.mesh)),
                )
        if target != B and mh.is_multihost():
            # each group's pad rows sit inside the global batch; a flat
            # [:B] can't drop them (same choreography as PPO's seam)
            rollout_batch = jax.tree_util.tree_map(
                lambda x: jax.device_put(
                    np.asarray(
                        mh.allgather_group_rows(
                            mh.local_rows(x)[:B], self.mesh
                        )
                    ),
                    replicated_sharding(self.mesh),
                ),
                rollout_batch,
            )
        elif target != B:
            rollout_batch = jax.tree_util.tree_map(
                lambda x: x[:B], rollout_batch
            )

        # honest rollout accounting + decode-engine ledger (shared
        # online-core helper)
        self._rollout_accounting_stats(
            response_ids, response_mask, gen_out, stats, iter_count
        )
        stats["time/rollout_time"] = clock.tick()
        stats["policy/sqrt_kl"] = jnp.sqrt(
            jnp.maximum(kl_stats["mean_kl"], 0.0)
        )
        stats["policy/kl_per_token"] = jnp.sqrt(
            jnp.maximum(kl_stats["mean_kl_per_token"], 0.0)
        )
        return rollout_batch, len(sequences)

    def _apply_staleness_clip(self, rollout_batch: GRPORolloutBatch):
        """IMPACT-style admission correction for an over-stale chunk
        (``exp.staleness.mode: clip``, arXiv:1912.00167): recompute
        behavior logprobs with the CURRENT policy (the proximal
        recompute) and thread the mismatch into the surrogate as a
        per-token clipped importance weight (``ops/grpo.py``
        ``is_weight``). The stored reference logprobs and group
        advantages are policy-independent and keep their values."""
        pad = self.generate_settings.pad_token_id
        q = jnp.asarray(rollout_batch.query_tensors, jnp.int32)
        r = jnp.asarray(rollout_batch.response_tensors, jnp.int32)
        P, N = q.shape[1], r.shape[1]
        tokens = jnp.concatenate([q, r], axis=1)
        attention_mask = (tokens != pad).astype(jnp.int32)
        resp_mask = jnp.asarray(rollout_batch.response_mask)
        attention_mask = attention_mask.at[:, P:].set(
            jnp.maximum(attention_mask[:, P:], resp_mask.astype(jnp.int32))
        )
        with self.mesh:
            fwd_fn = self._get_experience_fwd_fn(P, N)
            pre_batch, _ = fwd_fn(
                self.params, self.ref_params, tokens, attention_mask,
                resp_mask.astype(jnp.int32),
                jnp.ones((tokens.shape[0],), jnp.float32),
            )
        c = self._exp_cfg.staleness.clip_c
        mask = resp_mask.astype(jnp.float32)
        rho = jnp.exp(pre_batch.logprobs - rollout_batch.logprobs)
        is_weight = jnp.clip(rho, 1.0 - c, 1.0 + c) * mask + (1.0 - mask)
        return rollout_batch.replace(
            logprobs=pre_batch.logprobs,
            is_weight=is_weight,
        )
