"""Trainer registry and abstract base.

Parity: /root/reference/trlx/trainer/__init__.py:9-64 — string->class
registry populated by decorator, plus the abstract `learn()` contract.
"""

from __future__ import annotations

from abc import abstractmethod
from typing import Any, Callable, Dict, List, Optional

_TRAINERS: Dict[str, type] = {}


def register_trainer(name_or_cls):
    """Register a trainer class under its (lowercased) name (decorator).

    A duplicate name raises: two trainers silently shadowing each other
    under one key is exactly the bug a registry exists to prevent.
    Re-registering the same class (module reload) stays a no-op."""

    def _register(cls, name: str):
        key = name.lower()
        existing = _TRAINERS.get(key)
        if existing is not None and (
            (existing.__module__, existing.__qualname__)
            != (cls.__module__, cls.__qualname__)
        ):
            raise ValueError(
                f"trainer {name!r} is already registered to "
                f"{existing.__module__}.{existing.__qualname__}; refusing "
                "to overwrite it silently — pick a distinct name"
            )
        _TRAINERS[key] = cls
        return cls

    if isinstance(name_or_cls, str):
        return lambda cls: _register(cls, name_or_cls)
    return _register(name_or_cls, name_or_cls.__name__)


class BaseRLTrainer:
    """Abstract trainer: owns model/optimizer/tokenizer and the train loop.

    Subclasses implement `learn()`; online trainers also implement the
    rollout engine `make_experience`.
    """

    def __init__(
        self,
        config,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        stop_sequences: Optional[List[str]] = None,
        **kwargs: Any,
    ):
        self.config = config
        self.reward_fn = reward_fn
        self.metric_fn = metric_fn
        self.stop_sequences = stop_sequences or []

    def push_to_store(self, data):
        self.store.push(data)

    def add_eval_pipeline(self, eval_pipeline):
        self.eval_pipeline = eval_pipeline

    @abstractmethod
    def learn(self):
        """Run the full training loop."""
        raise NotImplementedError
