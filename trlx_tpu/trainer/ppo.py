"""PPO trainer: the method-specific half of the online experience core.

Parity: /root/reference/trlx/trainer/accelerate_ppo_trainer.py:35-553 and
the KL controllers from modeling_ppo.py:35-67. Metric keys match
(`time/rollout_generate`, `time/rollout_score`, `rollout_scores/*`,
`policy/sqrt_kl`, `kl_ctl_value`, ...), as does the running-moments
reward scaling and the adaptive KL schedule, so reward curves are
directly comparable.

The rollout ENGINE — prompt stream + cursors, chunked generate() with
one-chunk lookahead, cross-cycle prefetch, the experience transport
(`exp/`) and rollout fleet (`fleet/`) — lives in the trainer-agnostic
`trainer.base.TPUOnlineTrainer`; this module contributes only what is
PPO: the value-headed model, the teacher-forced score/assemble seam
(policy+ref+value forward, per-token KL penalty, reward injection), the
adaptive KL controller, GAE + the clipped surrogate loss, and the
IMPACT-style staleness clip recompute.

TPU re-design of the rollout loop (reference §3.2 call stack):
- Generation, the teacher-forced policy+ref+value forward, the KL
  penalty and reward assembly are TWO jitted calls per chunk (sample,
  then score+assemble); the reference interleaves ~10 host/device
  syncs and a rank0 broadcast/scatter round-trip per chunk.
- Reward scoring stays host-side (arbitrary user Python), computed once
  per host over its own shard — the NeMo-style per-host pattern
  (nemo_ppo_trainer.py:195-197), not the rank0-scatter one.
- Rollouts are born as rectangular PPORolloutBatch pytrees; no ragged
  tensor lists, no pad-at-collate.
"""

from __future__ import annotations

from time import time
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data import PPORolloutBatch, PromptBatch
from trlx_tpu.data.method_configs import PPOConfig
from trlx_tpu.models.wrappers import CausalLMWithValueHead, Seq2SeqLMWithValueHead
from trlx_tpu.ops.common import chunked_logprobs, logprobs_of_labels
from trlx_tpu.ops.ppo import gae_advantages_and_returns, ppo_loss
from trlx_tpu.ops.remat import resolve_remat
from trlx_tpu.parallel import data_sharding, shard_params
from trlx_tpu.parallel import multihost as mh
from trlx_tpu.parallel.mesh import replicated_sharding, vector_sharding
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import (  # noqa: F401  (_GroupChunkLoader re-export)
    TPUOnlineTrainer,
    _GroupChunkLoader,
)
from trlx_tpu.utils import Clock, logging

logger = logging.get_logger(__name__)


def _masked_kl_stats(kl, row_valid):
    """(mean_kl, mean_kl_per_token) over the rows row_valid marks 1:
    rows appended by pad_rows for dp-divisibility are excluded so they
    cannot bias the adaptive KL controller. A VECTOR (not a prefix
    count): on multi-host each data group's pad rows sit inside the
    global batch, so "the first n rows" would keep some groups' pad
    rows and drop other groups' real ones."""
    row_valid = row_valid.astype(jnp.float32)
    n_valid = jnp.maximum(row_valid.sum(), 1.0)
    mean_kl = (kl.sum(axis=1) * row_valid).sum() / n_valid
    mean_kl_per_token = (kl * row_valid[:, None]).sum() / (n_valid * kl.shape[1])
    return mean_kl, mean_kl_per_token


class AdaptiveKLController:
    """Ziegler-style proportional KL coefficient controller
    (parity: reference modeling_ppo.py:35-57)."""

    def __init__(self, init_kl_coef: float, target: float, horizon: int):
        self.value = init_kl_coef
        self.target = target
        self.horizon = horizon

    def update(self, current: float, n_steps: int) -> None:
        proportional_error = np.clip(current / self.target - 1, -0.2, 0.2)
        mult = 1 + proportional_error * n_steps / self.horizon
        self.value *= mult


class FixedKLController:
    """(parity: reference modeling_ppo.py:60-67)"""

    def __init__(self, kl_coef: float):
        self.value = kl_coef

    def update(self, current: float, n_steps: int) -> None:
        pass


@register_trainer("TPUPPOTrainer")
class TPUPPOTrainer(TPUOnlineTrainer):
    def __init__(self, config, **kwargs):
        if not isinstance(config.method, PPOConfig):
            raise ValueError("config.method must be PPOConfig")
        super().__init__(config, **kwargs)

        if (
            self.seq2seq
            and self._exp_cfg.enabled
            and self._exp_cfg.staleness.mode == "clip"
        ):
            raise NotImplementedError(
                "exp.staleness.mode='clip' needs the causal "
                "experience forward for the proximal recompute; "
                "use mode='reject' with seq2seq models"
            )

        if config.method.target:
            self.kl_ctl: Any = AdaptiveKLController(
                config.method.init_kl_coef, config.method.target, config.method.horizon
            )
        else:
            self.kl_ctl = FixedKLController(config.method.init_kl_coef)

        self.mean_kl = 0.0
        self._experience_fns: Dict[Tuple, Any] = {}

    # -- model -----------------------------------------------------------

    def setup_model(self) -> None:
        cfg, base_params, self.model_type = self.load_base_model()
        self.seq2seq = self.config.model.model_arch_type == "seq2seq"
        k = self.config.model.num_layers_unfrozen
        if self.config.model.peft_config is not None:
            from trlx_tpu.models.peft import normalize_peft_config

            pc = normalize_peft_config(self.config.model.peft_config)
            if self.seq2seq and pc["peft_type"] != "LORA":
                # matches the reference matrix: its own peft tests skip
                # seq2seq x {PROMPT,PREFIX} (peft 0.3.0 bugs)
                raise NotImplementedError(
                    "seq2seq supports peft_type='LORA' only"
                )
            # with adapters the reference model is the disabled-adapter
            # base, not a hydra branch (reference peft contract)
            k = -1
            if (
                pc["peft_type"] in ("PROMPT_TUNING", "PREFIX_TUNING")
                and self.config.method.num_value_layers_unfrozen
            ):
                raise NotImplementedError(
                    "num_value_layers_unfrozen with prompt/prefix tuning is "
                    "not supported (the value-branch capture forward does "
                    "not thread virtual-token adapters)"
                )
        at = None
        if self.seq2seq:
            if k is not None and 0 < k < cfg.n_decoder_layer:
                at = cfg.n_decoder_layer - k
            self.model = Seq2SeqLMWithValueHead(cfg, branch_at=at)
        else:
            if k is not None and 0 < k < cfg.n_layer:
                at = cfg.n_layer - k
            nv = self.config.method.num_value_layers_unfrozen
            value_at = cfg.n_layer - nv if nv and 0 < nv < cfg.n_layer else None
            self.model = CausalLMWithValueHead(
                cfg, branch_at=at, value_branch_at=value_at
            )
        self.rng, key = jax.random.split(self.rng)
        params = self.model.init_params(key, base_params)
        params.update(getattr(self, "_loaded_aux", None) or {})
        params = self.attach_peft(params)
        self.params = shard_params(self.mesh, params)
        # frozen in-process reference: the top-k branch (hydra) or a full
        # copy when everything is trainable (reference :74-77); with LoRA
        # the disabled-adapter base IS the reference (peft parity)
        self.ref_params = shard_params(self.mesh, self.model.make_ref_params(self.params))

    def trainable_mask(self):
        lora_mask = self.lora_freeze_mask(self.params)
        if lora_mask is not None:
            return lora_mask
        if self.seq2seq:
            return self.make_seq2seq_freeze_mask(self.params)
        return self.make_freeze_mask(self.params)

    # -- loss ------------------------------------------------------------

    def loss(self, params, batch: PPORolloutBatch):
        """Recompute logprobs/values on stored rollouts, GAE on the fly,
        clipped PPO objective (parity: reference loss :127-204)."""
        method = self.config.method
        if batch.advantages is not None:
            # gradient-accumulation compensation (_pre_accum_batch):
            # advantages were whitened over the FULL minibatch before
            # the microbatch split — recomputing here would whiten per
            # microbatch and diverge from the unsplit step
            advantages, returns = batch.advantages, batch.returns
        else:
            advantages, returns = gae_advantages_and_returns(
                batch.values, batch.rewards, gamma=method.gamma, lam=method.lam
            )
        pad = self.generate_settings.pad_token_id
        remat = resolve_remat(self.config.train.remat_policy)
        # chunked-from-hidden logprobs (train.logit_chunks): the full
        # [B, T, V] fp32 logits never materialize — the at-scale recipe
        chunks = self.config.train.logit_chunks
        if self.seq2seq:
            # query = encoder prompt; response = decoder ids (start token
            # + sampled tokens), parity: reference loss :146-173
            dec = batch.response_tensors
            enc_mask = (batch.query_tensors != pad).astype(jnp.int32)
            dec_mask = jnp.concatenate(
                [jnp.ones_like(dec[:, :1]), batch.response_mask.astype(jnp.int32)],
                axis=1,
            )
            out = self.model.forward_train(
                params, self.ref_params, batch.query_tensors, enc_mask, dec,
                dec_mask, remat=remat, compute_logits=chunks == 0,
            )
            if chunks:
                logprobs = chunked_logprobs(
                    self.model.logit_project_fn(params),
                    out["hidden_states"][:, :-1], dec[:, 1:], chunks,
                )
            else:
                logprobs = logprobs_of_labels(out["logits"][:, :-1], dec[:, 1:])
            values_pred = out["values"][:, :-1]
            return ppo_loss(
                logprobs=logprobs,
                values=values_pred,
                old_logprobs=batch.logprobs,
                old_values=batch.values,
                advantages=advantages,
                returns=returns,
                mask=batch.response_mask,
                cliprange=method.cliprange,
                cliprange_value=method.cliprange_value,
                vf_coef=method.vf_coef,
                is_weight=batch.is_weight,
                norm_n=None if batch.norm_n is None else batch.norm_n[0],
            )
        P = batch.query_tensors.shape[1]
        N = batch.response_tensors.shape[1]
        tokens = jnp.concatenate([batch.query_tensors, batch.response_tensors], axis=1)
        attention_mask = (tokens != pad).astype(jnp.int32)
        # response positions count even where response==pad (mask handles it)
        attention_mask = attention_mask.at[:, P:].set(
            jnp.maximum(attention_mask[:, P:], batch.response_mask.astype(jnp.int32))
        )
        out = self.model.forward_train(
            params, self.ref_params, tokens, attention_mask, remat=remat,
            compute_logits=chunks == 0,
        )
        if chunks:
            # only response positions need logprobs: slice hidden BEFORE
            # projecting, so even the chunked vocab matmul runs over N
            # rows, not P+N
            logprobs = chunked_logprobs(
                self.model.logit_project_fn(params),
                out["hidden_states"][:, P - 1 : P + N - 1],
                tokens[:, P : P + N], chunks,
            )
        else:
            logprobs = logprobs_of_labels(out["logits"][:, P - 1 : P + N - 1], tokens[:, P : P + N])
        values_pred = out["values"][:, P - 1 : P + N - 1]
        return ppo_loss(
            logprobs=logprobs,
            values=values_pred,
            old_logprobs=batch.logprobs,
            old_values=batch.values,
            advantages=advantages,
            returns=returns,
            mask=batch.response_mask,
            cliprange=method.cliprange,
            cliprange_value=method.cliprange_value,
            vf_coef=method.vf_coef,
            # experience-transport staleness correction (exp.staleness.
            # mode: clip); None on every other path = weight 1
            is_weight=batch.is_weight,
            # split-microbatch normalizer compensation (_pre_accum_batch)
            norm_n=None if batch.norm_n is None else batch.norm_n[0],
        )

    # -- the method-specific score/assemble seam -------------------------

    def _inner_epochs(self) -> int:
        return self.config.method.ppo_epochs

    def _rollout_stage_meta(self):
        # the adaptive KL coefficient at collection time rides the
        # deferred stats so the flush logs the value the chunk trained at
        return self.kl_ctl.value

    def _get_experience_fn(self, P: int, N: int, S: int):
        """Jitted score+assemble step: teacher-forced policy/ref/value
        forward, per-token KL penalty, terminal (or dense) reward add."""
        # logit_chunks is baked into the traced fn: it keys the cache
        key = (P, N, S, self.config.train.logit_chunks)
        if key in self._experience_fns:
            return self._experience_fns[key]
        model = self.model

        chunks = self.config.train.logit_chunks

        def seq2seq_fn(params, ref_params, enc_ids, enc_mask, dec_ids, response_mask, scores, scores_mask, kl_coef, row_valid, scale_div):
            scores = scores / jnp.maximum(scale_div, 1e-8)
            mask = response_mask.astype(jnp.float32)
            dec_mask = jnp.concatenate(
                [jnp.ones_like(dec_ids[:, :1]), response_mask.astype(jnp.int32)], axis=1
            )
            out = model.forward_train(
                params, ref_params, enc_ids, enc_mask, dec_ids, dec_mask,
                compute_logits=chunks == 0,
            )
            if chunks:
                from trlx_tpu.models.seq2seq import t5_logit_projection

                logprobs = chunked_logprobs(
                    model.logit_project_fn(params),
                    out["hidden_states"][:, :-1], dec_ids[:, 1:], chunks,
                ) * mask
                ref_logprobs = chunked_logprobs(
                    t5_logit_projection(ref_params, model.cfg),
                    out["ref_hidden"][:, :-1], dec_ids[:, 1:], chunks,
                ) * mask
            else:
                logprobs = logprobs_of_labels(out["logits"][:, :-1], dec_ids[:, 1:]) * mask
                ref_logprobs = logprobs_of_labels(out["ref_logits"][:, :-1], dec_ids[:, 1:]) * mask
            log_ratio = logprobs - ref_logprobs
            kl = jnp.exp(log_ratio) - 1 - log_ratio
            mean_kl, mean_kl_per_token = _masked_kl_stats(kl, row_valid)
            values = out["values"][:, :-1] * mask

            rewards = -kl_coef * log_ratio
            if S == 1:
                last = jnp.maximum(mask.sum(axis=1).astype(jnp.int32) - 1, 0)
                rewards = rewards + scores[:, 0:1] * jax.nn.one_hot(last, N, dtype=rewards.dtype)
            else:
                padded = jnp.zeros_like(rewards)
                padded = padded.at[:, :S].set(scores * scores_mask)
                rewards = rewards + padded
            rewards = rewards * mask

            batch_out = PPORolloutBatch(
                query_tensors=enc_ids,
                response_tensors=dec_ids,
                logprobs=logprobs,
                values=values,
                rewards=rewards,
                response_mask=mask,
            )
            return batch_out, {"mean_kl": mean_kl, "mean_kl_per_token": mean_kl_per_token}

        if self.seq2seq:
            self._experience_fns[key] = jax.jit(seq2seq_fn)
            return self._experience_fns[key]

        # causal path: composed from the SAME two jitted halves the
        # overlapped fast path uses (fwd + score inject), so the fallback
        # cannot numerically diverge from it
        fwd_fn = self._get_experience_fwd_fn(P, N)
        inject_fn = self._get_score_inject_fn(N, S)

        def fn(params, ref_params, tokens, attention_mask, response_mask, scores, scores_mask, kl_coef, row_valid, scale_div):
            # no envelope here: this composed fn is itself dispatched
            # through _dispatch_experience at its call site — wrapping
            # both layers would classify one OOM twice
            pre_batch, kl_stats = fwd_fn(
                params, ref_params, tokens, attention_mask, response_mask,
                kl_coef, row_valid,
            )
            return inject_fn(pre_batch, scores, scores_mask, scale_div), kl_stats

        self._experience_fns[key] = fn
        return self._experience_fns[key]

    def _get_experience_fwd_fn(self, P: int, N: int):
        """The score-independent half of the experience step: teacher-forced
        policy/ref/value forward + per-token KL penalty. Dispatched BEFORE
        host scoring (it only reads device tensors the sampler produced),
        so the heaviest rollout compute overlaps decode + reward_fn — with
        a slow reward model the whole forward hides under scoring. The
        score half is `_get_score_inject_fn`."""
        key = ("fwd", P, N, self.config.train.logit_chunks)
        if key in self._experience_fns:
            return self._experience_fns[key]
        model = self.model

        chunks = self.config.train.logit_chunks

        def fn(params, ref_params, tokens, attention_mask, response_mask, kl_coef, row_valid):
            out = model.forward_train(
                params, ref_params, tokens, attention_mask,
                compute_logits=chunks == 0,
            )
            if chunks:
                from trlx_tpu.models.transformer import logit_projection

                logprobs_full = chunked_logprobs(
                    model.logit_project_fn(params),
                    out["hidden_states"][:, :-1], tokens[:, 1:], chunks,
                )
                ref_logprobs_full = chunked_logprobs(
                    logit_projection(ref_params),
                    out["ref_hidden"][:, :-1], tokens[:, 1:], chunks,
                )
            else:
                logprobs_full = logprobs_of_labels(out["logits"][:, :-1], tokens[:, 1:])
                ref_logprobs_full = logprobs_of_labels(out["ref_logits"][:, :-1], tokens[:, 1:])

            full_mask = attention_mask[:, 1:].astype(jnp.float32)
            log_ratio_full = (logprobs_full - ref_logprobs_full) * full_mask
            kl = jnp.exp(log_ratio_full) - 1 - log_ratio_full
            mean_kl, mean_kl_per_token = _masked_kl_stats(kl, row_valid)

            mask = response_mask.astype(jnp.float32)
            sl = slice(P - 1, P + N - 1)
            logprobs = logprobs_full[:, sl] * mask
            values = out["values"][:, sl] * mask
            log_ratio = log_ratio_full[:, sl] * mask

            batch_out = PPORolloutBatch(
                query_tensors=tokens[:, :P],
                response_tensors=tokens[:, P:],
                logprobs=logprobs,
                values=values,
                rewards=-kl_coef * log_ratio,  # scores injected later
                response_mask=mask,
            )
            return batch_out, {"mean_kl": mean_kl, "mean_kl_per_token": mean_kl_per_token}

        self._experience_fns[key] = jax.jit(fn)
        return self._experience_fns[key]

    def _get_score_inject_fn(self, N: int, S: int):
        """Apply host-computed scores to a KL-only rollout batch: scale,
        add terminal (S=1) or dense (S>1) rewards, re-mask."""
        key = ("inject", N, S)
        if key in self._experience_fns:
            return self._experience_fns[key]

        def fn(batch_out, scores, scores_mask, scale_div):
            scores = scores / jnp.maximum(scale_div, 1e-8)
            mask = batch_out.response_mask
            rewards = batch_out.rewards
            if S == 1:
                last = jnp.maximum(mask.sum(axis=1).astype(jnp.int32) - 1, 0)
                rewards = rewards + scores[:, 0:1] * (
                    jax.nn.one_hot(last, N, dtype=rewards.dtype)
                )
            else:
                padded = jnp.zeros_like(rewards)
                padded = padded.at[:, :S].set(scores * scores_mask)
                rewards = rewards + padded
            return batch_out.replace(rewards=rewards * mask)

        self._experience_fns[key] = jax.jit(fn)
        return self._experience_fns[key]

    def _score_and_assemble(
        self, batch: PromptBatch, gen_out, stats: Dict[str, Any],
        iter_count: int, clock: Clock,
    ):
        """The score half of one rollout chunk: decode + reward_fn, the
        teacher-forced policy/ref/value forward, KL penalty + reward
        assembly, running-moment update and the chunk's stats (mutated
        into ``stats``). Shared verbatim by the direct rollout loop and
        the experience-transport producer, so the two paths cannot
        numerically diverge. Returns ``(rollout_batch, rows_local)``."""
        method = self.config.method
        prompt_tensors = np.asarray(batch.input_ids)
        seq_w = gen_out["sequences"].shape[1]
        N = gen_out["response_ids"].shape[1]
        P_width = prompt_tensors.shape[1]
        # a ragged multi-host chunk comes back PADDED per data group
        # with real_rows marking the group's real count — all row
        # bookkeeping below runs on real rows; the pad rows only
        # exist inside device arrays until the local slice
        real_local = gen_out.get("real_rows")
        B_local = (
            real_local
            if real_local is not None
            else gen_out["sequences"].shape[0] // mh.data_group_count(self.mesh)
        )

        # ONE packed device->host transfer for the three generation
        # outputs (a remote-tunneled chip pays ~100ms latency PER
        # transfer). The concatenate is enqueued FIRST — devices run
        # FIFO, so the DMA starts as soon as generation finishes and
        # streams while the experience forward below computes
        packed_dev = mh.local_rows(
            jnp.concatenate(
                [
                    gen_out["sequences"],
                    gen_out["response_ids"],
                    gen_out["response_mask"].astype(gen_out["sequences"].dtype),
                ],
                axis=1,
            )
        )
        try:
            packed_dev.copy_to_host_async()
        except Exception:
            pass

        # fast path: the score-INDEPENDENT half of the experience step
        # (policy/ref/value forward + KL penalty — the heaviest rollout
        # compute) is dispatched NOW, on the device tensors the sampler
        # just produced. It executes while the host decodes and scores
        # the samples; the tiny score-injection jit below completes the
        # rollout batch once reward_fn returns. Falls back to the
        # fused experience fn when host-side token rewrites (stop
        # sequences, seq2seq) or pad rows are needed.
        device_gen = (
            not self.seq2seq
            and not self.stop_sequences
            and B_local % self.local_ways() == 0
            # a padded multihost chunk (real_rows set — including the
            # divisible-but-widened case, where generate() padded up
            # to an already-compiled wider shape) must take the
            # host-scored path: the device fast path would build
            # pre_batch over the pad rows and mismatch the real-row
            # scores at injection
            and real_local is None
        )
        pre_batch = pre_kl_stats = None
        if device_gen:
            with self.mesh:
                fwd_fn = self._get_experience_fwd_fn(P_width, N)
                pre_batch, pre_kl_stats = self._dispatch_experience(
                    fwd_fn,
                    self.params,
                    self.ref_params,
                    gen_out["sequences"].astype(jnp.int32),
                    jnp.concatenate(
                        [
                            gen_out["prompt_mask"].astype(jnp.int32),
                            gen_out["response_mask"].astype(jnp.int32),
                        ],
                        axis=1,
                    ),
                    gen_out["response_mask"].astype(jnp.int32),
                    jnp.float32(self.kl_ctl.value),
                    # device_gen only runs on unpadded batches: every
                    # row is valid
                    jnp.ones((gen_out["sequences"].shape[0],), jnp.float32),
                )

        packed = packed_dev[:B_local]  # drop per-group pad rows
        sequences = packed[:, :seq_w]
        response_ids = packed[:, seq_w : seq_w + N]
        response_mask = packed[:, seq_w + N :]
        P = prompt_tensors.shape[1]

        prompt_sizes = [P] * len(sequences)
        str_samples, str_prompts, str_outputs = self.decode(
            prompt_tensors, sequences, prompt_sizes, append_eos_token=True
        )

        rollout_score_time = time()
        all_scores = self._call_reward_fn(
            samples=str_samples,
            prompts=str_prompts,
            outputs=str_outputs,
            tokenizer=self.tokenizer,
            **(batch.metadata or {}),
        )
        stats["time/rollout_score"] = time() - rollout_score_time

        scores_list = [np.atleast_1d(np.asarray(s, np.float32)) for s in all_scores]
        S = max(len(s) for s in scores_list)
        if S > N:
            # a dense reward vector longer than the response window (a
            # char-level reward_fn over a decode that appended the EOS
            # string, say) cannot be scattered onto [B, N] rewards: fold
            # the tail into the final in-window entry so no reward mass
            # is silently dropped
            scores_list = [
                np.concatenate([s[: N - 1], [s[N - 1 :].sum()]])
                if len(s) > N else s
                for s in scores_list
            ]
            S = N
        scores = np.zeros((len(scores_list), S), np.float32)
        scores_mask = np.zeros((len(scores_list), S), np.float32)
        for i, s in enumerate(scores_list):
            scores[i, : len(s)] = s
            scores_mask[i, : len(s)] = 1.0

        if self.stop_sequences:
            # stop-sequence trimming changed the outputs: rebuild the
            # response tokens from the trimmed strings (the reference
            # re-tokenizes unconditionally, :345-365 — lossy for some
            # tokenizers, so here only when actually needed)
            outputs = self.tokenizer(str_outputs, add_special_tokens=False)["input_ids"]
            response_ids = np.full((len(outputs), N), self.generate_settings.pad_token_id, np.int32)
            response_mask = np.zeros((len(outputs), N), np.int32)
            for i, o in enumerate(outputs):
                o = o[:N]
                response_ids[i, : len(o)] = o
                response_mask[i, : len(o)] = 1
            if self.seq2seq:
                start = sequences[:, :1]  # decoder start token column
                sequences = np.concatenate([start, response_ids], axis=1)
            else:
                sequences = np.concatenate([prompt_tensors, response_ids], axis=1)

        if method.cliprange_reward:
            scores = np.clip(scores, -method.cliprange_reward, method.cliprange_reward)

        # running reward moments + the reward-scaling divisor (shared
        # online-core helper — one implementation for PPO and GRPO)
        scale_div = self._update_reward_moments(scores, scores_mask, stats)

        # pad rows to the data-parallel multiple for sharding; the
        # extra rows are trimmed off the rollout batch afterwards
        # (multi-host: every group pads the same B -> target, so the
        # global batch stays rectangular; pad rows repeat the last
        # real row, are excluded from KL stats via the row-validity
        # vector below, and are dropped before the store push)
        B = len(sequences)
        target = B + (-B) % self.local_ways()

        def rpad(x):
            return self.pad_rows(x, target)

        sharding = data_sharding(self.mesh)
        if device_gen:
            # the forward half has been executing since right after
            # generation; complete it with the host-computed scores
            with self.mesh:
                inject_fn = self._get_score_inject_fn(N, S)
                rollout_batch = inject_fn(
                    pre_batch,
                    mh.global_from_local(scores, sharding),
                    mh.global_from_local(scores_mask, sharding),
                    scale_div,
                )
            kl_stats = pre_kl_stats
        else:
            exp_fn = self._get_experience_fn(P, N, S)
            if self.seq2seq:
                args = (
                    rpad(prompt_tensors.astype(np.int32)),
                    rpad(np.asarray(batch.attention_mask, np.int32)),
                    rpad(sequences.astype(np.int32)),
                )
            else:
                attention_mask = np.concatenate(
                    [np.asarray(batch.attention_mask, np.int32), response_mask],
                    axis=1,
                )
                args = (
                    rpad(sequences.astype(np.int32)),
                    rpad(attention_mask),
                )
            with self.mesh:
                rollout_batch, kl_stats = self._dispatch_experience(
                    exp_fn,
                    self.params,
                    self.ref_params,
                    *[mh.global_from_local(a, sharding) for a in args],
                    mh.global_from_local(rpad(response_mask), sharding),
                    mh.global_from_local(rpad(scores), sharding),
                    mh.global_from_local(rpad(scores_mask), sharding),
                    jnp.float32(self.kl_ctl.value),
                    # per-ROW validity (pad rows sit inside each data
                    # group's block of the global batch, so a prefix
                    # count can't mark them)
                    mh.global_from_local(
                        np.concatenate(
                            [np.ones(B, np.float32),
                             np.zeros(target - B, np.float32)]
                        ),
                        vector_sharding(self.mesh),
                    ),
                    scale_div,
                )
        if target != B and mh.is_multihost():
            # each group's pad rows sit inside the global batch; a
            # flat [:B] can't drop them. The chunk is tiny (only a
            # short FINAL chunk is ragged), so take the host
            # round-trip: local real rows -> allgather -> one
            # replicated, consistent global batch for the store
            rollout_batch = jax.tree_util.tree_map(
                lambda x: jax.device_put(
                    np.asarray(
                        mh.allgather_group_rows(
                            mh.local_rows(x)[:B], self.mesh
                        )
                    ),
                    replicated_sharding(self.mesh),
                ),
                rollout_batch,
            )
        elif target != B:
            # trim the sharding-pad rows ON DEVICE (the store keeps
            # device-resident rollouts; no host round-trip here)
            rollout_batch = jax.tree_util.tree_map(
                lambda x: x[:B], rollout_batch
            )

        # honest rollout accounting + decode-engine ledger (shared
        # online-core helper)
        self._rollout_accounting_stats(
            response_ids, response_mask, gen_out, stats, iter_count
        )
        stats["time/rollout_time"] = clock.tick()
        stats["policy/sqrt_kl"] = jnp.sqrt(
            jnp.maximum(kl_stats["mean_kl"], 0.0)
        )
        stats["policy/kl_per_token"] = jnp.sqrt(
            jnp.maximum(kl_stats["mean_kl_per_token"], 0.0)
        )
        return rollout_batch, len(sequences)

    def _apply_staleness_clip(self, rollout_batch: PPORolloutBatch):
        """IMPACT-style admission correction for an over-stale chunk
        (``exp.staleness.mode: clip``, arXiv:1912.00167): recompute
        logprobs/values with the CURRENT policy (the proximal recompute
        — the PPO ratio is then measured against the policy the
        optimization epoch actually starts from) and thread the
        behavior mismatch into the surrogate as a per-token CLIPPED
        importance weight rho = clip(pi_now/pi_behavior, 1±clip_c)
        (``ops/ppo.py`` ``is_weight``). The stored rewards keep their
        generation-time KL penalty (the terminal score is
        policy-independent)."""
        pad = self.generate_settings.pad_token_id
        q = jnp.asarray(rollout_batch.query_tensors, jnp.int32)
        r = jnp.asarray(rollout_batch.response_tensors, jnp.int32)
        P, N = q.shape[1], r.shape[1]
        tokens = jnp.concatenate([q, r], axis=1)
        attention_mask = (tokens != pad).astype(jnp.int32)
        resp_mask = jnp.asarray(rollout_batch.response_mask)
        attention_mask = attention_mask.at[:, P:].set(
            jnp.maximum(attention_mask[:, P:], resp_mask.astype(jnp.int32))
        )
        with self.mesh:
            fwd_fn = self._get_experience_fwd_fn(P, N)
            pre_batch, _ = self._dispatch_experience(
                fwd_fn,
                self.params, self.ref_params, tokens, attention_mask,
                resp_mask.astype(jnp.int32),
                jnp.float32(self.kl_ctl.value),
                jnp.ones((tokens.shape[0],), jnp.float32),
            )
        c = self._exp_cfg.staleness.clip_c
        mask = resp_mask.astype(jnp.float32)
        rho = jnp.exp(pre_batch.logprobs - rollout_batch.logprobs)
        is_weight = jnp.clip(rho, 1.0 - c, 1.0 + c) * mask + (1.0 - mask)
        return rollout_batch.replace(
            logprobs=pre_batch.logprobs,
            values=pre_batch.values,
            is_weight=is_weight,
        )

    def _finish_rollout_stats(self) -> None:
        """Materialize + log the deferred make_experience stats (sets
        self.mean_kl for the KL controller; feeds the guardrails the
        rollout-side health signals). Idempotent."""
        for stats, step, kl_ctl_value in self._deferred_rollout.flush():
            stats["kl_ctl_value"] = kl_ctl_value
            self.mean_kl = stats["policy/sqrt_kl"] ** 2
            if self.guardrails.enabled:
                self.guardrails.observe_rollout(
                    kl=self.mean_kl,
                    kl_target=getattr(self.kl_ctl, "target", None),
                    reward_mean=stats.get("rollout_scores/mean"),
                    running_mean=stats.get("rollout_scores/running_mean"),
                    running_std=stats.get("rollout_scores/running_std"),
                    truncation_rate=stats.get("rollout/truncation_rate"),
                )
            self._tracker_log(stats, step=step)

    # -- memory doctor hooks ---------------------------------------------

    def _pre_accum_batch(self, batch):
        """Gradient-accumulation compensation for the memory doctor's
        split_microbatch rung: GAE + advantage whitening are computed
        over the FULL step batch before the scan splits it, so the
        whitening statistics (batch mean/std) are num_mb-INVARIANT —
        an unsplit (num_mb=1) baseline is reproduced exactly
        (reduction-order tolerance, tests/test_memdoctor.py golden),
        and any further doctor split preserves numerics. A config that
        already accumulated (train.minibatch_size) whitened per
        microbatch pre-doctor; its first split switches to this
        full-batch scope with a logged warning (_apply_accum_factor) —
        no compensation can reproduce the old statistics from smaller
        microbatches. Outside a doctor split the batch passes through
        untouched: the pre-doctor minibatch path keeps its
        reference-parity per-microbatch whitening."""
        if self.memdoctor.accum_factor <= 1 or not isinstance(
            batch, PPORolloutBatch
        ):
            return batch
        method = self.config.method
        advantages, returns = gae_advantages_and_returns(
            batch.values, batch.rewards, gamma=method.gamma, lam=method.lam
        )
        # the loss's mask-count normalizer, fixed to full_total/num_mb:
        # each microbatch then divides by the same constant, so the
        # accumulated mean equals the unsplit sum/N_total exactly even
        # when ragged response masks make per-microbatch counts unequal
        rows = batch.response_mask.shape[0]
        norm = jnp.full(
            (rows,),
            batch.response_mask.astype(jnp.float32).sum() / self.num_mb,
            jnp.float32,
        )
        return batch.replace(advantages=advantages, returns=returns, norm_n=norm)

    def _drop_traced_fns(self) -> None:
        # the teacher-forced experience fns trace train.remat_policy
        # in too — a remat escalation must retrace them
        super()._drop_traced_fns()
        self._experience_fns.clear()

    def _extra_plan_items(self):
        """Preflight plan rows for PPO's method half: the teacher-forced
        experience forward materializes one chunk's activations at
        [chunk, P+N] on top of the rollout phase (it shares the phase
        with generation — they run back-to-back per chunk)."""
        from trlx_tpu.utils.memdoctor import PlanItem, _dtype_size

        train = self.config.train
        chunk = int(self.config.method.chunk_size)
        rows_dev = max(chunk // self.data_ways(), 1)
        cfg = self._lm().cfg
        S = train.seq_length
        # forward-only: residency is ~2 live layer activations, not the
        # whole saved-for-backward stack (unless logits materialize)
        act_b = int(rows_dev * S * cfg.hidden_size * 2
                    * _dtype_size(train.compute_dtype))
        chunks = max(int(train.logit_chunks or 0), 0)
        logit_rows = S if chunks == 0 else -(-S // chunks)
        logits_b = int(2 * rows_dev * logit_rows * cfg.vocab_size * 4)
        return [
            PlanItem("rollout", "experience_fwd", act_b + logits_b,
                     "teacher-forced policy+ref forward per chunk"),
        ]

    # -- controller state layered on the online-core hooks ---------------

    def _extra_fingerprint(self):
        """Consistency-watchdog extras: the online core's cursors plus
        the KL controller (host-side PPO state that MUST advance in
        lockstep across hosts)."""
        out = super()._extra_fingerprint()
        out["kl_ctl"] = float(self.kl_ctl.value)
        return out

    def _extra_state(self):
        state = super()._extra_state()
        state["kl_ctl_value"] = float(self.kl_ctl.value)
        state["mean_kl"] = float(self.mean_kl)
        return state

    def _restore_extra_state(self, state) -> None:
        if "kl_ctl_value" in state:
            self.kl_ctl.value = state["kl_ctl_value"]
        self.mean_kl = state.get("mean_kl", 0.0)
        super()._restore_extra_state(state)

    def post_backward_callback(self) -> None:
        # flush the deferred rollout stats first: they carry the mean KL
        # this controller update consumes (by now the async device->host
        # copy has landed under the train step, so this is a free read)
        super().post_backward_callback()
        self.kl_ctl.update(self.mean_kl, n_steps=self.config.train.batch_size)
