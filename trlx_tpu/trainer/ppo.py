"""PPO trainer: rollout engine + clipped-surrogate training.

Parity: /root/reference/trlx/trainer/accelerate_ppo_trainer.py:35-553 and
the KL controllers from modeling_ppo.py:35-67. Metric keys match
(`time/rollout_generate`, `time/rollout_score`, `rollout_scores/*`,
`policy/sqrt_kl`, `kl_ctl_value`, ...), as does the running-moments
reward scaling and the adaptive KL schedule, so reward curves are
directly comparable.

TPU re-design of the rollout loop (reference §3.2 call stack):
- Generation, the teacher-forced policy+ref+value forward, the KL
  penalty and reward assembly are TWO jitted calls per chunk (sample,
  then score+assemble); the reference interleaves ~10 host/device
  syncs and a rank0 broadcast/scatter round-trip per chunk.
- Reward scoring stays host-side (arbitrary user Python), computed once
  per host over its own shard — the NeMo-style per-host pattern
  (nemo_ppo_trainer.py:195-197), not the rank0-scatter one.
- Rollouts are born as rectangular PPORolloutBatch pytrees; no ragged
  tensor lists, no pad-at-collate.
"""

from __future__ import annotations

from time import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data import PPORolloutBatch, PromptBatch
from trlx_tpu.data.method_configs import PPOConfig
from trlx_tpu.exp import ExpConfig, ExperienceTransport
from trlx_tpu.exp import transport as exp_transport
from trlx_tpu.fleet.config import FleetConfig
from trlx_tpu.utils.guardrails import FLEET_SIGNAL, STALENESS_SIGNAL
from trlx_tpu.models.wrappers import CausalLMWithValueHead, Seq2SeqLMWithValueHead
from trlx_tpu.ops.common import (
    chunked_logprobs,
    logprobs_of_labels,
    running_moments_init,
    running_moments_update,
)
from trlx_tpu.ops.ppo import gae_advantages_and_returns, ppo_loss
from trlx_tpu.parallel import data_sharding, shard_params
from trlx_tpu.parallel import multihost as mh
from trlx_tpu.parallel.mesh import replicated_sharding, vector_sharding
from trlx_tpu.pipeline import DataLoader
from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUBaseTrainer
from trlx_tpu.utils import Clock, infinite_loader, logging
from trlx_tpu.utils.trackers import DeferredStats
from trlx_tpu.ops.remat import resolve_remat

logger = logging.get_logger(__name__)


def _masked_kl_stats(kl, row_valid):
    """(mean_kl, mean_kl_per_token) over the rows row_valid marks 1:
    rows appended by pad_rows for dp-divisibility are excluded so they
    cannot bias the adaptive KL controller. A VECTOR (not a prefix
    count): on multi-host each data group's pad rows sit inside the
    global batch, so "the first n rows" would keep some groups' pad
    rows and drop other groups' real ones."""
    row_valid = row_valid.astype(jnp.float32)
    n_valid = jnp.maximum(row_valid.sum(), 1.0)
    mean_kl = (kl.sum(axis=1) * row_valid).sum() / n_valid
    mean_kl_per_token = (kl * row_valid[:, None]).sum() / (n_valid * kl.shape[1])
    return mean_kl, mean_kl_per_token


class _GroupChunkLoader(DataLoader):
    """Per-data-group view of the GLOBAL prompt-chunk order: every
    process draws the SAME shuffle stream a plain ``DataLoader`` over
    the full prompt list would (one shuffle of the global index order
    per epoch, same RNG consumption), chunks it at the global chunk
    size, then collates ONLY this group's strided rows of each chunk.

    This is what makes the prompt stream topology-invariant: the chunk
    composition is fixed by (seed, chunk_size) alone, so a checkpoint
    cursor saved under G data groups replays the exact same prompts
    under G' groups — while each host still pays only 1/G of the
    per-pull collation (the index slice happens BEFORE collate).
    Groups are padded to equal row counts by wrapping within the chunk
    (SPMD lockstep needs equal-shape pulls; the repeated row is the
    same compromise `shard_list` made)."""

    def __init__(
        self, dataset, batch_size, collate_fn, group, group_count,
        seed, shuffle=True, drop_last=True,
    ):
        super().__init__(
            dataset, batch_size, collate_fn=collate_fn, shuffle=shuffle,
            drop_last=drop_last, seed=seed,
        )
        self.group = group
        self.group_count = group_count

    def _select_rows(self, idxs) -> List[int]:
        # DataLoader.__iter__ hook: shuffle/chunking stay the base
        # class's (the parity-critical RNG stream is written ONCE);
        # only the row selection differs
        local = [int(i) for i in idxs[self.group :: self.group_count]]
        want = (len(idxs) + self.group_count - 1) // self.group_count
        i = 0
        while len(local) < want:
            local.append(int(idxs[(self.group + i * self.group_count) % len(idxs)]))
            i += 1
        return local


class AdaptiveKLController:
    """Ziegler-style proportional KL coefficient controller
    (parity: reference modeling_ppo.py:35-57)."""

    def __init__(self, init_kl_coef: float, target: float, horizon: int):
        self.value = init_kl_coef
        self.target = target
        self.horizon = horizon

    def update(self, current: float, n_steps: int) -> None:
        proportional_error = np.clip(current / self.target - 1, -0.2, 0.2)
        mult = 1 + proportional_error * n_steps / self.horizon
        self.value *= mult


class FixedKLController:
    """(parity: reference modeling_ppo.py:60-67)"""

    def __init__(self, kl_coef: float):
        self.value = kl_coef

    def update(self, current: float, n_steps: int) -> None:
        pass


@register_trainer("TPUPPOTrainer")
class TPUPPOTrainer(TPUBaseTrainer):
    def __init__(self, config, **kwargs):
        if not isinstance(config.method, PPOConfig):
            raise ValueError("config.method must be PPOConfig")
        super().__init__(config, **kwargs)

        data_ways = self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
        if config.method.chunk_size % data_ways:
            raise ValueError(
                f"method.chunk_size {config.method.chunk_size} must be divisible "
                f"by dp*fsdp={data_ways}"
            )
        self.store = PPORolloutStorage(pad_token_id=self.generate_settings.pad_token_id)
        self.running_moments = running_moments_init()
        self.ref_mean = config.method.ref_mean
        self.ref_std = config.method.ref_std

        if config.method.target:
            self.kl_ctl: Any = AdaptiveKLController(
                config.method.init_kl_coef, config.method.target, config.method.horizon
            )
        else:
            self.kl_ctl = FixedKLController(config.method.init_kl_coef)

        self.mean_kl = 0.0
        self._deferred_rollout = DeferredStats()
        # rollout-data cursor: how many prompt chunks this run has pulled
        # off the (deterministically shuffled) prompt stream. Saved in
        # state.json so a resumed run fast-forwards to the exact position
        # instead of replaying the stream from its start.
        self._prompt_batches_consumed = 0
        self._resume_prompt_cursor = 0
        # cross-cycle rollout prefetch (method.overlap_rollouts): the
        # next cycle's first chunk, generated ahead of the current fused
        # optimization block, plus the prompt cursor it must rewind to
        # if it never trains (preemption / run end)
        self._prefetched_gen: Optional[Tuple] = None
        self._prefetch_cursor_start: Optional[int] = None
        self.log_rollouts = config.train.rollout_logging_dir is not None
        if self.log_rollouts:
            self.setup_rollout_logging(config)
        self._experience_fns: Dict[Tuple, Any] = {}
        # resilient experience transport (ppo.exp.*, trlx_tpu/exp/):
        # rollout chunks travel through a leased, deduplicating queue
        # with a staleness admission gate; default off = the direct
        # rollout loop, and fault-free the transport path is golden-
        # checked bit-equal to it (tests/test_exp_queue.py)
        self._exp_cfg = ExpConfig.from_dict(getattr(config.method, "exp", None))
        self._exp: Optional[ExperienceTransport] = None
        if self._exp_cfg.enabled:
            if self.seq2seq and self._exp_cfg.staleness.mode == "clip":
                raise NotImplementedError(
                    "exp.staleness.mode='clip' needs the causal "
                    "experience forward for the proximal recompute; "
                    "use mode='reject' with seq2seq models"
                )
            self._exp = ExperienceTransport(
                self._exp_cfg, owner=f"proc{mh.process_index()}"
            )
        # policy version the in-flight overlap_rollouts prefetch was
        # generated at (the chunk is consumed one optimizer cycle later,
        # so its recorded version must be the generation-time one)
        self._prefetch_policy_version = 0
        # fault-tolerant rollout fleet (ppo.fleet.*, trlx_tpu/fleet/):
        # chunk production routed to cross-process workers behind the
        # transport seam — membership heartbeats, versioned weight
        # broadcast, degraded-mode fallback to the in-process path
        self._fleet_cfg = FleetConfig.from_dict(
            getattr(config.method, "fleet", None)
        )
        self._fleet = None
        if self._fleet_cfg.enabled:
            if self._exp is None:
                raise ValueError(
                    "ppo.fleet.enabled requires ppo.exp.enabled: the "
                    "fleet produces chunks BEHIND the experience "
                    "transport (delivery/dedup/staleness stay its job)"
                )
            if mh.process_count() > 1:
                raise NotImplementedError(
                    "ppo.fleet with a multi-process learner mesh is not "
                    "supported yet (run one learner process; workers "
                    "scale horizontally instead)"
                )
            from trlx_tpu.fleet.coordinator import FleetCoordinator

            self._fleet = FleetCoordinator(
                self._fleet_cfg,
                self._fleet_cfg.resolved_dir(config.train.checkpoint_dir),
                owner=f"learner-{mh.process_index()}",
            )

    # -- model -----------------------------------------------------------

    def setup_model(self) -> None:
        cfg, base_params, self.model_type = self.load_base_model()
        self.seq2seq = self.config.model.model_arch_type == "seq2seq"
        k = self.config.model.num_layers_unfrozen
        if self.config.model.peft_config is not None:
            from trlx_tpu.models.peft import normalize_peft_config

            pc = normalize_peft_config(self.config.model.peft_config)
            if self.seq2seq and pc["peft_type"] != "LORA":
                # matches the reference matrix: its own peft tests skip
                # seq2seq x {PROMPT,PREFIX} (peft 0.3.0 bugs)
                raise NotImplementedError(
                    "seq2seq supports peft_type='LORA' only"
                )
            # with adapters the reference model is the disabled-adapter
            # base, not a hydra branch (reference peft contract)
            k = -1
            if (
                pc["peft_type"] in ("PROMPT_TUNING", "PREFIX_TUNING")
                and self.config.method.num_value_layers_unfrozen
            ):
                raise NotImplementedError(
                    "num_value_layers_unfrozen with prompt/prefix tuning is "
                    "not supported (the value-branch capture forward does "
                    "not thread virtual-token adapters)"
                )
        at = None
        if self.seq2seq:
            if k is not None and 0 < k < cfg.n_decoder_layer:
                at = cfg.n_decoder_layer - k
            self.model = Seq2SeqLMWithValueHead(cfg, branch_at=at)
        else:
            if k is not None and 0 < k < cfg.n_layer:
                at = cfg.n_layer - k
            nv = self.config.method.num_value_layers_unfrozen
            value_at = cfg.n_layer - nv if nv and 0 < nv < cfg.n_layer else None
            self.model = CausalLMWithValueHead(
                cfg, branch_at=at, value_branch_at=value_at
            )
        self.rng, key = jax.random.split(self.rng)
        params = self.model.init_params(key, base_params)
        params.update(getattr(self, "_loaded_aux", None) or {})
        params = self.attach_peft(params)
        self.params = shard_params(self.mesh, params)
        # frozen in-process reference: the top-k branch (hydra) or a full
        # copy when everything is trainable (reference :74-77); with LoRA
        # the disabled-adapter base IS the reference (peft parity)
        self.ref_params = shard_params(self.mesh, self.model.make_ref_params(self.params))

    def trainable_mask(self):
        lora_mask = self.lora_freeze_mask(self.params)
        if lora_mask is not None:
            return lora_mask
        if self.seq2seq:
            return self.make_seq2seq_freeze_mask(self.params)
        return self.make_freeze_mask(self.params)

    # -- loss ------------------------------------------------------------

    def loss(self, params, batch: PPORolloutBatch):
        """Recompute logprobs/values on stored rollouts, GAE on the fly,
        clipped PPO objective (parity: reference loss :127-204)."""
        method = self.config.method
        advantages, returns = gae_advantages_and_returns(
            batch.values, batch.rewards, gamma=method.gamma, lam=method.lam
        )
        pad = self.generate_settings.pad_token_id
        remat = resolve_remat(self.config.train.remat_policy)
        # chunked-from-hidden logprobs (train.logit_chunks): the full
        # [B, T, V] fp32 logits never materialize — the at-scale recipe
        chunks = self.config.train.logit_chunks
        if self.seq2seq:
            # query = encoder prompt; response = decoder ids (start token
            # + sampled tokens), parity: reference loss :146-173
            dec = batch.response_tensors
            enc_mask = (batch.query_tensors != pad).astype(jnp.int32)
            dec_mask = jnp.concatenate(
                [jnp.ones_like(dec[:, :1]), batch.response_mask.astype(jnp.int32)],
                axis=1,
            )
            out = self.model.forward_train(
                params, self.ref_params, batch.query_tensors, enc_mask, dec,
                dec_mask, remat=remat, compute_logits=chunks == 0,
            )
            if chunks:
                logprobs = chunked_logprobs(
                    self.model.logit_project_fn(params),
                    out["hidden_states"][:, :-1], dec[:, 1:], chunks,
                )
            else:
                logprobs = logprobs_of_labels(out["logits"][:, :-1], dec[:, 1:])
            values_pred = out["values"][:, :-1]
            return ppo_loss(
                logprobs=logprobs,
                values=values_pred,
                old_logprobs=batch.logprobs,
                old_values=batch.values,
                advantages=advantages,
                returns=returns,
                mask=batch.response_mask,
                cliprange=method.cliprange,
                cliprange_value=method.cliprange_value,
                vf_coef=method.vf_coef,
                is_weight=batch.is_weight,
            )
        P = batch.query_tensors.shape[1]
        N = batch.response_tensors.shape[1]
        tokens = jnp.concatenate([batch.query_tensors, batch.response_tensors], axis=1)
        attention_mask = (tokens != pad).astype(jnp.int32)
        # response positions count even where response==pad (mask handles it)
        attention_mask = attention_mask.at[:, P:].set(
            jnp.maximum(attention_mask[:, P:], batch.response_mask.astype(jnp.int32))
        )
        out = self.model.forward_train(
            params, self.ref_params, tokens, attention_mask, remat=remat,
            compute_logits=chunks == 0,
        )
        if chunks:
            # only response positions need logprobs: slice hidden BEFORE
            # projecting, so even the chunked vocab matmul runs over N
            # rows, not P+N
            logprobs = chunked_logprobs(
                self.model.logit_project_fn(params),
                out["hidden_states"][:, P - 1 : P + N - 1],
                tokens[:, P : P + N], chunks,
            )
        else:
            logprobs = logprobs_of_labels(out["logits"][:, P - 1 : P + N - 1], tokens[:, P : P + N])
        values_pred = out["values"][:, P - 1 : P + N - 1]
        return ppo_loss(
            logprobs=logprobs,
            values=values_pred,
            old_logprobs=batch.logprobs,
            old_values=batch.values,
            advantages=advantages,
            returns=returns,
            mask=batch.response_mask,
            cliprange=method.cliprange,
            cliprange_value=method.cliprange_value,
            vf_coef=method.vf_coef,
            # experience-transport staleness correction (exp.staleness.
            # mode: clip); None on every other path = weight 1
            is_weight=batch.is_weight,
        )

    # -- rollout engine --------------------------------------------------

    def _get_experience_fn(self, P: int, N: int, S: int):
        """Jitted score+assemble step: teacher-forced policy/ref/value
        forward, per-token KL penalty, terminal (or dense) reward add."""
        # logit_chunks is baked into the traced fn: it keys the cache
        key = (P, N, S, self.config.train.logit_chunks)
        if key in self._experience_fns:
            return self._experience_fns[key]
        model = self.model

        chunks = self.config.train.logit_chunks

        def seq2seq_fn(params, ref_params, enc_ids, enc_mask, dec_ids, response_mask, scores, scores_mask, kl_coef, row_valid, scale_div):
            scores = scores / jnp.maximum(scale_div, 1e-8)
            mask = response_mask.astype(jnp.float32)
            dec_mask = jnp.concatenate(
                [jnp.ones_like(dec_ids[:, :1]), response_mask.astype(jnp.int32)], axis=1
            )
            out = model.forward_train(
                params, ref_params, enc_ids, enc_mask, dec_ids, dec_mask,
                compute_logits=chunks == 0,
            )
            if chunks:
                from trlx_tpu.models.seq2seq import t5_logit_projection

                logprobs = chunked_logprobs(
                    model.logit_project_fn(params),
                    out["hidden_states"][:, :-1], dec_ids[:, 1:], chunks,
                ) * mask
                ref_logprobs = chunked_logprobs(
                    t5_logit_projection(ref_params, model.cfg),
                    out["ref_hidden"][:, :-1], dec_ids[:, 1:], chunks,
                ) * mask
            else:
                logprobs = logprobs_of_labels(out["logits"][:, :-1], dec_ids[:, 1:]) * mask
                ref_logprobs = logprobs_of_labels(out["ref_logits"][:, :-1], dec_ids[:, 1:]) * mask
            log_ratio = logprobs - ref_logprobs
            kl = jnp.exp(log_ratio) - 1 - log_ratio
            mean_kl, mean_kl_per_token = _masked_kl_stats(kl, row_valid)
            values = out["values"][:, :-1] * mask

            rewards = -kl_coef * log_ratio
            if S == 1:
                last = jnp.maximum(mask.sum(axis=1).astype(jnp.int32) - 1, 0)
                rewards = rewards + scores[:, 0:1] * jax.nn.one_hot(last, N, dtype=rewards.dtype)
            else:
                padded = jnp.zeros_like(rewards)
                padded = padded.at[:, :S].set(scores * scores_mask)
                rewards = rewards + padded
            rewards = rewards * mask

            batch_out = PPORolloutBatch(
                query_tensors=enc_ids,
                response_tensors=dec_ids,
                logprobs=logprobs,
                values=values,
                rewards=rewards,
                response_mask=mask,
            )
            return batch_out, {"mean_kl": mean_kl, "mean_kl_per_token": mean_kl_per_token}

        if self.seq2seq:
            self._experience_fns[key] = jax.jit(seq2seq_fn)
            return self._experience_fns[key]

        # causal path: composed from the SAME two jitted halves the
        # overlapped fast path uses (fwd + score inject), so the fallback
        # cannot numerically diverge from it
        fwd_fn = self._get_experience_fwd_fn(P, N)
        inject_fn = self._get_score_inject_fn(N, S)

        def fn(params, ref_params, tokens, attention_mask, response_mask, scores, scores_mask, kl_coef, row_valid, scale_div):
            pre_batch, kl_stats = fwd_fn(
                params, ref_params, tokens, attention_mask, response_mask,
                kl_coef, row_valid,
            )
            return inject_fn(pre_batch, scores, scores_mask, scale_div), kl_stats

        self._experience_fns[key] = fn
        return self._experience_fns[key]

    def _get_experience_fwd_fn(self, P: int, N: int):
        """The score-independent half of the experience step: teacher-forced
        policy/ref/value forward + per-token KL penalty. Dispatched BEFORE
        host scoring (it only reads device tensors the sampler produced),
        so the heaviest rollout compute overlaps decode + reward_fn — with
        a slow reward model the whole forward hides under scoring. The
        score half is `_get_score_inject_fn`."""
        key = ("fwd", P, N, self.config.train.logit_chunks)
        if key in self._experience_fns:
            return self._experience_fns[key]
        model = self.model

        chunks = self.config.train.logit_chunks

        def fn(params, ref_params, tokens, attention_mask, response_mask, kl_coef, row_valid):
            out = model.forward_train(
                params, ref_params, tokens, attention_mask,
                compute_logits=chunks == 0,
            )
            if chunks:
                from trlx_tpu.models.transformer import logit_projection

                logprobs_full = chunked_logprobs(
                    model.logit_project_fn(params),
                    out["hidden_states"][:, :-1], tokens[:, 1:], chunks,
                )
                ref_logprobs_full = chunked_logprobs(
                    logit_projection(ref_params),
                    out["ref_hidden"][:, :-1], tokens[:, 1:], chunks,
                )
            else:
                logprobs_full = logprobs_of_labels(out["logits"][:, :-1], tokens[:, 1:])
                ref_logprobs_full = logprobs_of_labels(out["ref_logits"][:, :-1], tokens[:, 1:])

            full_mask = attention_mask[:, 1:].astype(jnp.float32)
            log_ratio_full = (logprobs_full - ref_logprobs_full) * full_mask
            kl = jnp.exp(log_ratio_full) - 1 - log_ratio_full
            mean_kl, mean_kl_per_token = _masked_kl_stats(kl, row_valid)

            mask = response_mask.astype(jnp.float32)
            sl = slice(P - 1, P + N - 1)
            logprobs = logprobs_full[:, sl] * mask
            values = out["values"][:, sl] * mask
            log_ratio = log_ratio_full[:, sl] * mask

            batch_out = PPORolloutBatch(
                query_tensors=tokens[:, :P],
                response_tensors=tokens[:, P:],
                logprobs=logprobs,
                values=values,
                rewards=-kl_coef * log_ratio,  # scores injected later
                response_mask=mask,
            )
            return batch_out, {"mean_kl": mean_kl, "mean_kl_per_token": mean_kl_per_token}

        self._experience_fns[key] = jax.jit(fn)
        return self._experience_fns[key]

    def _get_score_inject_fn(self, N: int, S: int):
        """Apply host-computed scores to a KL-only rollout batch: scale,
        add terminal (S=1) or dense (S>1) rewards, re-mask."""
        key = ("inject", N, S)
        if key in self._experience_fns:
            return self._experience_fns[key]

        def fn(batch_out, scores, scores_mask, scale_div):
            scores = scores / jnp.maximum(scale_div, 1e-8)
            mask = batch_out.response_mask
            rewards = batch_out.rewards
            if S == 1:
                last = jnp.maximum(mask.sum(axis=1).astype(jnp.int32) - 1, 0)
                rewards = rewards + scores[:, 0:1] * (
                    jax.nn.one_hot(last, N, dtype=rewards.dtype)
                )
            else:
                padded = jnp.zeros_like(rewards)
                padded = padded.at[:, :S].set(scores * scores_mask)
                rewards = rewards + padded
            return batch_out.replace(rewards=rewards * mask)

        self._experience_fns[key] = jax.jit(fn)
        return self._experience_fns[key]

    def make_experience(self, num_rollouts: int = 1024, iter_count: int = 0) -> None:
        """Collect `num_rollouts` rollouts into the store (parity:
        reference make_experience :251-525; §3.2 call stack)."""
        # hang doctor: the rollout phase heartbeats per chunk inside the
        # loop, so a many-chunk collection stays healthy while a single
        # wedged generate/score goes silent past the rollout deadline
        with self.watchdog.phase("rollout", step=iter_count):
            self._make_experience(num_rollouts, iter_count)

    def _make_experience(self, num_rollouts: int, iter_count: int) -> None:
        if self._exp is not None:
            return self._make_experience_exp(num_rollouts, iter_count)
        logger.info("Collecting rollouts")
        self._rollout_abandoned = False
        # snapshot the prompt cursor: an abandoned (preempted) rollout
        # discards its partial store, so the cursor must rewind to here
        # or the resumed run would skip prompts that never trained. When
        # the cycle starts from a prefetched chunk (overlap_rollouts),
        # the rewind target is the cursor BEFORE that chunk's prompts
        # were pulled — the prefetch pull already advanced it.
        prompt_cursor_start = (
            self._prefetch_cursor_start
            if self._prefetched_gen is not None
            else self._prompt_batches_consumed
        )
        # guardrail `requeue` rewinds to here: the whole cycle's prompts
        # replay when its rollout batch turns out poisoned
        self._cycle_cursor_start = prompt_cursor_start
        self._finish_rollout_stats()  # flush any deferred previous-cycle stats
        clock = Clock()
        n_collected = 0
        accumulated_stats: List[Dict[str, float]] = []

        pbar = logging.progress(total=num_rollouts, desc="rollouts")
        # one-chunk lookahead: generation for chunk i+1 is DISPATCHED
        # before chunk i's host work (decode + reward_fn), so the device
        # samples while the host scores — the reference's rollout loop is
        # fully serial here (SURVEY §7 "host-device choreography")
        if self._prefetched_gen is not None:
            # cycle-level overlap: chunk 0 was dispatched ahead of the
            # previous cycle's fused optimization block and sampled
            # under it on-device (pre_optimization_hook)
            next_batch, next_gen, next_gen_time = self._prefetched_gen
            self._prefetched_gen = None
            self._prefetch_cursor_start = None
        else:
            next_batch = self._next_prompt_batch()
            rollout_generate_time = time()
            next_gen = self.generate(
                next_batch.input_ids, next_batch.attention_mask
            )
            next_gen_time = time() - rollout_generate_time
        chunk_rows = len(next_batch.input_ids) * mh.data_group_count(self.mesh)
        while n_collected < num_rollouts:
            self.watchdog.beat("rollout", step=iter_count)
            if self.chaos is not None:
                # chaos: the sampler wedges at the top of this chunk —
                # the rollout phase goes silent and the watchdog's
                # deadline (not the scheduler) must end the run
                self.chaos.stall("stall_rollout")
            # rollout collection dominates PPO wall-clock: a preemption
            # landing here must not wait out the remaining chunks (the
            # grace period would expire before the final save). Abandon
            # the rollout — learn()'s epoch-top check saves and exits.
            # Forced sync: every host runs this loop in lockstep.
            if self._should_stop(force=True):
                logger.warning(
                    "preemption during rollout collection: abandoning "
                    "after %d/%d rollouts", n_collected, num_rollouts,
                )
                # flags the store as truncated: the total_steps that
                # prepare_learning derives from it must not be persisted
                # as this run's real budget. The cursor rewinds to the
                # cycle start — this cycle's chunks never train, so the
                # resumed run must replay them.
                self._rollout_abandoned = True
                self._prompt_batches_consumed = prompt_cursor_start
                break
            stats: Dict[str, float] = {}
            batch, gen_out = next_batch, next_gen
            stats["time/rollout_generate"] = next_gen_time
            if n_collected + chunk_rows < num_rollouts:
                next_batch = self._next_prompt_batch()
                rollout_generate_time = time()
                next_gen = self.generate(
                    next_batch.input_ids, next_batch.attention_mask
                )
                next_gen_time = time() - rollout_generate_time
            else:
                next_batch, next_gen = None, None

            rollout_batch, rows_local = self._score_and_assemble(
                batch, gen_out, stats, iter_count, clock
            )
            accumulated_stats.append(stats)

            self.push_to_store(rollout_batch)
            n_collected += rows_local * mh.data_group_count(self.mesh)
            if hasattr(pbar, "update"):
                pbar.update(rows_local * mh.data_group_count(self.mesh))
            logger.info("[rollout %d / %d]", n_collected, num_rollouts)

        if not accumulated_stats:
            # rollout abandoned before the first chunk completed
            # (preemption): nothing to log, nothing pending
            if hasattr(pbar, "close"):
                pbar.close()
            return
        agg = {
            k: sum(xs[k] for xs in accumulated_stats) / len(accumulated_stats)
            for k in accumulated_stats[-1]
        }
        # ONE packed async device->host copy for every accumulated device
        # scalar, materialized lazily (post_backward / next
        # make_experience): on a remote-tunneled chip the blocking read
        # costs a full ~100ms round trip, which this way overlaps the
        # train step instead of extending the rollout phase
        if hasattr(pbar, "close"):
            pbar.close()
        self._deferred_rollout.stage(agg, step=iter_count, meta=self.kl_ctl.value)

    def _score_and_assemble(
        self, batch: PromptBatch, gen_out, stats: Dict[str, Any],
        iter_count: int, clock: Clock,
    ):
        """The score half of one rollout chunk: decode + reward_fn, the
        teacher-forced policy/ref/value forward, KL penalty + reward
        assembly, running-moment update and the chunk's stats (mutated
        into ``stats``). Shared verbatim by the direct rollout loop and
        the experience-transport producer, so the two paths cannot
        numerically diverge. Returns ``(rollout_batch, rows_local)``."""
        method = self.config.method
        prompt_tensors = np.asarray(batch.input_ids)
        seq_w = gen_out["sequences"].shape[1]
        N = gen_out["response_ids"].shape[1]
        P_width = prompt_tensors.shape[1]
        # a ragged multi-host chunk comes back PADDED per data group
        # with real_rows marking the group's real count — all row
        # bookkeeping below runs on real rows; the pad rows only
        # exist inside device arrays until the local slice
        real_local = gen_out.get("real_rows")
        B_local = (
            real_local
            if real_local is not None
            else gen_out["sequences"].shape[0] // mh.data_group_count(self.mesh)
        )

        # ONE packed device->host transfer for the three generation
        # outputs (a remote-tunneled chip pays ~100ms latency PER
        # transfer). The concatenate is enqueued FIRST — devices run
        # FIFO, so the DMA starts as soon as generation finishes and
        # streams while the experience forward below computes
        packed_dev = mh.local_rows(
            jnp.concatenate(
                [
                    gen_out["sequences"],
                    gen_out["response_ids"],
                    gen_out["response_mask"].astype(gen_out["sequences"].dtype),
                ],
                axis=1,
            )
        )
        try:
            packed_dev.copy_to_host_async()
        except Exception:
            pass

        # fast path: the score-INDEPENDENT half of the experience step
        # (policy/ref/value forward + KL penalty — the heaviest rollout
        # compute) is dispatched NOW, on the device tensors the sampler
        # just produced. It executes while the host decodes and scores
        # the samples; the tiny score-injection jit below completes the
        # rollout batch once reward_fn returns. Falls back to the
        # fused experience fn when host-side token rewrites (stop
        # sequences, seq2seq) or pad rows are needed.
        device_gen = (
            not self.seq2seq
            and not self.stop_sequences
            and B_local % self.local_ways() == 0
            # a padded multihost chunk (real_rows set — including the
            # divisible-but-widened case, where generate() padded up
            # to an already-compiled wider shape) must take the
            # host-scored path: the device fast path would build
            # pre_batch over the pad rows and mismatch the real-row
            # scores at injection
            and real_local is None
        )
        pre_batch = pre_kl_stats = None
        if device_gen:
            with self.mesh:
                fwd_fn = self._get_experience_fwd_fn(P_width, N)
                pre_batch, pre_kl_stats = fwd_fn(
                    self.params,
                    self.ref_params,
                    gen_out["sequences"].astype(jnp.int32),
                    jnp.concatenate(
                        [
                            gen_out["prompt_mask"].astype(jnp.int32),
                            gen_out["response_mask"].astype(jnp.int32),
                        ],
                        axis=1,
                    ),
                    gen_out["response_mask"].astype(jnp.int32),
                    jnp.float32(self.kl_ctl.value),
                    # device_gen only runs on unpadded batches: every
                    # row is valid
                    jnp.ones((gen_out["sequences"].shape[0],), jnp.float32),
                )

        packed = packed_dev[:B_local]  # drop per-group pad rows
        sequences = packed[:, :seq_w]
        response_ids = packed[:, seq_w : seq_w + N]
        response_mask = packed[:, seq_w + N :]
        P = prompt_tensors.shape[1]

        prompt_sizes = [P] * len(sequences)
        str_samples, str_prompts, str_outputs = self.decode(
            prompt_tensors, sequences, prompt_sizes, append_eos_token=True
        )

        rollout_score_time = time()
        all_scores = self._call_reward_fn(
            samples=str_samples,
            prompts=str_prompts,
            outputs=str_outputs,
            tokenizer=self.tokenizer,
            **(batch.metadata or {}),
        )
        stats["time/rollout_score"] = time() - rollout_score_time

        scores_list = [np.atleast_1d(np.asarray(s, np.float32)) for s in all_scores]
        S = max(len(s) for s in scores_list)
        scores = np.zeros((len(scores_list), S), np.float32)
        scores_mask = np.zeros((len(scores_list), S), np.float32)
        for i, s in enumerate(scores_list):
            scores[i, : len(s)] = s
            scores_mask[i, : len(s)] = 1.0

        if self.stop_sequences:
            # stop-sequence trimming changed the outputs: rebuild the
            # response tokens from the trimmed strings (the reference
            # re-tokenizes unconditionally, :345-365 — lossy for some
            # tokenizers, so here only when actually needed)
            outputs = self.tokenizer(str_outputs, add_special_tokens=False)["input_ids"]
            response_ids = np.full((len(outputs), N), self.generate_settings.pad_token_id, np.int32)
            response_mask = np.zeros((len(outputs), N), np.int32)
            for i, o in enumerate(outputs):
                o = o[:N]
                response_ids[i, : len(o)] = o
                response_mask[i, : len(o)] = 1
            if self.seq2seq:
                start = sequences[:, :1]  # decoder start token column
                sequences = np.concatenate([start, response_ids], axis=1)
            else:
                sequences = np.concatenate([prompt_tensors, response_ids], axis=1)

        if method.cliprange_reward:
            scores = np.clip(scores, -method.cliprange_reward, method.cliprange_reward)

        # local per-row sums -> one GLOBAL vector; the running-moment
        # update then reduces over every host's rows in-graph (the
        # reference all-gathers scores to rank 0 instead). A short
        # final chunk (prompt dataset smaller than chunk_size) may not
        # divide dp*fsdp — keep the tiny vector replicated then
        # (padding would bias the running reward moments). Multi-host
        # replication of per-group-DIFFERENT rows needs a host-side
        # allgather first, so every process places the same full
        # vector (parity: the reference pads across processes,
        # accelerate_ppo_trainer.py:292-300).
        local_sums = (scores * scores_mask).sum(axis=1)
        rows = len(local_sums) * mh.data_group_count(self.mesh)
        if rows % self.data_ways() == 0:
            score_sums = mh.global_from_local(
                local_sums, vector_sharding(self.mesh)
            )
        elif mh.is_multihost():
            score_sums = jax.device_put(
                np.asarray(
                    mh.allgather_group_rows(
                        local_sums.astype(np.float32), self.mesh
                    ),
                    np.float32,
                ),
                replicated_sharding(self.mesh),
            )
        else:
            score_sums = mh.global_from_local(
                local_sums, replicated_sharding(self.mesh)
            )
        if self.ref_mean is None:
            self.ref_mean = float(score_sums.mean())
            self.ref_std = float(score_sums.std())
        new_moments, scores_mean, scores_std = running_moments_update(
            self.running_moments, score_sums
        )
        # a NaN-poisoned chunk must not permanently poison the
        # running reward moments (they scale every later reward and
        # persist across checkpoints): keep the pre-chunk moments
        # when the chunk's sums are non-finite. The chunk's OWN
        # stats still report the poison, so the guardrails see it.
        keep = jnp.all(jnp.isfinite(score_sums))
        self.running_moments = jax.tree_util.tree_map(
            lambda n, o: jnp.where(keep, n, o),
            new_moments, self.running_moments,
        )
        # stats stay DEVICE scalars until the single packed fetch at
        # the end of make_experience (each host read costs a full
        # round-trip on a remote-tunneled chip)
        stats["rollout_scores/mean"] = scores_mean
        stats["rollout_scores/std"] = scores_std
        stats["rollout_scores/running_mean"] = self.running_moments.mean
        stats["rollout_scores/running_std"] = self.running_moments.std

        # reward scaling happens inside the experience fn: pass the
        # divisor as a device scalar instead of fetching the running
        # std to the host
        if method.scale_reward == "running":
            scale_div = self.running_moments.std
        elif method.scale_reward == "ref":
            scale_div = jnp.float32(max(self.ref_std, 1e-8))
        else:
            scale_div = jnp.float32(1.0)

        # pad rows to the data-parallel multiple for sharding; the
        # extra rows are trimmed off the rollout batch afterwards
        # (multi-host: every group pads the same B -> target, so the
        # global batch stays rectangular; pad rows repeat the last
        # real row, are excluded from KL stats via the row-validity
        # vector below, and are dropped before the store push)
        B = len(sequences)
        target = B + (-B) % self.local_ways()

        def rpad(x):
            return self.pad_rows(x, target)

        sharding = data_sharding(self.mesh)
        if device_gen:
            # the forward half has been executing since right after
            # generation; complete it with the host-computed scores
            with self.mesh:
                inject_fn = self._get_score_inject_fn(N, S)
                rollout_batch = inject_fn(
                    pre_batch,
                    mh.global_from_local(scores, sharding),
                    mh.global_from_local(scores_mask, sharding),
                    scale_div,
                )
            kl_stats = pre_kl_stats
        else:
            exp_fn = self._get_experience_fn(P, N, S)
            if self.seq2seq:
                args = (
                    rpad(prompt_tensors.astype(np.int32)),
                    rpad(np.asarray(batch.attention_mask, np.int32)),
                    rpad(sequences.astype(np.int32)),
                )
            else:
                attention_mask = np.concatenate(
                    [np.asarray(batch.attention_mask, np.int32), response_mask],
                    axis=1,
                )
                args = (
                    rpad(sequences.astype(np.int32)),
                    rpad(attention_mask),
                )
            with self.mesh:
                rollout_batch, kl_stats = exp_fn(
                    self.params,
                    self.ref_params,
                    *[mh.global_from_local(a, sharding) for a in args],
                    mh.global_from_local(rpad(response_mask), sharding),
                    mh.global_from_local(rpad(scores), sharding),
                    mh.global_from_local(rpad(scores_mask), sharding),
                    jnp.float32(self.kl_ctl.value),
                    # per-ROW validity (pad rows sit inside each data
                    # group's block of the global batch, so a prefix
                    # count can't mark them)
                    mh.global_from_local(
                        np.concatenate(
                            [np.ones(B, np.float32),
                             np.zeros(target - B, np.float32)]
                        ),
                        vector_sharding(self.mesh),
                    ),
                    scale_div,
                )
        if target != B and mh.is_multihost():
            # each group's pad rows sit inside the global batch; a
            # flat [:B] can't drop them. The chunk is tiny (only a
            # short FINAL chunk is ragged), so take the host
            # round-trip: local real rows -> allgather -> one
            # replicated, consistent global batch for the store
            rollout_batch = jax.tree_util.tree_map(
                lambda x: jax.device_put(
                    np.asarray(
                        mh.allgather_group_rows(
                            mh.local_rows(x)[:B], self.mesh
                        )
                    ),
                    replicated_sharding(self.mesh),
                ),
                rollout_batch,
            )
        elif target != B:
            # trim the sharding-pad rows ON DEVICE (the store keeps
            # device-resident rollouts; no host round-trip here)
            rollout_batch = jax.tree_util.tree_map(
                lambda x: x[:B], rollout_batch
            )

        # honest rollout accounting: pad emissions from finished
        # rows are NOT generated tokens — report mask-weighted real
        # tokens plus batch occupancy, and a truncation rate (rows
        # that ran to max_new_tokens without an EOS: a degenerate
        # policy that stops emitting EOS shows up here, and the
        # guardrails can trip on it via truncation_max)
        rm_np = np.asarray(response_mask)
        ri_np = np.asarray(response_ids)
        N_resp = rm_np.shape[1]
        real_toks = float(rm_np.sum())
        stats["rollout/real_tokens"] = real_toks
        stats["rollout/token_occupancy"] = real_toks / max(
            rm_np.shape[0] * N_resp, 1
        )
        eos_id = self.generate_settings.eos_token_id
        full_rows = rm_np.sum(axis=1) >= N_resp
        hit_eos = (
            ((ri_np == eos_id) & (rm_np > 0)).any(axis=1)
            if eos_id >= 0
            else np.zeros(len(full_rows), bool)
        )
        stats["rollout/truncation_rate"] = (
            float((full_rows & ~hit_eos).mean()) if len(full_rows) else 0.0
        )
        gstats = gen_out.get("gen_stats")
        if gstats is not None:
            g = {k: float(np.asarray(v)) for k, v in gstats.items()}
            # per-refill heartbeat accounting (host-side,
            # post-dispatch): with the decode engine a chunk is ONE
            # device dispatch, so the refills all land at once —
            # batch them into a single annotated beat (count=N)
            # instead of N same-instant beats that would evict the
            # other phases from the watchdog's bounded timeline
            refills = int(g.get("refills", 0))
            if refills:
                self.watchdog.beat(
                    "rollout", step=iter_count, count=refills
                )
            stats["rollout/engine_occupancy"] = g.get("occupancy", 0.0)
            stats["rollout/engine_refills"] = g.get("refills", 0.0)
            stats["rollout/engine_decode_steps"] = g.get("decode_steps", 0.0)
            if "drafted" in g:
                stats["rollout/spec_accept_rate"] = g["accepted"] / max(
                    g["drafted"], 1.0
                )
            if g.get("oom_truncated") or g.get("unserved"):
                logger.warning(
                    "gen_engine: page pool exhausted (%d lanes "
                    "truncated, %d prompts unserved) — raise "
                    "ppo.gen_engine.pool_pages",
                    int(g.get("oom_truncated", 0)),
                    int(g.get("unserved", 0)),
                )
        stats["time/rollout_time"] = clock.tick()
        stats["policy/sqrt_kl"] = jnp.sqrt(
            jnp.maximum(kl_stats["mean_kl"], 0.0)
        )
        stats["policy/kl_per_token"] = jnp.sqrt(
            jnp.maximum(kl_stats["mean_kl_per_token"], 0.0)
        )
        return rollout_batch, len(sequences)

    # -- experience transport (ppo.exp.*) --------------------------------

    def _exp_snapshot(self) -> Dict[str, Any]:
        """Replay state for a production lease, taken BEFORE the chunk
        touches anything: the trainer RNG key and the host-side reward
        accounting (running moments, ref stats). jax arrays are
        immutable, so holding references is free; restoring them makes
        a re-dispatched production bit-identical to the original
        attempt (same key -> same samples, same moments -> same reward
        scaling), which is what lets a producer death leave the
        consumed stream untouched. (The prompt batch itself is stashed
        on the lease at pull time — ``snap["batch"]`` — so a replay
        never re-pulls the stream.)"""
        return {
            "rng": self.rng,
            "running_moments": self.running_moments,
            "ref_mean": self.ref_mean,
            "ref_std": self.ref_std,
        }

    def _exp_restore_snapshot(self, snap: Dict[str, Any]) -> None:
        self.rng = snap["rng"]
        self.running_moments = snap["running_moments"]
        self.ref_mean = snap["ref_mean"]
        self.ref_std = snap["ref_std"]

    def _exp_wait(self, iter_count: int):
        """Bounded-wait callback for transport waits (back-pressure,
        lease expiry): beat the ``exp_wait`` watchdog phase and sleep
        one poll — a genuinely wedged queue then trips the watchdog
        deadline instead of hanging undiagnosed."""
        import time as _time

        def wait(poll_s: float) -> None:
            self.watchdog.beat("exp_wait", step=iter_count)
            _time.sleep(poll_s)

        return wait

    def _exp_produce(self, lease, iter_count: int, clock: Clock) -> None:
        """Produce one chunk under ``lease`` and deliver it: pull the
        prompt chunk (or consume the cycle's overlap prefetch), sample,
        score+assemble, then offer to the queue with the lease's
        heartbeats at each milestone. Re-dispatched leases (attempt > 1
        or a staleness re-dispatch) restore the replay snapshot first,
        so the regenerated chunk is bit-identical to the lost one."""
        exp = self._exp
        snap = lease.meta if lease.meta is not None else {}
        lease.meta = snap
        if snap.get("rng") is not None:
            # no-op on a fresh attempt (the snapshot IS the live state);
            # on a re-dispatch it rewinds the producer-side effects so
            # the replay is bit-identical
            self._exp_restore_snapshot(snap)
        stats: Dict[str, float] = {}
        if snap.get("gen") is not None:
            # replaying a chunk originally produced from the cycle
            # prefetch: the generation (old params, old key) cannot be
            # re-run — redeliver the retained samples wholesale
            batch, gen_out, gen_time, version = snap["gen"]
        elif self._prefetched_gen is not None:
            batch, gen_out, gen_time = self._prefetched_gen
            self._prefetched_gen = None
            self._prefetch_cursor_start = None
            version = self._prefetch_policy_version
            snap["gen"] = (batch, gen_out, gen_time, version)
        else:
            batch = snap.get("batch")
            if batch is None:
                batch = self._next_prompt_batch()
                snap["batch"] = batch
            if self._fleet is not None and self._fleet_produce(
                lease, snap, batch, iter_count
            ):
                # produced + delivered by a fleet worker (the learner
                # adopted its post-production snapshot); the transport
                # consumer loop takes it from here
                return
            exp.heartbeat(lease)
            t0 = time()
            gen_out = self.generate(batch.input_ids, batch.attention_mask)
            gen_time = time() - t0
            version = self._policy_version
        stats["time/rollout_generate"] = gen_time
        exp.heartbeat(lease)
        rollout_batch, rows_local = self._score_and_assemble(
            batch, gen_out, stats, iter_count, clock
        )
        exp.heartbeat(lease)
        if self.chaos is not None and self.chaos.consult("stale_flood"):
            # chaos: the chunk's staleness metadata is corrupted — its
            # recorded generation version lands far behind the live
            # policy, so the admission gate must reject (or clip) it
            version = version - (self._exp_cfg.staleness.max_staleness + 10)
        if self.chaos is not None and self.chaos.consult("queue_wedge"):
            # chaos: the learner stops draining — the next offers see a
            # full queue and the bounded back-pressure wait must ride
            # it out under exp_wait heartbeats
            exp.wedge()
        payload = (rollout_batch, stats, rows_local)
        with self.watchdog.phase("exp_wait", step=iter_count):
            exp.deliver(
                lease, version, payload, meta={"snapshot": snap},
                wait=self._exp_wait(iter_count),
            )
            if self.chaos is not None and self.chaos.consult(
                "duplicate_delivery"
            ):
                # chaos: the producer's retry races its own success —
                # the same finished chunk is delivered twice; consumer
                # dedup must drop the redelivery
                exp.deliver(
                    lease, version, payload, meta={"snapshot": snap},
                    wait=self._exp_wait(iter_count),
                )

    # -- rollout fleet (ppo.fleet.*) -------------------------------------

    def _fleet_post_publish(self, path: str) -> None:
        """Chaos seam for ``broadcast_corrupt``: fired once per landed
        weight-snapshot publish, AFTER the atomic rename — only the
        workers' manifest verification can catch the flipped bit."""
        if self.chaos is not None and self.chaos.consult("broadcast_corrupt"):
            self.chaos.corrupt_broadcast(path)

    def _fleet_degrade(self, why: str) -> bool:
        """Record a healthy->degraded transition and trip the ``fleet``
        guardrail signal (once per transition — a long outage must not
        spam the escalation ladder). Always returns False so callers
        can ``return self._fleet_degrade(...)`` out of the fleet path."""
        if self._fleet.note_degraded(why):
            self.guardrails.trip(
                FLEET_SIGNAL,
                f"rollout fleet degraded: {why} — falling back to "
                "in-process production (bit-equal to the fleet-less run)",
            )
        return False

    def _fleet_ready(self, iter_count: int) -> bool:
        """Evict silent workers, then gate on ``fleet.min_workers``.
        The FIRST production waits out ``fleet.startup_timeout_s`` for
        the fleet to register (workers launch in parallel with the
        learner's compile, so "not there yet" is the common case) — a
        fleet that never comes up degrades instead of wedging the run."""
        import time as _time

        fleet, cfg = self._fleet, self._fleet_cfg
        deadline = (
            None if fleet._waited_startup
            else _time.time() + cfg.startup_timeout_s
        )
        fleet._waited_startup = True
        while True:
            fleet.registry.evict_silent()
            if len(fleet.live_workers()) >= cfg.min_workers:
                return True
            if deadline is None or _time.time() >= deadline:
                return False
            self.watchdog.beat("rollout", step=iter_count)
            _time.sleep(cfg.poll_s)

    def _fleet_produce(
        self, lease, snap: Dict[str, Any], batch, iter_count: int
    ) -> bool:
        """Produce the leased chunk on the worker fleet: publish the
        policy snapshot if due, dispatch the prompt batch + replay
        snapshot to a live worker, watch its membership heartbeats
        while it generates, and hand the delivered payload to the
        transport under the learner's own lease. A worker that goes
        silent mid-chunk is evicted and the chunk re-dispatched with
        the SAME snapshot (bit-identical regeneration). Returns False
        — after tripping the ``fleet`` signal once per transition —
        when the fleet is below ``min_workers`` (or a dispatch timed
        out); the caller then produces the chunk in-process from the
        same snapshot, so degradation is invisible in the loss stream."""
        import time as _time

        from trlx_tpu.fleet import serde as fleet_serde

        fleet, cfg, exp = self._fleet, self._fleet_cfg, self._exp
        # publish before the readiness gate: workers that are still
        # attaching need the snapshot to produce anything at all. But a
        # DEGRADED fleet with no registered workers at all has no
        # consumers — skip the full-model snapshot (host copy + npz +
        # sha256 + fsync per policy version) until a registration
        # reappears, or a dead fleet taxes every remaining cycle
        if not fleet.degraded or fleet.registry.worker_records():
            fleet.ensure_published(
                self._policy_version,
                lambda: fleet_serde.params_to_arrays(self.params),
                post_publish=self._fleet_post_publish,
            )
        if not self._fleet_ready(iter_count):
            return self._fleet_degrade(
                f"{len(fleet.live_workers())} live workers < "
                f"fleet.min_workers={cfg.min_workers}"
            )
        fleet.note_recovered()
        chunk_id = lease.chunk_id

        def degrade_dispatched(why: str) -> bool:
            # abandon the outstanding dispatch: a later-rejoining
            # evicted worker must not burn a generation on a chunk the
            # learner is about to produce in-process, and its late
            # delivery must not linger to collide with a future
            # regeneration of the same id. The lease goes back to the
            # learner — IT is the producer from here on, and expiry
            # logs should say so
            fleet.clear_chunk(chunk_id)
            exp.reassign(lease, exp.owner)
            return self._fleet_degrade(why)
        # a previous incarnation/attempt may have left a delivery for
        # this seq (learner restart, staleness re-dispatch): the replay
        # contract makes a same-snapshot leftover bit-identical, but a
        # staleness regeneration must NOT consume the old samples —
        # clear and regenerate, which is correct for both
        fleet.clear_chunk(chunk_id)
        arrays, prompt_meta = fleet_serde.prompt_batch_to_arrays(batch)
        # self state == the replay snapshot at this point (a re-dispatch
        # restored it at the top of _exp_produce), so the wire snapshot
        # is exactly what an in-process production would consume
        wire_meta = {
            "iter_count": int(iter_count),
            "snapshot": fleet_serde.snapshot_to_wire(self._exp_snapshot()),
            "prompt_metadata": prompt_meta,
        }
        tried: Tuple[str, ...] = ()
        worker = fleet.select_worker()
        if worker is None:
            return self._fleet_degrade("no dispatchable worker")
        attempt = fleet.next_attempt(chunk_id)
        valid_attempts = {attempt}
        exp.reassign(lease, worker)
        fleet.dispatch(chunk_id, attempt, worker, wire_meta, arrays)
        deadline = _time.time() + cfg.dispatch_timeout_s
        # delivery is polled every tick, but the membership scan
        # (dir listing + one JSON parse per worker record) only needs
        # the TTL's resolution — on a shared/remote filesystem the
        # difference is thousands of metadata reads per chunk
        scan_every = max(cfg.worker_ttl_s / 4.0, cfg.poll_s)
        next_scan = 0.0
        while True:
            self.watchdog.beat("rollout", step=iter_count)
            exp.heartbeat(lease)
            msg = fleet.poll_delivery(chunk_id)
            if msg is not None:
                if int(msg[0].get("attempt", -1)) in valid_attempts:
                    break
                # a lingering worker's late delivery from an attempt
                # ABANDONED before this production (a staleness
                # regeneration reuses the chunk id with a NEW snapshot):
                # consuming it would replay the exact payload the gate
                # refused. Drop the payload only — the outstanding
                # assignment stays so the current worker isn't stranded
                fleet.clear_delivery(chunk_id)
                msg = None
            if _time.time() >= next_scan:
                next_scan = _time.time() + scan_every
                fleet.registry.evict_silent()
                lost = worker not in fleet.live_workers()
            else:
                lost = False
            if lost:
                # the producing worker died / partitioned / got
                # quarantined mid-chunk: re-dispatch elsewhere with the
                # same snapshot (regeneration is bit-identical, so the
                # consumed stream never sees the loss)
                tried = tried + (worker,)
                if len(fleet.live_workers()) < cfg.min_workers:
                    return degrade_dispatched(
                        f"worker {worker!r} lost mid-chunk {chunk_id} "
                        "and the live fleet fell below min_workers"
                    )
                worker = (
                    fleet.select_worker(exclude=tried)
                    or fleet.select_worker()  # all live ones tried: retry the set
                )
                if worker is None:
                    return degrade_dispatched(
                        f"no dispatchable worker for chunk {chunk_id}"
                    )
                attempt = fleet.next_attempt(chunk_id)
                valid_attempts.add(attempt)
                exp.reassign(lease, worker)
                fleet.dispatch(chunk_id, attempt, worker, wire_meta, arrays)
                deadline = _time.time() + cfg.dispatch_timeout_s
                continue
            if _time.time() >= deadline:
                # alive-but-wedged worker: the membership TTL never
                # fires, so this bound is the backstop. Evict (flap-
                # tracked) and degrade; the in-process regeneration is
                # bit-identical via the replay snapshot.
                fleet.registry.evict(
                    worker,
                    f"dispatch timeout: chunk {chunk_id} undelivered "
                    f"after {cfg.dispatch_timeout_s:g}s",
                )
                return degrade_dispatched(
                    f"chunk {chunk_id} timed out on worker {worker!r}"
                )
            _time.sleep(cfg.poll_s)
        meta_d, arrays_d = msg
        # a consumed delivery breaks the producing worker's eviction
        # streak — flap quarantine means consecutive evictions, not
        # cumulative-forever
        fleet.registry.note_healthy(str(meta_d.get("worker", "")))
        rollout_batch = fleet_serde.rollout_from_arrays(arrays_d)
        stats: Dict[str, Any] = dict(meta_d.get("stats") or {})
        rows_local = int(meta_d["rows_local"])
        version = int(meta_d["policy_version"])
        # adopt the worker's post-production snapshot: the learner's
        # RNG/moments chain continues exactly as if it had produced the
        # chunk in-process — that adoption is what keeps the fleet path
        # bit-equal to ppo.exp.enabled
        self._exp_restore_snapshot(
            fleet_serde.snapshot_from_wire(meta_d["post_snapshot"], self.rng)
        )
        exp.heartbeat(lease)
        with self.watchdog.phase("exp_wait", step=iter_count):
            exp.deliver(
                lease, version, (rollout_batch, stats, rows_local),
                meta={"snapshot": snap}, wait=self._exp_wait(iter_count),
            )
        fleet.clear_chunk(chunk_id)
        return True

    def _shutdown_producers(self) -> None:
        """learn()-exit hook (trainer/base.py): write the fleet's
        clean-finish flag ONLY when the step budget is actually done —
        a preemption / stall / crash exit leaves the workers alive for
        the relaunched learner's membership-epoch re-attach handshake."""
        if self._fleet is None:
            return
        total = getattr(self, "total_steps", None)
        budget = self.config.train.total_steps if total is None else total
        if self.iter_count >= budget:
            self._fleet.shutdown("train budget reached")
            logger.info(
                "fleet: clean finish — %s", self._fleet.stats_summary()
            )
        else:
            logger.info(
                "fleet: learner exiting at step %d < %d with the fleet "
                "left ATTACHED (workers re-register on the relaunch's "
                "membership epoch)", self.iter_count, budget,
            )

    def _make_experience_exp(self, num_rollouts: int, iter_count: int) -> None:
        """The experience-transport rollout loop: the in-process PPO
        trainer acting as the first producer/consumer pair behind the
        leased queue (ROADMAP item 1's remote rollout fleet plugs in
        behind the same seam). Fault-free it is bit-equal to the direct
        loop: the same prompt pulls, the same RNG splits per generate,
        the same score math (shared ``_score_and_assemble``), consumed
        in the same order (the queue is in-order by construction)."""
        import time as _time

        logger.info("Collecting rollouts (experience transport)")
        self._rollout_abandoned = False
        exp = self._exp
        prompt_cursor_start = (
            self._prefetch_cursor_start
            if self._prefetched_gen is not None
            else self._prompt_batches_consumed
        )
        self._cycle_cursor_start = prompt_cursor_start
        self._finish_rollout_stats()
        clock = Clock()
        n_collected = 0
        accumulated_stats: List[Dict[str, float]] = []
        pbar = logging.progress(total=num_rollouts, desc="rollouts")
        scfg = self._exp_cfg.staleness
        pending_redispatch = None  # a reclaimed/re-leased chunk to produce
        while n_collected < num_rollouts:
            self.watchdog.beat("rollout", step=iter_count)
            if self.chaos is not None:
                # chaos: same wedge site as the direct loop — the
                # producer stalls at the top of a chunk and the
                # watchdog deadline must end the run
                self.chaos.stall("stall_rollout")
            if self._should_stop(force=True):
                logger.warning(
                    "preemption during rollout collection: abandoning "
                    "after %d/%d rollouts", n_collected, num_rollouts,
                )
                self._rollout_abandoned = True
                self._prompt_batches_consumed = prompt_cursor_start
                # in-flight chunks and leases never train: void them so
                # the resumed run's replayed prompts produce fresh
                # chunks under a new epoch
                exp.abort_epoch()
                break
            chunk = exp.poll()
            if chunk is None:
                lease = pending_redispatch
                pending_redispatch = None
                if lease is None:
                    gap = exp.queue.next_undelivered()
                    gap_lease = exp.leases.get((exp.queue.epoch, gap))
                    if gap_lease is not None:
                        # the next in-order chunk is leased but not
                        # delivered: its producer died (or is slow).
                        # Wait out the lease TTL under the exp_wait
                        # phase, then reclaim + re-dispatch.
                        with self.watchdog.phase("exp_wait", step=iter_count):
                            while True:
                                reclaimed = exp.reclaim_expired()
                                if reclaimed:
                                    lease = reclaimed[0]
                                    break
                                self.watchdog.beat(
                                    "exp_wait", step=iter_count
                                )
                                _time.sleep(self._exp_cfg.wait_poll_s)
                    else:
                        lease = exp.begin_chunk(snapshot=self._exp_snapshot())
                        if self.chaos is not None and self.chaos.consult(
                            "worker_death_mid_lease"
                        ):
                            # chaos: the producer dies right after
                            # taking the lease — before any side
                            # effect. Heartbeats stop; the consumer
                            # loop above waits out the TTL and
                            # re-dispatches the chunk.
                            exp.producer_died(lease)
                            continue
                self._exp_produce(lease, iter_count, clock)
                continue
            verdict, staleness = exp.admit(chunk, self._policy_version)
            if staleness > scfg.max_staleness and self.guardrails.enabled:
                self.guardrails.trip(
                    STALENESS_SIGNAL,
                    f"chunk {chunk.chunk_id} is {staleness} policy "
                    f"versions stale (> max {scfg.max_staleness}; "
                    f"verdict: {verdict}) — the rollout producers are "
                    "falling behind the learner",
                )
            if verdict == exp_transport.REJECT:
                # over-stale: drop the delivery and regenerate the
                # chunk's prompts with the current policy (the replay
                # snapshot keeps the regeneration deterministic). A
                # chunk born from the cycle prefetch retains its old
                # samples in snap["gen"] for lost-delivery replay —
                # but a staleness reject must NOT redeliver those
                # verbatim (same samples, same version -> an infinite
                # reject/redeliver loop): strip the retained
                # generation, keep its prompt batch, so the produce
                # path re-samples with the live policy and stamps the
                # live version
                snap = chunk.meta.get("snapshot")
                if snap is not None and snap.get("gen") is not None:
                    snap["batch"] = snap["gen"][0]
                    snap["gen"] = None
                pending_redispatch = exp.redispatch_rejected(chunk)
                continue
            rollout_batch, stats, rows_local = chunk.payload
            if verdict == exp_transport.ADMIT_CLIP:
                rollout_batch = self._apply_staleness_clip(rollout_batch)
                stats["exp/staleness_clipped"] = 1.0
            elif scfg.mode == "clip":
                # uniform store pytree structure: every batch of a
                # clip-mode run carries weights (fresh chunks at 1)
                rollout_batch = rollout_batch.replace(
                    is_weight=jnp.ones_like(rollout_batch.response_mask)
                )
            stats["exp/staleness"] = float(staleness)
            self.push_to_store(rollout_batch)
            exp.committed(chunk)
            accumulated_stats.append(stats)
            n_collected += rows_local * mh.data_group_count(self.mesh)
            if hasattr(pbar, "update"):
                pbar.update(rows_local * mh.data_group_count(self.mesh))
            logger.info("[rollout %d / %d]", n_collected, num_rollouts)

        if not accumulated_stats:
            if hasattr(pbar, "close"):
                pbar.close()
            return
        # aggregate over the UNION of keys: conditional keys (a clip
        # admission mid-cycle) must not vanish just because the final
        # chunk was fresh — that telemetry is exactly what the
        # staleness ledger exists to surface
        all_keys = [k for xs in accumulated_stats for k in xs]
        agg = {
            k: sum(xs.get(k, 0.0) for xs in accumulated_stats) / len(accumulated_stats)
            for k in dict.fromkeys(all_keys)
        }
        # transport health ledger rides the same deferred stage as the
        # rollout stats (host ints — free)
        agg.update({
            f"exp/{k}": float(v)
            for k, v in exp.stats_summary().items()
            if isinstance(v, (int, float))
        })
        if self._fleet is not None:
            # fleet health rides the same ledger: dispatches/evictions/
            # quarantines/degradations per cycle, all host ints
            agg.update({
                f"fleet/{k}": float(v)
                for k, v in self._fleet.stats_summary().items()
                if isinstance(v, (int, float))
            })
        if hasattr(pbar, "close"):
            pbar.close()
        self._deferred_rollout.stage(agg, step=iter_count, meta=self.kl_ctl.value)

    def _apply_staleness_clip(self, rollout_batch: PPORolloutBatch):
        """IMPACT-style admission correction for an over-stale chunk
        (``exp.staleness.mode: clip``, arXiv:1912.00167): recompute
        logprobs/values with the CURRENT policy (the proximal recompute
        — the PPO ratio is then measured against the policy the
        optimization epoch actually starts from) and thread the
        behavior mismatch into the surrogate as a per-token CLIPPED
        importance weight rho = clip(pi_now/pi_behavior, 1±clip_c)
        (``ops/ppo.py`` ``is_weight``). The stored rewards keep their
        generation-time KL penalty (the terminal score is
        policy-independent)."""
        pad = self.generate_settings.pad_token_id
        q = jnp.asarray(rollout_batch.query_tensors, jnp.int32)
        r = jnp.asarray(rollout_batch.response_tensors, jnp.int32)
        P, N = q.shape[1], r.shape[1]
        tokens = jnp.concatenate([q, r], axis=1)
        attention_mask = (tokens != pad).astype(jnp.int32)
        resp_mask = jnp.asarray(rollout_batch.response_mask)
        attention_mask = attention_mask.at[:, P:].set(
            jnp.maximum(attention_mask[:, P:], resp_mask.astype(jnp.int32))
        )
        with self.mesh:
            fwd_fn = self._get_experience_fwd_fn(P, N)
            pre_batch, _ = fwd_fn(
                self.params, self.ref_params, tokens, attention_mask,
                resp_mask.astype(jnp.int32),
                jnp.float32(self.kl_ctl.value),
                jnp.ones((tokens.shape[0],), jnp.float32),
            )
        c = self._exp_cfg.staleness.clip_c
        mask = resp_mask.astype(jnp.float32)
        rho = jnp.exp(pre_batch.logprobs - rollout_batch.logprobs)
        is_weight = jnp.clip(rho, 1.0 - c, 1.0 + c) * mask + (1.0 - mask)
        return rollout_batch.replace(
            logprobs=pre_batch.logprobs,
            values=pre_batch.values,
            is_weight=is_weight,
        )

    def _extra_consistency_checks(self) -> None:
        """Every host must hold the SAME experience-transport consumer
        cursor — a drifted cursor means hosts silently trained
        different chunks. Asserted through ``multihost.cursor_consensus``
        at the guardrails consistency cadence; disagreement trips the
        ladder like any other divergence."""
        if self._exp is None or not self.guardrails.enabled:
            return
        result = mh.cursor_consensus(
            "exp", self._exp.queue.epoch, self._exp.queue.cursor
        )
        if not result.agree:
            self.guardrails.trip(
                "consistency",
                f"experience-transport cursor diverged at step "
                f"{self.iter_count}: {result.detail}",
            )

    def _finish_rollout_stats(self) -> None:
        """Materialize + log the deferred make_experience stats (sets
        self.mean_kl for the KL controller; feeds the guardrails the
        rollout-side health signals). Idempotent."""
        for stats, step, kl_ctl_value in self._deferred_rollout.flush():
            stats["kl_ctl_value"] = kl_ctl_value
            self.mean_kl = stats["policy/sqrt_kl"] ** 2
            if self.guardrails.enabled:
                self.guardrails.observe_rollout(
                    kl=self.mean_kl,
                    kl_target=getattr(self.kl_ctl, "target", None),
                    reward_mean=stats.get("rollout_scores/mean"),
                    running_mean=stats.get("rollout_scores/running_mean"),
                    running_std=stats.get("rollout_scores/running_std"),
                    truncation_rate=stats.get("rollout/truncation_rate"),
                )
            self._tracker_log(stats, step=step)

    # -- loop hooks ------------------------------------------------------

    def setup_rollout_logging(self, config) -> None:
        import json
        import os
        import uuid

        assert os.path.isdir(config.train.rollout_logging_dir)
        self.run_id = f"run-{uuid.uuid4()}"
        self.rollout_logging_dir = os.path.join(
            config.train.rollout_logging_dir, self.run_id
        )
        os.mkdir(self.rollout_logging_dir)
        with open(os.path.join(self.rollout_logging_dir, "config.json"), "w") as f:
            f.write(json.dumps(config.to_dict(), indent=2))

    def add_prompt_pipeline(self, pipeline) -> None:
        # the pipeline is retained so guardrail interventions (requeue /
        # rollback) can rebuild the stream and replay untrained prompts
        self._prompt_pipeline = pipeline
        self._build_prompt_iterator()
        self._fast_forward_prompts()

    def _build_prompt_iterator(self) -> None:
        """(Re)create the prompt stream from position zero. The loader
        draws its shuffles from the config seed, so a rebuild replays
        the exact chunk sequence — fast-forwarding then restores any
        cursor, including one BEHIND the live position (streams only
        advance; rewind = rebuild + replay).

        TOPOLOGY-INVARIANT: the stream is one GLOBAL shuffle over the
        full prompt list, chunked at the global chunk_size; each data
        group then collates only its own rows of every global chunk
        (`_GroupChunkLoader`). The chunk sequence — and therefore the
        saved `prompt_batches_consumed` cursor — means the SAME prompts
        regardless of how many hosts/data groups the run has, so an
        elastic resume onto a different topology neither drops nor
        double-trains a prompt. (The previous scheme shuffled each
        group's strided slice independently, which re-partitioned the
        stream whenever the group count changed.) Single-group runs are
        byte-identical to the old behavior: same loader, same RNG
        stream, no slicing."""
        pipeline = self._prompt_pipeline
        # drop_last keeps chunk shapes static: one compiled sampler;
        # a prompt list smaller than one chunk degrades to a single
        # kept-ragged chunk (the historical len(loader)==0 fallback)
        chunk, drop_last = self.config.method.chunk_size, True
        if len(pipeline) < chunk:
            chunk, drop_last = len(pipeline), False
        group, group_count = mh.data_group_info(self.mesh)
        if group_count > 1:
            loader = _GroupChunkLoader(
                pipeline, chunk, pipeline.collate, group, group_count,
                seed=self.config.train.seed, drop_last=drop_last,
            )
        else:
            loader = pipeline.create_loader(
                chunk, shuffle=True, drop_last=drop_last,
                seed=self.config.train.seed,
            )
        self.prompt_iterator = infinite_loader(loader)
        self._prompt_batches_consumed = 0

    def _rewind_prompt_stream(self, cursor: int) -> None:
        """Rebuild the stream and advance it so the NEXT pull is chunk
        ``cursor`` — the replay path for prompts whose rollouts never
        trained (host-side batch pulls only: no generation, no scoring)."""
        self._build_prompt_iterator()
        for _ in range(cursor):
            next(self.prompt_iterator)
        self._prompt_batches_consumed = cursor

    def _reset_data_stream(self) -> None:
        """Guardrail-rollback hook: stream back to zero; the subsequent
        load() fast-forwards to the checkpoint's saved cursor."""
        if getattr(self, "_prompt_pipeline", None) is None:
            return
        self._resume_prompt_cursor = 0
        if self._exp is not None:
            # in-flight transport chunks belong to the discarded live
            # state; the load() that follows restores the committed
            # cursor on top of the bumped epoch
            self._exp.abort_epoch()
        self._build_prompt_iterator()

    def _requeue_poisoned_batch(self) -> bool:
        """Guardrail `requeue` rung: drop the poisoned rollout store and
        rewind the prompt stream to the cycle start, so the same prompts
        are re-collected with the CURRENT policy (their poisoned
        rollouts never train; recomputed importance ratios make the
        replay sound — IMPACT, arXiv:1912.00167)."""
        start = getattr(self, "_cycle_cursor_start", None)
        if len(self.store) == 0 or start is None:
            return False
        self._abandon_prefetch()
        if self._exp is not None:
            # the rebuilt stream replays this cycle's prompts: void the
            # transport's in-flight chunks/leases under a new epoch so
            # an old delivery can never shadow a replayed one
            self._exp.abort_epoch()
        self.store.clear_history()
        self._rewind_prompt_stream(start)
        logger.warning(
            "guardrails: discarded the poisoned rollout batch; prompt "
            "stream rewound to chunk %d for replay", start,
        )
        return True

    def _reward_fallback_value(self) -> float:
        """`resilient_io.fallback_reward: hold_mean` — substitute the
        running-moments mean while the reward service is down, keeping
        the reward distribution stationary instead of injecting zeros."""
        try:
            v = float(np.asarray(self.running_moments.mean))
        except Exception:
            return 0.0
        return v if np.isfinite(v) else 0.0

    def _next_prompt_batch(self) -> PromptBatch:
        batch = next(self.prompt_iterator)
        self._prompt_batches_consumed += 1
        return batch

    # -- cross-cycle rollout prefetch (method.overlap_rollouts) ----------

    def pre_optimization_hook(self, will_continue: bool) -> None:
        """Dispatch the FIRST chunk of the next cycle's generation ahead
        of the fused optimization block, with the pre-update params.
        Device FIFO runs the generation before the train scan — whose
        buffer donation invalidates these params for any LATER dispatch
        — and the host decodes+scores the chunk while the block trains.
        The samples are one policy update stale, which PPO's importance
        ratio absorbs: the teacher-forced scorer recomputes old_logprobs
        with the updated params when the chunk is consumed, so the ratio
        stays self-consistent with the optimization epoch's start."""
        if not self.config.method.overlap_rollouts or not will_continue:
            return
        if self._prefetched_gen is not None or not hasattr(self, "prompt_iterator"):
            return
        cursor0 = self._prompt_batches_consumed
        batch = self._next_prompt_batch()
        t0 = time()
        with self.watchdog.phase("rollout", step=self.iter_count):
            gen = self.generate(batch.input_ids, batch.attention_mask)
        self._prefetched_gen = (batch, gen, time() - t0)
        self._prefetch_cursor_start = cursor0
        # staleness metadata: the prefetched chunk's samples belong to
        # the PRE-update policy — it is consumed one optimizer cycle
        # later at exactly staleness 1 (which the admission gate's
        # default max_staleness admits untouched)
        self._prefetch_policy_version = self._policy_version

    def _abandon_prefetch(self) -> None:
        """Drop an in-flight prefetched chunk and rewind the prompt
        cursor: its rollouts never train (run ending / preempted), so a
        resumed run must replay those prompts."""
        if self._prefetched_gen is None:
            return
        self._prefetched_gen = None
        self._prompt_batches_consumed = self._prefetch_cursor_start
        self._prefetch_cursor_start = None

    def _fast_forward_prompts(self) -> None:
        """Resume: advance the prompt stream to the saved cursor. The
        loader's shuffle RNG is stateful per epoch, so replaying `skip`
        host-side batch pulls (cheap: pre-tokenized collation, no
        generation) reproduces the exact data order the killed run would
        have continued with."""
        skip = self._resume_prompt_cursor - self._prompt_batches_consumed
        if skip <= 0 or not hasattr(self, "prompt_iterator"):
            return
        logger.info(
            "resume: fast-forwarding the prompt stream by %d chunks to "
            "restore the rollout data order", skip,
        )
        for _ in range(skip):
            next(self.prompt_iterator)
        self._prompt_batches_consumed += skip

    def _extra_fingerprint(self):
        """Consistency-watchdog extras: the rollout-data cursor and the
        KL controller — the two pieces of host-side PPO state that MUST
        advance in lockstep across hosts (a drifted cursor silently
        trains different prompts per host)."""
        out = {
            "prompt_cursor": float(self._prompt_batches_consumed),
            "kl_ctl": float(self.kl_ctl.value),
        }
        if self._exp is not None:
            # the transport's committed consumer position must advance
            # in lockstep too (a drifted cursor = hosts training
            # different chunks); also asserted dedicatedly through
            # multihost.cursor_consensus in _extra_consistency_checks
            out["exp_epoch"] = float(self._exp.queue.epoch)
            out["exp_cursor"] = float(self._exp.queue.cursor)
        return out

    # -- resumable state -------------------------------------------------

    def _extra_state(self):
        rm = self.running_moments
        state = {
            "kl_ctl_value": float(self.kl_ctl.value),
            "mean_kl": float(self.mean_kl),
            "ref_mean": None if self.ref_mean is None else float(self.ref_mean),
            "ref_std": None if self.ref_std is None else float(self.ref_std),
            "running_moments": {
                "mean": float(rm.mean), "var": float(rm.var),
                "std": float(rm.std), "count": float(rm.count),
            },
            # an in-flight prefetched chunk has NOT trained: persist the
            # cursor from before its pull, so a resume from this
            # checkpoint replays those prompts instead of skipping them
            "prompt_batches_consumed": (
                self._prefetch_cursor_start
                if self._prefetched_gen is not None
                else self._prompt_batches_consumed
            ),
            # the cursor counts GLOBAL chunks of the topology-invariant
            # stream (this marker lets a restore distinguish cursors
            # saved under the old per-group-shuffle scheme)
            "prompt_stream": "global-chunks-v1",
        }
        if self._exp is not None:
            # the experience-transport consumer cursor, committed INSIDE
            # the atomic checkpoint (state.json rides the integrity
            # manifest): a resume replays exactly the unconsumed chunks
            # — produced-but-unconsumed ones regenerate from the
            # group-invariant prompt stream. Invariant (verify_ckpt.py's
            # torn-commit detector): cursor <= prompt_batches_consumed,
            # every committed chunk consumed a prompt pull.
            state["exp_queue"] = {
                **self._exp.state_dict(),
                "policy_version": self._policy_version,
                "staleness_mode": self._exp_cfg.staleness.mode,
            }
        if self._fleet is not None:
            # membership epoch + last broadcast version, committed by
            # the SAME atomic state.json write as the exp cursor —
            # verify_ckpt.py's torn-commit detector holds the pair to
            # the publish-cadence invariant (a cursor referencing a
            # policy the committed snapshot never broadcast is torn)
            state["fleet"] = self._fleet.state()
        return state

    def _restore_extra_state(self, state) -> None:
        from trlx_tpu.ops.common import RunningMoments

        if "kl_ctl_value" in state:
            self.kl_ctl.value = state["kl_ctl_value"]
        self.mean_kl = state.get("mean_kl", 0.0)
        self.ref_mean = state.get("ref_mean", self.ref_mean)
        self.ref_std = state.get("ref_std", self.ref_std)
        rm = state.get("running_moments")
        if rm:
            self.running_moments = RunningMoments(
                mean=jnp.float32(rm["mean"]), var=jnp.float32(rm["var"]),
                std=jnp.float32(rm["std"]), count=jnp.float32(rm["count"]),
            )
        eq = state.get("exp_queue")
        if eq and self._exp is not None:
            self._exp.load_state_dict(eq)
            self._policy_version = int(eq.get("policy_version", 0))
        if self._fleet is not None:
            # the restore may have moved _policy_version backwards
            # (rollback): drop the publish cursor so the next cycle
            # rebroadcasts the restored params — otherwise workers keep
            # the rolled-back-over weights and their chunks admit as
            # non-stale (generation version ahead of the learner's)
            self._fleet.reset_published()
        self._resume_prompt_cursor = state.get("prompt_batches_consumed", 0)
        if (
            self._resume_prompt_cursor
            and state.get("prompt_stream") != "global-chunks-v1"
            and mh.data_group_count(self.mesh) > 1
        ):
            # pre-elastic multihost checkpoints counted chunks of
            # per-group shuffled streams; the invariant stream replays
            # a (deterministic) different partitioning from the same
            # cursor — continue, but say so
            logger.warning(
                "restored prompt cursor %d predates the "
                "topology-invariant stream: the replayed chunk "
                "composition differs from the saving run's on multi-"
                "group meshes", self._resume_prompt_cursor,
            )
        self._fast_forward_prompts()

    def prepare_learning(self) -> None:
        self.eval_dataloader = mh.shard_pipeline(self.eval_pipeline, self.mesh).create_loader(
            max(self.config.method.chunk_size // mh.data_group_count(self.mesh), 1)
        )
        # the restored iter_count keys the deferred rollout-stats flush:
        # without it a resumed run logs its first rollout at step 0 and
        # breaks tracker-step monotonicity
        self.make_experience(self.config.method.num_rollouts, self.iter_count)
        self.n_inner_epochs = self.config.method.ppo_epochs
        n_batches = len(self.store) // self.config.train.batch_size
        self.total_steps = min(
            self.config.train.epochs * self.n_inner_epochs * max(n_batches, 1),
            self.config.train.total_steps,
        )

    def create_train_dataloader(self):
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True, drop_last=True,
            seed=self.config.train.seed + self.iter_count,
        )

    def post_backward_callback(self) -> None:
        # flush the deferred rollout stats first: they carry the mean KL
        # this controller update consumes (by now the async device->host
        # copy has landed under the train step, so this is a free read)
        self._finish_rollout_stats()
        self.kl_ctl.update(self.mean_kl, n_steps=self.config.train.batch_size)

    def _fused_epoch_batch(self):
        # the rollout store is a rectangular (device-resident) pytree:
        # the whole ppo_epochs x minibatch loop can run as one fused scan
        return self.store.fused_epoch_source()

    def post_epoch_callback(self) -> None:
        if self.log_rollouts:
            self.store.export_history(self.rollout_logging_dir, self.tokenizer)
        self.store.clear_history()
        self.make_experience(self.config.method.num_rollouts, self.iter_count)
