"""ILQL trainer: offline Q-learning from reward-labeled samples.

Parity: /root/reference/trlx/trainer/accelerate_ilql_trainer.py:30-255
(module-level `make_experience` tokenizing samples into an
ILQLRolloutStorage with the normalized return on the final action token)
and modeling_ilql.py (loss via ILQLConfig, target-Q Polyak sync every
`steps_for_target_q_sync` steps, advantage-shaped generation).
"""

from __future__ import annotations

from typing import List, Optional, Union

import jax
import numpy as np

from trlx_tpu.data import ILQLBatch
from trlx_tpu.data.method_configs import ILQLConfig
from trlx_tpu.models.wrappers import CausalLMWithILQLHeads, Seq2SeqLMWithILQLHeads
from trlx_tpu.ops.ilql import ilql_loss
from trlx_tpu.parallel import shard_params
from trlx_tpu.pipeline.offline_pipeline import ILQLRolloutStorage, tokenize_dialogue
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUBaseTrainer
from trlx_tpu.utils import logging
from trlx_tpu.ops.remat import resolve_remat

logger = logging.get_logger(__name__)


def make_experience(
    samples: Union[List[str], List[tuple]],
    rewards: List[float],
    tokenizer=None,
    max_length: int = 2048,
    verbose: bool = True,
) -> ILQLRolloutStorage:
    """Tokenize dialogues, compute state/action indices and place the
    normalized return on the final action token (parity: reference
    accelerate_ilql_trainer.py:30-100)."""
    if verbose:
        logger.info("Collecting rollouts")
    if tokenizer is not None:
        samples = [tokenize_dialogue(s, tokenizer, max_length) for s in samples]

    all_input_ids, all_actions_ixs, all_states_ixs, all_dones = [], [], [], []
    for sample in samples:
        length = 0
        input_ids = [t for m in sample for t in m.tokens]
        all_input_ids.append(input_ids)
        actions_ixs: List[np.ndarray] = []
        for dm in sample:
            if dm.is_output:
                actions_ixs.append(np.arange(length - 1, length + len(dm.tokens) - 1))
            length += len(dm.tokens)
        if not actions_ixs:
            raise ValueError("sample has no output tokens")
        acts = np.concatenate(actions_ixs)
        states = np.concatenate([acts, [length - 1]])
        all_actions_ixs.append(acts.tolist())
        all_states_ixs.append(states.tolist())
        all_dones.append([1] * (len(states) - 1) + [0])

    returns = np.asarray(rewards, np.float64)
    returns = returns - returns.mean()
    std = returns.std()
    if not np.isnan(std) and std > 0:
        returns = returns / (std + np.finfo(returns.dtype).eps)
    rewards_per_sample = []
    for acts, ret in zip(all_actions_ixs, returns):
        rs = [0.0] * len(acts)
        rs[-1] = float(ret)
        rewards_per_sample.append(rs)

    attention_masks = [[1] * len(ids) for ids in all_input_ids]
    return ILQLRolloutStorage(
        all_input_ids, attention_masks, rewards_per_sample,
        all_states_ixs, all_actions_ixs, all_dones,
    )


def make_experience_seq2seq(
    samples, rewards, tokenizer=None, max_length: int = 2048,
    verbose: bool = True, decoder_start_token_id: int = 0,
):
    """Seq2seq variant: first phrase is the encoder prompt, second the
    decoder output; indices run over DECODER positions (parity: reference
    accelerate_ilql_trainer.py:179-245).

    The decoder rows are [decoder_start] ++ output tokens: the loss (and
    the reference, modeling_ilql.py:102) reads actions from
    decoder_input_ids[:, 1:], i.e. position 0 is pure conditioning.
    Without the explicit start prepend the start->first-token transition
    is never trained, and generation — which begins every rollout from
    the start token — immediately emits EOS (caught recording the
    summarize-shape curve: perfectly-fit BC runs generated only empty
    summaries)."""
    from trlx_tpu.pipeline.offline_pipeline import ILQLSeq2SeqRolloutStorage

    if verbose:
        logger.info("Collecting rollouts")
    if tokenizer is not None:
        samples = [tokenize_dialogue(s, tokenizer, max_length) for s in samples]

    all_input_ids, all_output_ids = [], []
    all_actions_ixs, all_states_ixs, all_dones = [], [], []
    for sample in samples:
        inputs = [m for m in sample if not m.is_output]
        outputs = [m for m in sample if m.is_output]
        if not outputs:
            raise ValueError("sample has no output tokens")
        all_input_ids.append([t for m in inputs for t in m.tokens])
        out_tokens = [int(decoder_start_token_id)] + [
            t for m in outputs for t in m.tokens
        ]
        all_output_ids.append(out_tokens)
        # length >= 2 always: the start token plus at least one output
        # token (empty outputs raised above)
        length = len(out_tokens)
        acts = list(range(length - 1))
        states = acts + [length - 1]
        all_actions_ixs.append(acts)
        all_states_ixs.append(states)
        all_dones.append([1] * (len(states) - 1) + [0])

    returns = np.asarray(rewards, np.float64)
    returns = returns - returns.mean()
    std = returns.std()
    if not np.isnan(std) and std > 0:
        returns = returns / (std + np.finfo(returns.dtype).eps)
    rewards_per_sample = []
    for acts, ret in zip(all_actions_ixs, returns):
        rs = [0.0] * len(acts)
        rs[-1] = float(ret)
        rewards_per_sample.append(rs)

    attention_masks = [[1] * len(ids) for ids in all_input_ids]
    return ILQLSeq2SeqRolloutStorage(
        all_input_ids, attention_masks, all_output_ids, rewards_per_sample,
        all_states_ixs, all_actions_ixs, all_dones,
    )


@register_trainer("TPUILQLTrainer")
class TPUILQLTrainer(TPUBaseTrainer):
    def __init__(self, config, **kwargs):
        if not isinstance(config.method, ILQLConfig):
            raise ValueError("config.method must be ILQLConfig")
        super().__init__(config, **kwargs)
        self._sync_fn = None

    def setup_model(self) -> None:
        self.seq2seq = self.config.model.model_arch_type == "seq2seq"
        cfg, base_params, self.model_type = self.load_base_model()
        method = self.config.method
        if self.seq2seq:
            if self.config.model.peft_config is not None:
                from trlx_tpu.models.peft import normalize_peft_config

                if normalize_peft_config(self.config.model.peft_config)[
                    "peft_type"
                ] != "LORA":
                    # matches the reference matrix (its peft tests skip
                    # seq2seq x {PROMPT,PREFIX}, peft 0.3.0 bugs)
                    raise NotImplementedError(
                        "seq2seq ILQL supports peft_type='LORA' only"
                    )
            self.model = Seq2SeqLMWithILQLHeads(
                cfg, two_qs=method.two_qs, alpha=method.alpha
            )
        else:
            self.model = CausalLMWithILQLHeads(
                cfg, two_qs=method.two_qs, alpha=method.alpha
            )
        self.rng, key = jax.random.split(self.rng)
        params = self.model.init_params(key, base_params)
        aux = getattr(self, "_loaded_aux", None) or {}
        if "heads" in aux:
            heads = dict(aux["heads"])
            for k in ("q_heads", "target_q_heads"):
                if isinstance(heads.get(k), dict):
                    # orbax round-trips lists as {"0": ..., "1": ...}
                    heads[k] = [heads[k][i] for i in sorted(heads[k], key=int)]
            aux = dict(aux, heads=heads)
        params.update(aux)
        params = self.attach_peft(params)
        self.params = shard_params(self.mesh, params)

    def trainable_mask(self):
        mask = self.lora_freeze_mask(self.params) or self.make_freeze_mask(self.params)
        if mask is None:
            # target heads only ever move through Polyak sync
            mask = jax.tree_util.tree_map(lambda _: np.float32(1.0), self.params)
        mask["heads"]["target_q_heads"] = jax.tree_util.tree_map(
            lambda _: np.float32(0.0), mask["heads"]["target_q_heads"]
        )
        return mask

    def loss(self, params, batch):
        remat = resolve_remat(self.config.train.remat_policy)
        if self.seq2seq:
            logits, qvs = self.model.forward(
                params, batch.input_ids, batch.attention_mask,
                batch.decoder_input_ids, batch.states_ixs, batch.actions_ixs,
                remat=remat,
            )
        else:
            logits, qvs = self.model.forward(
                params, batch.input_ids, batch.attention_mask,
                batch.states_ixs, batch.actions_ixs, remat=remat,
            )
        method = self.config.method
        return ilql_loss(
            logits, *qvs[:2], qvs[2], batch,
            tau=method.tau, gamma=method.gamma, cql_scale=method.cql_scale,
            awac_scale=method.awac_scale, beta=method.beta, two_qs=method.two_qs,
        )

    def generation_logits_processor(self, params, beta=None):
        """`beta` arrives per-call when evaluate() sweeps `gen_kwargs.beta`
        over a list (the reference's gen-kwarg sweep protocol, ref
        accelerate_base_trainer.py:339-505 / modeling_ilql.py generate);
        otherwise the config scalar applies."""
        if beta is None:
            beta = self.config.method.gen_kwargs.get("beta", 1.0)
            if isinstance(beta, (list, tuple)):
                # sweep-shaped config reached a non-sweeping call site
                # (e.g. experience generation): shape with the first value
                beta = beta[0]
        return self.model.make_logits_processor(params["heads"], float(beta))

    def make_experience(self, samples, rewards, seq_length: int = 1024) -> None:
        # hang doctor: offline experience building is host-bound
        # (tokenize + index) — heartbeat it as its own phase
        with self.watchdog.phase("experience"):
            if self.seq2seq:
                self.store = make_experience_seq2seq(
                    samples, rewards, self.tokenizer, seq_length,
                    decoder_start_token_id=self.model.cfg.decoder_start_token_id,
                )
            else:
                self.store = make_experience(
                    samples, rewards, self.tokenizer, seq_length
                )

    def prepare_learning(self) -> None:
        self.eval_dataloader = self.eval_pipeline.create_loader(
            self.config.train.batch_size
        )
        self.n_inner_epochs = 1
        n_batches = len(self.store) // self.config.train.batch_size
        self.total_steps = min(
            self.config.train.epochs * max(n_batches, 1),
            self.config.train.total_steps,
        )

    def create_train_dataloader(self):
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True, drop_last=True,
            seed=self.config.train.seed + self.iter_count,
        )

    def post_backward_callback(self) -> None:
        method = self.config.method
        if self.iter_count % method.steps_for_target_q_sync == 0:
            if self._sync_fn is None:
                self._sync_fn = jax.jit(
                    lambda p: self.model.sync_target(p, method.alpha),
                    donate_argnums=0,
                )
            with self.mesh:
                self.params = self._sync_fn(self.params)
