"""Model wrappers: causal LM + value head (PPO, with in-process frozen
reference branch) and causal LM + ILQL heads.

Parity: /root/reference/trlx/models/modeling_ppo.py:244-499
(`AutoModelForCausalLMWith{Value,HydraValue}Head`) and
modeling_ilql.py:262-479 (`AutoModelForCausalLMWithILQLHeads`). The
reference's per-architecture `ModelBranch` classes (modeling_ppo.py:502-1637)
are unnecessary here: the frozen reference branch is a slice of the stacked
layer stack re-run from the captured hidden state
(`TransformerLM.forward_with_branch_capture` / `forward_from_layer`).

Wrappers are functional: `params` trees in, activation dicts out, so the
trainers can jit/shard/donate them directly.

LoRA: when a params tree carries a "lora" overlay ({path: {a, b}}, see
trlx_tpu.models.lora), `_effective_base` merges it onto a
gradient-stopped base — so only the adapters (and heads) train, matching
the reference's peft contract (tests/test_peft.py: backprop touches
adapters only; the reference model is the disabled-adapter forward).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.models.heads import (
    apply_head,
    apply_ilql_heads,
    init_head,
    init_ilql_heads,
    sync_target_q_heads,
)
from trlx_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    extract_branch_params,
    logit_projection,
)

Array = jnp.ndarray


def _effective_base(wrapper, params: Dict) -> Dict:
    """Resolve the base param tree, merging a LoRA overlay if present.
    With any peft adapter the base is stop-gradiented: only the adapter
    (+ heads) trains, and the backward never materializes base grads."""
    if "lora" in params:
        from trlx_tpu.models.lora import merge_lora

        return merge_lora(
            jax.lax.stop_gradient(params["base"]), params["lora"],
            getattr(wrapper, "lora_scaling", 1.0),
        )
    if "prompt" in params or "prefix" in params:
        return jax.lax.stop_gradient(params["base"])
    return params["base"]


def _adapter_kwargs(params: Dict) -> Dict:
    """Prompt/prefix adapter kwargs for TransformerLM.__call__."""
    from trlx_tpu.models.peft import adapter_call_kwargs

    return adapter_call_kwargs(params)


class CausalLM:
    """Bare causal LM wrapper (SFT/RFT path — no auxiliary heads)."""

    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg
        self.lm = TransformerLM(cfg)

    def init_params(self, rng: jax.Array, base_params: Optional[Dict] = None) -> Dict:
        if base_params is None:
            base_params = self.lm.init(rng)
        return {"base": base_params}

    def forward(
        self,
        params: Dict,
        input_ids: Array,
        attention_mask: Optional[Array] = None,
        remat: bool = False,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        return self.lm(
            _effective_base(self, params), input_ids, attention_mask,
            remat=remat, compute_logits=compute_logits,
            **_adapter_kwargs(params),
        )

    def logit_project_fn(self, params: Dict):
        """hidden -> logits closure for chunked-from-hidden losses
        (`ops.common.chunked_logprobs`); resolves any LoRA overlay so the
        projection matches the forward's effective weights."""
        return logit_projection(_effective_base(self, params))


class CausalLMWithValueHead:
    """Policy LM + scalar value head; optional hydra reference branch.

    `branch_at` (= n_layer - num_layers_unfrozen) picks where the frozen
    reference branch forks off. With `branch_at is None` (all layers
    unfrozen) PPO needs a full frozen copy of the params as reference —
    the trainer keeps that copy and calls `forward_ref_full`.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        branch_at: Optional[int] = None,
        value_branch_at: Optional[int] = None,
    ):
        self.cfg = cfg
        self.lm = TransformerLM(cfg)
        self.branch_at = branch_at
        # value branch: a separate TRAINABLE copy of the top layers feeding
        # the value head (reference make_value_branch /
        # num_value_layers_unfrozen, modeling_ppo.py:255-263)
        self.value_branch_at = value_branch_at

    # -- params ----------------------------------------------------------

    def init_params(self, rng: jax.Array, base_params: Optional[Dict] = None) -> Dict:
        r_base, r_head = jax.random.split(rng)
        if base_params is None:
            base_params = self.lm.init(r_base)
        params = {
            "base": base_params,
            "v_head": init_head(r_head, self.cfg.hidden_size, 1),
        }
        if self.value_branch_at is not None:
            params["v_branch"] = jax.tree_util.tree_map(
                jnp.copy,
                {
                    "blocks": jax.tree_util.tree_map(
                        lambda x: x[self.value_branch_at :], base_params["blocks"]
                    ),
                    "ln_f": base_params["ln_f"],
                },
            )
        return params

    def _values(self, params: Dict, out: Dict) -> Array:
        """Value head input: final hidden, or the value branch re-run from
        its captured fork point."""
        if self.value_branch_at is None:
            return apply_head(params["v_head"], out["hidden_states"])[..., 0]
        h = out["v_branch_hidden"]
        ring = None
        if out["attn_bias"] is None:  # ring-attention trunk pass
            ring = self.lm._ring_mesh(h.shape[0], h.shape[1], None)
        h, _ = self.lm._scan_blocks(
            params["v_branch"]["blocks"], h, out["attn_bias"], out["positions"],
            local_bias=out.get("local_bias"),
            layer_offset=self.value_branch_at,
            key_mask=out.get("key_mask"), ring_mesh=ring,
        )
        hidden = self.lm.ln_f.apply({"params": params["v_branch"]["ln_f"]}, h)
        return apply_head(params["v_head"], hidden)[..., 0]

    def make_ref_params(self, params: Dict) -> Dict:
        """Frozen reference: the top branch only (hydra) or the full tree.

        Deep-copied: the trainer donates `params` buffers every step, so
        the reference must not alias them."""
        if self.branch_at is not None:
            branch = extract_branch_params(params["base"], self.branch_at)
        else:
            branch = jax.lax.stop_gradient(params["base"])
        return jax.tree_util.tree_map(jnp.copy, branch)

    # -- forwards --------------------------------------------------------

    def _capture_points(self):
        points = set()
        if self.branch_at is not None:
            points.add(self.branch_at)
        if self.value_branch_at is not None:
            points.add(self.value_branch_at)
        return tuple(sorted(points))

    def _multi_forward(self, params, input_ids, attention_mask, remat,
                       compute_logits=True):
        """Trunk pass capturing hydra and/or value-branch fork hiddens."""
        base = _effective_base(self, params)
        points = self._capture_points()
        out = self.lm.forward_with_multi_capture(
            base, input_ids, attention_mask, points, remat=remat,
            compute_logits=compute_logits,
        )
        named = dict(zip(points, out["captures"]))
        if self.branch_at is not None:
            out["branch_hidden"] = named[self.branch_at]
        if self.value_branch_at is not None:
            out["v_branch_hidden"] = named[self.value_branch_at]
        return out

    def forward(
        self,
        params: Dict,
        input_ids: Array,
        attention_mask: Optional[Array] = None,
        remat: bool = False,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        if self.value_branch_at is None:
            out = self.lm(
                _effective_base(self, params), input_ids, attention_mask,
                remat=remat, compute_logits=compute_logits,
                **_adapter_kwargs(params),
            )
        else:
            out = self._multi_forward(
                params, input_ids, attention_mask, remat, compute_logits
            )
        return dict(out, values=self._values(params, out))

    def logit_project_fn(self, params: Dict):
        """hidden -> logits closure for chunked-from-hidden losses
        (`ops.common.chunked_logprobs`); resolves any LoRA overlay so the
        projection matches the forward's effective weights."""
        return logit_projection(_effective_base(self, params))

    def forward_train(
        self,
        params: Dict,
        ref_params: Dict,
        input_ids: Array,
        attention_mask: Optional[Array] = None,
        remat: bool = False,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        """One pass producing policy logits, values AND reference logits.

        Hydra mode shares the trunk below `branch_at` between policy and
        reference (the whole point of the reference's hydra heads —
        modeling_ppo.py:410-453 — done here with an array slice instead of
        six per-arch branch classes).

        `compute_logits=False` (train.logit_chunks) skips BOTH full-vocab
        projections; `ref_hidden` is always returned so chunked losses can
        project the reference's logprobs themselves.
        """
        if self.branch_at is None:
            out = self.forward(
                params, input_ids, attention_mask, remat=remat,
                compute_logits=compute_logits,
            )
            ref_out = self.lm(
                ref_params, input_ids, attention_mask, remat=remat,
                compute_logits=compute_logits,
            )
        else:
            out = self._multi_forward(
                params, input_ids, attention_mask, remat, compute_logits
            )
            out["values"] = self._values(params, out)
            ref_out = self.lm.forward_from_layer(
                ref_params,
                jax.lax.stop_gradient(out["branch_hidden"]),
                out["attn_bias"],
                out["positions"],
                remat=remat,
                local_bias=out.get("local_bias"),
                key_mask=out.get("key_mask"),
                compute_logits=compute_logits,
            )
        return dict(
            out,
            ref_logits=(
                jax.lax.stop_gradient(ref_out["logits"])
                if compute_logits else None
            ),
            ref_hidden=jax.lax.stop_gradient(ref_out["hidden_states"]),
        )


class Seq2SeqLMWithValueHead:
    """Encoder-decoder policy + value head over decoder hidden states;
    optional frozen top-decoder reference branch.

    Parity: reference `AutoModelForSeq2SeqLMWith{Value,HydraValue}Head`
    (modeling_ppo.py:1242-1480) + the frozen `T5Branch` (:1483-1592).
    """

    def __init__(self, cfg, branch_at: Optional[int] = None):
        from trlx_tpu.models.seq2seq import T5LM

        self.cfg = cfg
        self.lm = T5LM(cfg)
        self.branch_at = branch_at

    def init_params(self, rng: jax.Array, base_params: Optional[Dict] = None) -> Dict:
        r_base, r_head = jax.random.split(rng)
        if base_params is None:
            base_params = self.lm.init(r_base)
        return {
            "base": base_params,
            "v_head": init_head(r_head, self.cfg.d_model, 1),
        }

    def make_ref_params(self, params: Dict) -> Dict:
        from trlx_tpu.models.seq2seq import extract_t5_branch_params

        if self.branch_at is not None:
            return extract_t5_branch_params(params["base"], self.branch_at)
        return jax.tree_util.tree_map(
            jnp.copy, jax.lax.stop_gradient(params["base"])
        )

    def forward(
        self,
        params: Dict,
        input_ids: Array,
        attention_mask: Array,
        decoder_input_ids: Array,
        decoder_attention_mask: Optional[Array] = None,
        remat: bool = False,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        out = self.lm(
            _effective_base(self, params), input_ids, attention_mask,
            decoder_input_ids, decoder_attention_mask, remat=remat,
            compute_logits=compute_logits,
        )
        values = apply_head(params["v_head"], out["hidden_states"])[..., 0]
        return dict(out, values=values)

    def logit_project_fn(self, params: Dict):
        """hidden -> logits closure for chunked-from-hidden losses."""
        from trlx_tpu.models.seq2seq import t5_logit_projection

        return t5_logit_projection(_effective_base(self, params), self.cfg)

    def forward_train(
        self,
        params: Dict,
        ref_params: Dict,
        input_ids: Array,
        attention_mask: Array,
        decoder_input_ids: Array,
        decoder_attention_mask: Optional[Array] = None,
        remat: bool = False,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        if self.branch_at is None:
            out = self.forward(
                params, input_ids, attention_mask, decoder_input_ids,
                decoder_attention_mask, remat=remat,
                compute_logits=compute_logits,
            )
            ref_out = self.lm(
                ref_params, input_ids, attention_mask, decoder_input_ids,
                decoder_attention_mask, remat=remat,
                compute_logits=compute_logits,
            )
        else:
            out = self.lm.forward_with_branch_capture(
                params["base"], input_ids, attention_mask, decoder_input_ids,
                decoder_attention_mask, self.branch_at, remat=remat,
                compute_logits=compute_logits,
            )
            out["values"] = apply_head(params["v_head"], out["hidden_states"])[..., 0]
            ref_out = self.lm.forward_from_layer(
                ref_params,
                jax.lax.stop_gradient(out["branch_hidden"]),
                out["self_bias"],
                jax.lax.stop_gradient(out["encoder_hidden"]),
                out["cross_bias"],
                remat=remat,
                compute_logits=compute_logits,
                pos_bias=out.get("pos_bias"),
                skey_mask=out.get("skey_mask"),
                ckey_mask=out.get("ckey_mask"),
            )
        return dict(
            out,
            ref_logits=(
                jax.lax.stop_gradient(ref_out["logits"])
                if compute_logits else None
            ),
            ref_hidden=jax.lax.stop_gradient(ref_out["hidden_states"]),
        )


class Seq2SeqLMWithILQLHeads:
    """Encoder-decoder LM + ILQL head group over DECODER hidden states
    (parity: reference AutoModelForSeq2SeqLMWithILQLHeads,
    modeling_ilql.py:481-666)."""

    def __init__(self, cfg, two_qs: bool = True, alpha: float = 0.001):
        from trlx_tpu.models.seq2seq import T5LM

        self.cfg = cfg
        self.lm = T5LM(cfg)
        self.two_qs = two_qs
        self.alpha = alpha

    def init_params(self, rng: jax.Array, base_params: Optional[Dict] = None) -> Dict:
        r_base, r_heads = jax.random.split(rng)
        if base_params is None:
            base_params = self.lm.init(r_base)
        return {
            "base": base_params,
            "heads": init_ilql_heads(
                r_heads, self.cfg.d_model, self.cfg.vocab_size, self.two_qs
            ),
        }

    def forward(
        self,
        params: Dict,
        input_ids: Array,
        attention_mask: Array,
        decoder_input_ids: Array,
        states_ixs: Array,
        actions_ixs: Array,
        remat: bool = False,
    ) -> Tuple[Array, Tuple]:
        from trlx_tpu.models.seq2seq import t5_logit_projection
        from trlx_tpu.ops.common import batched_index_select

        base = _effective_base(self, params)
        # the loss only needs logits AT the action positions: gather the
        # hidden rows first, then project — [B, A, V] instead of [B, T, V]
        # (identical math; the vocab matmul runs on A rows, not T)
        out = self.lm(
            base, input_ids, attention_mask,
            decoder_input_ids, remat=remat, compute_logits=False,
        )
        qs, target_qs, vs = apply_ilql_heads(
            params["heads"], out["hidden_states"], states_ixs, actions_ixs
        )
        h_at = batched_index_select(out["hidden_states"], actions_ixs, dim=1)
        logits_at_actions = t5_logit_projection(base, self.cfg)(h_at)
        return logits_at_actions, (qs, target_qs, vs)

    def sync_target(self, params: Dict, alpha: Optional[float] = None) -> Dict:
        return dict(
            params,
            heads=sync_target_q_heads(
                params["heads"], self.alpha if alpha is None else alpha
            ),
        )

    def make_logits_processor(self, params_heads: Dict, beta: float):
        from trlx_tpu.ops.ilql import ilql_shape_logits

        def processor(hidden_last: Array, logits_last: Array) -> Array:
            qs = [apply_head(h, hidden_last) for h in params_heads["target_q_heads"]]
            vs = apply_head(params_heads["v_head"], hidden_last)
            return ilql_shape_logits(logits_last, qs, vs, beta)

        return processor


class CausalLMWithILQLHeads:
    """Causal LM + ILQL head group (v, q, frozen target q).

    Parity: modeling_ilql.py:262-479; generation-time advantage shaping is
    a `logits_processor` for trlx_tpu.models.generation (built by
    `make_ilql_logits_processor`).
    """

    def __init__(self, cfg: TransformerConfig, two_qs: bool = True, alpha: float = 0.001):
        self.cfg = cfg
        self.lm = TransformerLM(cfg)
        self.two_qs = two_qs
        self.alpha = alpha

    def init_params(self, rng: jax.Array, base_params: Optional[Dict] = None) -> Dict:
        r_base, r_heads = jax.random.split(rng)
        if base_params is None:
            base_params = self.lm.init(r_base)
        return {
            "base": base_params,
            "heads": init_ilql_heads(
                r_heads, self.cfg.hidden_size, self.cfg.vocab_size, self.two_qs
            ),
        }

    def forward(
        self,
        params: Dict,
        input_ids: Array,
        attention_mask: Optional[Array],
        states_ixs: Array,
        actions_ixs: Array,
        remat: bool = False,
    ) -> Tuple[Array, Tuple]:
        """Returns (logits_at_actions, (qs, target_qs, vs)) — the shape the
        ILQL loss consumes (trlx_tpu.ops.ilql.ilql_loss)."""
        from trlx_tpu.ops.common import batched_index_select

        base = _effective_base(self, params)
        # the loss only needs logits AT the action positions: gather the
        # hidden rows first, then project — [B, A, V] instead of [B, T, V]
        # (identical math; the vocab matmul runs on A rows, not T)
        out = self.lm(
            base, input_ids, attention_mask,
            remat=remat, compute_logits=False, **_adapter_kwargs(params),
        )
        qs, target_qs, vs = apply_ilql_heads(
            params["heads"], out["hidden_states"], states_ixs, actions_ixs
        )
        h_at = batched_index_select(out["hidden_states"], actions_ixs, dim=1)
        logits_at_actions = logit_projection(base)(h_at)
        return logits_at_actions, (qs, target_qs, vs)

    def sync_target(self, params: Dict, alpha: Optional[float] = None) -> Dict:
        return dict(
            params,
            heads=sync_target_q_heads(
                params["heads"], self.alpha if alpha is None else alpha
            ),
        )

    def make_logits_processor(self, params_heads: Dict, beta: float):
        """Advantage shaping `log pi_beta + beta * (minQ - V)` for the
        jitted decode loop (parity: modeling_ilql.py:365-374)."""
        from trlx_tpu.ops.ilql import ilql_shape_logits

        def processor(hidden_last: Array, logits_last: Array) -> Array:
            qs = [apply_head(h, hidden_last) for h in params_heads["target_q_heads"]]
            vs = apply_head(params_heads["v_head"], hidden_last)
            return ilql_shape_logits(logits_last, qs, vs, beta)

        return processor
