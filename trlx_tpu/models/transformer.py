"""TPU-native causal transformer: one architecture-polymorphic decoder.

Replaces the reference's per-architecture `ModelBranch` family
(/root/reference/trlx/models/modeling_ppo.py:502-1637 — six hand-copied
decoder loops for GPT2/OPT/Bloom/Llama/BigCode/T5): here a single
functional decoder covers GPT-2 / GPT-J / GPT-NeoX / OPT / Llama through
config switches (position embedding, norm type, MLP gating, residual
layout), and "run the top-k layers from a hidden state" is an array slice
of the stacked layer parameters, not a reimplementation.

Design notes (TPU-first):
- Layer parameters are **stacked** along a leading `layer` axis
  (init via vmap) and the forward is a `lax.scan` over them: one traced
  block regardless of depth -> fast compile, and XLA keeps the loop on
  device. Hydra reference branches and layer freezing become slicing /
  masking of the leading axis.
- Sharding is by **path rules** (trlx_tpu/parallel/sharding.py), not
  boxed flax metadata: the param tree stays a plain pytree of arrays so
  the trainers can slice/mask/donate it freely.
- Compute dtype is configurable (bf16 on the MXU); attention scores,
  softmax, norms and logits accumulate in fp32.
- KV-cache decode reuses the same block code: attention takes
  preallocated static-shape cache buffers and a write index (no dynamic
  shapes anywhere).
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

Array = jnp.ndarray
NEG_INF = -1e9  # additive mask value (finite: avoids NaN rows for all-masked)


@dataclass(frozen=True)
class TransformerConfig:
    """Static architecture description (hashable: usable as a jit static)."""

    vocab_size: int
    hidden_size: int
    n_layer: int
    n_head: int
    n_positions: int = 1024
    intermediate_size: Optional[int] = None  # default 4*hidden
    n_kv_head: Optional[int] = None  # grouped-query attention; default n_head
    head_dim: Optional[int] = None  # default hidden // n_head

    # architecture switches
    pos_embed: str = "learned"  # "learned" | "rotary" | "alibi" | "none"
    pos_offset: int = 0  # OPT: learned table has 2 leading pad rows
    embed_layernorm: bool = False  # bloom: LayerNorm after word embeddings
    rotary_style: str = "neox"  # "neox" (half rotate) | "gptj" (interleaved)
    rotary_dim: Optional[int] = None  # default head_dim
    rope_theta: float = 10000.0
    # gpt-neo quirks: queries are NOT scaled by 1/sqrt(head_dim), and
    # every other layer attends only within a sliding window
    attn_scale: Optional[float] = None  # None -> 1/sqrt(head_dim)
    local_window: Optional[int] = None  # sliding-window size for "local" layers
    attn_layers: Optional[Tuple[str, ...]] = None  # per-layer "global"/"local"
    norm: str = "layernorm"  # "layernorm" | "rmsnorm"
    layer_norm_epsilon: float = 1e-5
    activation: str = "gelu_new"  # "gelu_new" | "gelu" | "silu" | "relu"
    mlp_gated: bool = False  # llama-style SwiGLU
    parallel_residual: bool = False  # gptj/neox: attn and mlp share input
    use_attn_bias: bool = True
    # gpt-neo: q/k/v have no bias but out_proj does; None = use_attn_bias
    use_attn_out_bias: Optional[bool] = None
    use_mlp_bias: bool = True
    use_norm_bias: bool = True
    tie_word_embeddings: bool = True

    # numerics
    dtype: Any = jnp.bfloat16  # compute dtype inside blocks
    param_dtype: Any = jnp.float32
    # "xla" (let the compiler fuse) | "pallas" (first-party fused kernel
    # for full teacher-forced forwards; decode steps always use XLA) |
    # "ring" (sequence/context parallelism: teacher-forced forwards run
    # ops.ring_attention over the mesh's `sp` axis — requires
    # TransformerLM.mesh to be set and seq divisible by sp; decode steps
    # and non-plain-bias architectures fall back to XLA).
    # The pallas path is fused in BOTH directions (online-softmax forward
    # + chunked flash backward, ops/flash_attention.py): the [B,H,T,S]
    # score tensor never exists, so training at 8k+ tokens is where it
    # pays for itself.
    attention_impl: str = "xla"
    # None | "int8" | "int8_kernel": generate() quantizes the KV cache
    # after prefill so the decode loop's full-cache read rides an int8
    # stream (half the HBM traffic of bf16 — decode at large batch×seq
    # is bound on exactly that read). Prefill numerics are untouched;
    # decode picks up symmetric quantization noise (bounded in
    # tests/test_generation.py). "int8" drives the folded-scale XLA
    # path; "int8_kernel" additionally routes aligned caches through
    # the pallas decode kernel (slower on v5e today — see the measured
    # note in Attention's int8 branch — kept for tuning).
    kv_cache_quant: Optional[str] = None
    # None | "int8": generate() rewrites block kernels to int8 +
    # per-output-channel scales for the rollout (prefill AND decode run
    # the same quantized policy; the teacher-forced experience pass
    # keeps full precision). Halves the 2.4 GB/step block-weight read
    # that dominates decode after the int8 KV cache.
    decode_weights_quant: Optional[str] = None
    # pipeline parallelism: microbatches per pipelined forward when the
    # mesh has a pp axis > 1 (0 = one microbatch per pipeline stage).
    # The bubble fraction is (pp-1)/(M+pp-1); raise M to amortize it.
    pp_microbatches: int = 0
    # "gpipe" (differentiate the forward scan; stores M+pp-1 boundary
    # activations) | "1f1b" (custom-VJP backward interleaving recompute
    # with the cotangent pipeline; O(pp) boundary liveness per stage,
    # one extra forward — parallel/pipeline.py:_run_1f1b)
    pp_schedule: str = "gpipe"

    def __post_init__(self):
        if self.intermediate_size is None:
            object.__setattr__(self, "intermediate_size", 4 * self.hidden_size)
        if self.head_dim is None:
            object.__setattr__(self, "head_dim", self.hidden_size // self.n_head)
        if self.n_kv_head is None:
            object.__setattr__(self, "n_kv_head", self.n_head)
        if self.rotary_dim is None and self.pos_embed == "rotary":
            object.__setattr__(self, "rotary_dim", self.head_dim)

    def replace(self, **kw) -> "TransformerConfig":
        return dataclasses.replace(self, **kw)


def _activation(name: str) -> Callable[[Array], Array]:
    return {
        "gelu_new": partial(jax.nn.gelu, approximate=True),
        "gelu": partial(jax.nn.gelu, approximate=False),
        "silu": jax.nn.silu,
        "relu": jax.nn.relu,
    }[name]


# ---------------------------------------------------------------------------
# Rotary position embedding
# ---------------------------------------------------------------------------


def rope_frequencies(cfg: TransformerConfig, positions: Array) -> Tuple[Array, Array]:
    """cos/sin tables [batch, seq, rotary_dim//2] for given positions."""
    dim = cfg.rotary_dim
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, T, dim/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: Array, cos: Array, sin: Array, style: str) -> Array:
    """Rotate the first rotary_dim channels of x [B, T, H, D]."""
    rot_dim = cos.shape[-1] * 2
    x_rot, x_pass = x[..., :rot_dim], x[..., rot_dim:]
    x_rot = x_rot.astype(jnp.float32)
    cos = cos[:, :, None, :]  # broadcast over heads
    sin = sin[:, :, None, :]
    if style == "gptj":
        x1, x2 = x_rot[..., 0::2], x_rot[..., 1::2]
        rotated = jnp.stack(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        ).reshape(x_rot.shape)
    else:  # neox / llama: rotate halves
        half = rot_dim // 2
        x1, x2 = x_rot[..., :half], x_rot[..., half:]
        rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return jnp.concatenate([rotated.astype(x.dtype), x_pass], axis=-1)


def alibi_slopes(n_head: int) -> Array:
    """Per-head ALiBi slopes (bloom parity). The bias added to scores is
    `slope[h] * key_position`, equivalent to the canonical
    `-slope * (q_pos - k_pos)` because the per-query constant cancels in
    softmax — this is also how HF bloom builds its alibi tensor."""
    p = 2 ** math.floor(math.log2(n_head))
    base = 2.0 ** (-(2.0 ** -(math.log2(p) - 3)))
    slopes = [base ** i for i in range(1, p + 1)]
    if p < n_head:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * p) - 3)))
        slopes += [extra_base ** i for i in range(1, 2 * (n_head - p) + 1, 2)]
    return jnp.asarray(slopes, jnp.float32)


# ---------------------------------------------------------------------------
# Modules (params are plain arrays; composition is functional below)
# ---------------------------------------------------------------------------


class Norm(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: Array) -> Array:
        cfg = self.cfg
        x32 = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (cfg.hidden_size,), cfg.param_dtype)
        if cfg.norm == "rmsnorm":
            var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
            y = x32 * jax.lax.rsqrt(var + cfg.layer_norm_epsilon) * scale
        else:
            mean = jnp.mean(x32, axis=-1, keepdims=True)
            var = jnp.var(x32, axis=-1, keepdims=True)
            y = (x32 - mean) * jax.lax.rsqrt(var + cfg.layer_norm_epsilon) * scale
            if cfg.use_norm_bias:
                y = y + self.param(
                    "bias", nn.initializers.zeros, (cfg.hidden_size,), cfg.param_dtype
                )
        return y.astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        x: Array,  # [B, T, E]
        attn_bias: Array,  # [B, 1, T, S] additive fp32
        positions: Array,  # [B, T] absolute positions (for rope)
        cache: Optional[Dict[str, Array]] = None,  # {"ck","cv"}: [L, B, S, Hkv, D], "ix", "index"
        key_mask: Optional[Array] = None,  # [B, T]; enables the pallas path
        ring_mesh=None,  # Mesh; non-None routes to ring attention over `sp`
    ) -> Tuple[Array, Optional[Dict[str, Array]]]:
        cfg = self.cfg
        B, T, E = x.shape
        H, Hkv, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim

        dense = partial(
            QDense,
            axis=-1,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            use_bias=cfg.use_attn_bias,
        )
        q = dense(features=(H, D), name="q")(x)
        k = dense(features=(Hkv, D), name="k")(x)
        v = dense(features=(Hkv, D), name="v")(x)

        if cfg.pos_embed == "rotary":
            cos, sin = rope_frequencies(cfg, positions)
            q = apply_rope(q, cos, sin, cfg.rotary_style)
            k = apply_rope(k, cos, sin, cfg.rotary_style)

        new_kv = None
        kernel_out = None  # set by the fused int8 decode kernel path
        if cache is not None and "pk" in cache:
            # paged cache (slot→page indirection, models/gen_engine.py):
            # write the T incoming tokens into their pages, attend each
            # query against its slot's gathered logical sequence. All
            # masking (per-row lengths, causality among the T tokens,
            # refill staleness) rides the additive bias, so this branch
            # is generic over plain/alibi/local architectures. The
            # folded-scale int8 math and the gather/scatter live in
            # ops/decode_attention.paged_attention_step.
            from trlx_tpu.ops.decode_attention import paged_attention_step

            scale = (
                cfg.attn_scale if cfg.attn_scale is not None
                else 1.0 / math.sqrt(D)
            )
            pools = {
                name: cache[name]
                for name in ("pk", "pv", "pk_scale", "pv_scale")
                if name in cache
            }
            kernel_out, new_kv = paged_attention_step(
                q, k, v, pools, cache["ix"], cache["page_table"],
                cache["slot_pos"], attn_bias, scale,
                lane_valid=cache.get("lane_valid"),
                contiguous=bool(cache.get("contiguous", False)),
                impl=cache.get("attn_impl", "xla"),
            )
        elif cache is not None:
            # update-carry-FIRST: write this layer's new [B, T, Hkv, D]
            # column into the scan-carried stacked buffer, then attend
            # against a slice of the UPDATED buffer. The column write
            # aliases in place (the buffer is a scan carry) and the row
            # slice is a read, so the only cache traffic per step is one
            # full read + one column write. The previous design built a
            # per-layer `dynamic_update_slice(row, col)` copy BEFORE the
            # carry write — a second full-cache materialization costing
            # 3.2 GB of extra HBM writes per decoded token at 1.3B,
            # measured 13.6 vs 6.5 ms/step on the cache mechanics alone
            # (v5e, 24L x b8 x 2048 slots). Two earlier designs were
            # worse still: stacking full updated buffers as scan ys
            # (rewrites the whole cache every token), and attending
            # against the stale buffer + patching new-column scores
            # (defeats XLA's in-place aliasing entirely, 15x slower).
            idx = cache["index"]
            ix = cache["ix"]
            if "ck_scale" in cache:
                # int8 cache (decode only; generate() quantizes the
                # prefilled cache once — see quantize_kv_cache).
                # Buffer layout is [L, B, Hkv, S, D] (kv-head OUTSIDE
                # the slot axis) so the fused decode kernel's per-cell
                # blocks are plain trailing (S, D) tiles; scales are
                # K per (slot, kv-head) / V per (kv-head, channel) so
                # both dequants commute out of the attention reductions
                # (rationale + measured per-token-V cost in
                # ops/decode_attention.py).
                kq, ks = _quantize_kv(k)  # [B,T,Hkv,D] int8, [B,T,Hkv]
                layer_vs = cache["v_scale"]  # [B, Hkv, 1, D]
                vq = jnp.clip(
                    jnp.round(
                        v.astype(jnp.float32)
                        / jnp.maximum(layer_vs.transpose(0, 2, 1, 3), 1e-12)
                    ),
                    -127.0,
                    127.0,
                ).astype(jnp.int8)
                ck = jax.lax.dynamic_update_slice(
                    cache["ck"], kq.transpose(0, 2, 1, 3)[None],
                    (ix, 0, 0, idx, 0),
                )
                # V stores [.., S, D] like K. A [.., D, S] variant
                # (contracting axis minor for the AV dot) was measured
                # 2026-07-31: it re-fuses the AV convert but makes the
                # per-step column write strided across the minor axis —
                # net wash (849 vs 868 tok/s, inside run noise), so the
                # write-friendly layout stays
                cv = jax.lax.dynamic_update_slice(
                    cache["cv"], vq.transpose(0, 2, 1, 3)[None],
                    (ix, 0, 0, idx, 0),
                )
                cks = jax.lax.dynamic_update_slice(
                    cache["ck_scale"],
                    ks.transpose(0, 2, 1)[:, :, None][None].astype(
                        cache["ck_scale"].dtype
                    ),
                    (ix, 0, 0, 0, idx),
                )
                new_kv = {"ck": ck, "cv": cv, "ck_scale": cks}
                S = ck.shape[3]
                plain = (
                    cfg.attn_scale is None
                    and cfg.pos_embed != "alibi"
                    and cfg.local_window is None
                )
                if (
                    cfg.kv_cache_quant == "int8_kernel"
                    and T == 1
                    and plain
                    and key_mask is not None
                    and S % 128 == 0
                ):
                    # fused pallas decode kernel: int8 K/V stream
                    # straight from the full carried buffer
                    # (scalar-prefetched layer index), scales folded
                    # in-kernel. Measured SLOWER than the folded-scale
                    # XLA path below at 1.3B b8 seq2048 on v5e (0.185
                    # vs ~0.13 ms/layer — per-cell M=1 dots underuse
                    # the MXU), so it is opt-in until tuned; kept
                    # because its per-cell VMEM streaming is the right
                    # shape for longer caches (ops/decode_attention.py)
                    from trlx_tpu.ops.decode_attention import (
                        decode_attention_int8,
                    )

                    kernel_out = decode_attention_int8(
                        q[:, 0], ck, cv, cks, layer_vs, key_mask, ix,
                        sm_scale=1.0 / math.sqrt(D),
                    )[:, None]  # [B, 1, H, D]
                elif plain:
                    # folded-scale XLA path (the production "int8"
                    # decode): keep K/V int8 end to end — the per-slot
                    # K scale rides the [B,H,T,S] scores (fuses into
                    # the softmax chain), the per-channel V scale rides
                    # the [B,T,H,D] output; nothing S-sized is ever
                    # dequantized to HBM
                    k_i8 = jax.lax.dynamic_index_in_dim(
                        ck, ix, 0, keepdims=False
                    )  # [B, Hkv, S, D]
                    v_i8 = jax.lax.dynamic_index_in_dim(
                        cv, ix, 0, keepdims=False
                    )  # [B, Hkv, S, D]
                    ks_l = jax.lax.dynamic_index_in_dim(
                        cks, ix, 0, keepdims=False
                    )  # [B, Hkv, 1, S]
                    if Hkv != H:
                        rep = H // Hkv
                        k_i8 = jnp.repeat(k_i8, rep, axis=1)
                        v_i8 = jnp.repeat(v_i8, rep, axis=1)
                        ks_l = jnp.repeat(ks_l, rep, axis=1)
                        layer_vs = jnp.repeat(layer_vs, rep, axis=1)
                    scores = jnp.einsum(
                        "bthd,bhsd->bhts",
                        q,
                        k_i8.astype(cfg.dtype),
                        preferred_element_type=jnp.float32,
                    ) * (1.0 / math.sqrt(D))
                    scores = scores * ks_l + attn_bias
                    probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
                    kernel_out = jnp.einsum(
                        "bhts,bhsd->bthd", probs, v_i8.astype(cfg.dtype)
                    ) * layer_vs.transpose(0, 2, 1, 3).astype(cfg.dtype)
                else:
                    # non-plain-bias fallback: full dequant back to the
                    # [B, S, Hkv, D] orientation the generic XLA path
                    # expects — correctness, not a fast path
                    k = (
                        jax.lax.dynamic_index_in_dim(ck, ix, 0, keepdims=False)
                        .astype(jnp.float32)
                        * jax.lax.dynamic_index_in_dim(
                            cks, ix, 0, keepdims=False
                        ).transpose(0, 1, 3, 2)
                    ).astype(cfg.dtype).transpose(0, 2, 1, 3)
                    v = (
                        jax.lax.dynamic_index_in_dim(cv, ix, 0, keepdims=False)
                        .astype(jnp.float32)
                        * layer_vs
                    ).astype(cfg.dtype).transpose(0, 2, 1, 3)
            else:
                ck = jax.lax.dynamic_update_slice(
                    cache["ck"], k[None].astype(cache["ck"].dtype), (ix, 0, idx, 0, 0)
                )
                cv = jax.lax.dynamic_update_slice(
                    cache["cv"], v[None].astype(cache["cv"].dtype), (ix, 0, idx, 0, 0)
                )
                new_kv = {"ck": ck, "cv": cv}
                k = jax.lax.dynamic_index_in_dim(ck, ix, 0, keepdims=False).astype(cfg.dtype)
                v = jax.lax.dynamic_index_in_dim(cv, ix, 0, keepdims=False).astype(cfg.dtype)

        # the pallas kernel bakes in 1/sqrt(D) scaling and a plain
        # causal+padding mask; architectures with nonstandard scaling or
        # extra additive biases (alibi, local windows) take the XLA path
        plain_bias = (
            cfg.attn_scale is None
            and cfg.pos_embed != "alibi"
            and cfg.local_window is None
        )
        # prefill (cache present, T>1) can use the pallas kernel when the
        # cache carries a STATIC write index (a Python int placed by
        # init_cache/generate; a cache crossing a jit boundary turns it
        # into a tracer and this cleanly falls back to XLA): queries sit
        # at slots [static_index, static_index+T) against the full cache
        # length. Decode steps (T=1) stay XLA — they're memory-bound.
        # Mosaic lowers the kernels' dynamic chunk loads only at aligned
        # offsets: cache length (lane dim of the mask load, chunked at
        # >=128 when 128 | S) and query length (sublane q blocks, 8-row
        # granularity). generate() rounds its cache to 128 slots so real
        # rollouts always qualify; unaligned callers fall back to XLA.
        prefill_offset = None
        if (
            cache is not None
            and T > 1
            and isinstance(cache.get("static_index"), int)
            and cache["ck"].shape[2] % 128 == 0
            and T % 8 == 0
        ):
            prefill_offset = cache["static_index"]
        use_pallas = (
            cfg.attention_impl == "pallas"
            and ring_mesh is None
            and key_mask is not None
            and plain_bias
            and (cache is None or prefill_offset is not None)
            and kernel_out is None
        )
        if Hkv != H and not use_pallas and kernel_out is None:
            # grouped-query on the XLA/ring paths: repeat kv heads (the
            # pallas kernel handles GQA natively and must NOT see
            # repeated kv — that would forfeit its grouped HBM reads)
            rep = H // Hkv
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

        if kernel_out is not None:
            out = kernel_out
        elif ring_mesh is not None:
            # sequence-parallel path: K/V rotate around the `sp` ring via
            # ppermute while each shard accumulates its queries' attention
            # (TransformerLM._ring_mesh gates on plain-bias archs, full
            # teacher-forced forwards and mesh-divisible shapes)
            from trlx_tpu.ops.ring_attention import ring_attention_sharded

            out = ring_attention_sharded(
                q, k, v, ring_mesh, segment_mask=key_mask, causal=True
            )
        elif use_pallas:
            from trlx_tpu.ops.flash_attention import flash_attention

            out = flash_attention(
                q.transpose(0, 2, 1, 3),
                k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3),
                key_mask,
                q_offset=prefill_offset,
            ).transpose(0, 2, 1, 3)
        else:
            scale = cfg.attn_scale if cfg.attn_scale is not None else 1.0 / math.sqrt(D)
            # [B, H, T, S]; accumulate scores in fp32 for stability
            scores = jnp.einsum(
                "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
            ) * scale
            scores = scores + attn_bias
            probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bhts,bshd->bthd", probs, v)

        out_bias = (
            cfg.use_attn_out_bias
            if cfg.use_attn_out_bias is not None
            else cfg.use_attn_bias
        )
        proj = QDense(
            features=E,
            axis=(-2, -1),
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02 / math.sqrt(2 * cfg.n_layer)),
            use_bias=out_bias,
            name="o",
        )
        return proj(out), new_kv


class QDense(nn.Module):
    """DenseGeneral-compatible linear that additionally accepts an int8
    kernel with a per-output-channel dequant scale.

    Same param names/shapes/init as `nn.DenseGeneral` (kernel =
    (input_dims..., features...), zero bias), so checkpoints and HF
    interop are unchanged. At decode time `quantize_decode_weights`
    rewrites the param tree: kernel → int8, plus a `kernel_scale` leaf
    this module detects via `has_variable`. The int8→compute-dtype
    convert fuses into the dot's operand load, so the HBM weight stream
    halves (the dominant decode cost at 1.3B: 2.4 GB of block weights
    per step); the scale multiplies the tiny output because per-output-
    channel scaling commutes out of the contraction. Training paths
    never see a scale and run the exact DenseGeneral math.
    """

    features: Any  # int or tuple
    axis: Any = -1  # int or tuple of input axes to contract
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.normal(0.02)
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: Array) -> Array:
        feats = (
            self.features if isinstance(self.features, tuple)
            else (self.features,)
        )
        axes = self.axis if isinstance(self.axis, tuple) else (self.axis,)
        axes = tuple(a % x.ndim for a in axes)
        in_shape = tuple(x.shape[a] for a in axes)
        kernel = self.param(
            "kernel", self.kernel_init, in_shape + feats, self.param_dtype
        )
        y = jax.lax.dot_general(
            x.astype(self.dtype),
            kernel.astype(self.dtype),
            ((axes, tuple(range(len(axes)))), ((), ())),
        )
        if self.has_variable("params", "kernel_scale"):
            y = y * self.get_variable("params", "kernel_scale").astype(
                self.dtype
            )
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, feats, self.param_dtype
            )
            y = y + bias.astype(self.dtype)
        return y


def quantize_decode_weights(params: Dict) -> Dict:
    """Rewrite every stacked block kernel to int8 + per-output-channel
    scale (consumed by QDense) for the decode loop.

    Decode reads every weight once per token: at 1.3B the 2.4 GB of
    block kernels dominate the per-step HBM budget even after the int8
    KV cache. Per-output-channel symmetric scales keep the error at the
    per-matmul level (~0.4% relative); sampling runs the SAME quantized
    policy for prefill and every decode step, so trajectories are
    self-consistent — the teacher-forced experience pass then scores
    them with the full-precision weights, which is the usual
    behavior-policy/scoring split (same contract as the int8 KV cache,
    quantize_kv_cache above). Embeddings and the logit projection stay
    in compute dtype (the tied wte must serve lookups).

    Only kernels under `blocks` dense modules are rewritten; scan
    xs-slicing delivers per-layer int8 kernels + scales to QDense
    automatically.
    """
    # feature rank by dense-module name (kernel = (L, inputs..., feats...))
    n_feats = {"q": 2, "k": 2, "v": 2, "o": 1,
               "fc_in": 1, "fc_gate": 1, "fc_out": 1}

    def walk(tree, name=None):
        out = {}
        for child_name, leaf in tree.items():
            if isinstance(leaf, dict):
                out[child_name] = walk(leaf, child_name)
            else:
                out[child_name] = leaf
        if name in n_feats and "kernel" in tree:
            w = tree["kernel"].astype(jnp.float32)
            red = tuple(range(1, w.ndim - n_feats[name]))  # input dims
            s = jnp.max(jnp.abs(w), axis=red) / 127.0  # [L, feats...]
            out["kernel"] = jnp.round(
                w / jnp.maximum(jnp.expand_dims(s, red), 1e-12)
            ).astype(jnp.int8)
            out["kernel_scale"] = s.astype(jnp.float32)
        return out

    return dict(params, blocks=walk(params["blocks"]))


def _quantize_kv(x: Array) -> Tuple[Array, Array]:
    """Symmetric per-(…, head) int8 quantization over the trailing D
    axis: returns (int8 values, per-row fp32 scales shaped x.shape[:-1]).
    Rows of zeros (unwritten cache slots) get scale 0 and dequantize
    back to exact zeros."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    s = amax / 127.0
    q = jnp.round(
        x.astype(jnp.float32) / jnp.maximum(s, 1e-12)[..., None]
    ).astype(jnp.int8)
    return q, s


def quantize_kv_cache(cache: Dict) -> Dict:
    """One-shot int8 quantization of a prefilled KV cache.

    Decode at large batch×seq is HBM-bandwidth-bound on the full-cache
    read every step (3.22 GB at 1.3B b8 seq2048 in bf16); int8 halves
    that stream. Quantizing AFTER prefill keeps the pallas prefill path
    byte-identical — only the decode loop sees int8, and Attention's
    scaled-score path (see the cache branch in Attention.__call__)
    never materializes a dequantized copy. The reference has no KV
    quantization at all (HF `generate` caches follow model dtype); this
    is a TPU-roofline design choice, opt-in via
    TransformerConfig.kv_cache_quant="int8".

    Layout change: the bf16 cache is [L, B, S, Hkv, D]; the quantized
    cache is [L, B, Hkv, S, D] — kv-head OUTSIDE the slot axis, so the
    fused decode kernel's per-(batch, kv-head) grid cells read plain
    trailing (S, D) tiles (ops/decode_attention.py). Scales: K per
    (layer, batch, kv-head, slot) over D, stored [L, B, Hkv, 1, S]; V
    per (layer, batch, kv-head, channel) over the slot axis, stored
    [L, B, Hkv, 1, D] and FROZEN here — decode writes saturate against
    it. The 1.25x headroom covers new tokens whose |v| drifts past the
    prefix max on a channel (post-norm value magnitudes are
    near-stationary across decode); saturation error is bounded either
    way, and the headroom costs ~0.3 bits of prefix precision.
    """
    k = cache["k"].astype(jnp.float32).transpose(0, 1, 3, 2, 4)
    v = cache["v"].astype(jnp.float32).transpose(0, 1, 3, 2, 4)
    kq, ks = _quantize_kv(k)  # per (L, B, Hkv, S) over D — the same
    # formula Attention's decode write path applies to new columns
    vs = jnp.max(jnp.abs(v), axis=3) * (1.25 / 127.0)  # [L, B, Hkv, D]
    vq = jnp.clip(
        jnp.round(v / jnp.maximum(vs, 1e-12)[:, :, :, None]), -127.0, 127.0
    ).astype(jnp.int8)
    out = dict(
        cache, k=kq, v=vq,
        k_scale=ks[:, :, :, None].astype(jnp.float32),
        v_scale=vs[:, :, :, None].astype(jnp.float32),
    )
    out.pop("static_index", None)  # decode loops carry arrays only
    return out


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: Array) -> Array:
        cfg = self.cfg
        act = _activation(cfg.activation)
        up = partial(
            QDense,
            features=cfg.intermediate_size,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            use_bias=cfg.use_mlp_bias,
        )
        h = act(up(name="fc_in")(x))
        if cfg.mlp_gated:
            h = h * up(name="fc_gate")(x)
        down = QDense(
            features=cfg.hidden_size,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02 / math.sqrt(2 * cfg.n_layer)),
            use_bias=cfg.use_mlp_bias,
            name="fc_out",
        )
        return down(h)


class Block(nn.Module):
    """Pre-norm decoder block; sequential (gpt2/llama) or parallel
    (gptj/neox) residual layout."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        x: Array,
        attn_bias: Array,
        positions: Array,
        cache: Optional[Dict[str, Array]] = None,
        key_mask: Optional[Array] = None,
        ring_mesh=None,
    ) -> Tuple[Array, Optional[Dict[str, Array]]]:
        cfg = self.cfg
        h = Norm(cfg, name="ln_1")(x)
        attn_out, new_kv = Attention(cfg, name="attn")(
            h, attn_bias, positions, cache, key_mask, ring_mesh
        )
        if cfg.parallel_residual:
            x = x + attn_out + MLP(cfg, name="mlp")(h)
        else:
            x = x + attn_out
            x = x + MLP(cfg, name="mlp")(Norm(cfg, name="ln_2")(x))
        return x, new_kv


class Embedding(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, input_ids: Array, positions: Array) -> Array:
        cfg = self.cfg
        wte = self.param(
            "wte", nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype,
        )
        h = jnp.take(wte, input_ids, axis=0)
        if cfg.pos_embed == "learned":
            # pos_offset: OPT's table carries 2 leading pad rows; real
            # position i lives at table row i + offset
            wpe = self.param(
                "wpe", nn.initializers.normal(0.01),
                (cfg.n_positions + cfg.pos_offset, cfg.hidden_size), cfg.param_dtype,
            )
            h = h + jnp.take(
                wpe,
                jnp.clip(positions, 0, cfg.n_positions - 1) + cfg.pos_offset,
                axis=0,
            )
        return h.astype(cfg.dtype)

    def attend(self, hidden: Array) -> Array:
        """Tied-embedding logits: hidden @ wte.T (fp32 accumulation)."""
        wte = self.get_variable("params", "wte")
        return jnp.einsum(
            "bte,ve->btv", hidden, wte.astype(hidden.dtype),
            preferred_element_type=jnp.float32,
        )


class LMHead(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, hidden: Array) -> Array:
        kernel = self.param(
            "kernel", nn.initializers.normal(0.02),
            (self.cfg.hidden_size, self.cfg.vocab_size), self.cfg.param_dtype,
        )
        return jnp.einsum(
            "bte,ev->btv", hidden, kernel.astype(hidden.dtype),
            preferred_element_type=jnp.float32,
        )


# ---------------------------------------------------------------------------
# Functional composition: explicit param tree, scan over stacked layers
# ---------------------------------------------------------------------------


def logit_projection(params: Dict):
    """hidden -> fp32 logits closure over a TransformerLM param tree
    (tied wte or untied lm_head), matching `TransformerLM._logits`
    numerics exactly (compute-dtype matmul, fp32 accumulation). Feeds
    `ops.common.chunked_logprobs` so losses can avoid materializing
    full [B, T, V] logits."""
    if "lm_head" in params:
        kernel = params["lm_head"]["kernel"]

        def proj(h: Array) -> Array:
            return jnp.einsum(
                "...e,ev->...v", h, kernel.astype(h.dtype),
                preferred_element_type=jnp.float32,
            )

        return proj
    wte = params["embed"]["wte"]

    def proj(h: Array) -> Array:
        return jnp.einsum(
            "...e,ve->...v", h, wte.astype(h.dtype),
            preferred_element_type=jnp.float32,
        )

    return proj


def make_attention_bias(
    key_mask: Array,  # [B, S] 1 = attendable key slot
    q_slots: Array,  # [T] or [B, T] slot index of each query token
    k_slots: Array,  # [S] slot index of each key slot
) -> Array:
    """Additive causal+padding bias [B, 1, T, S] in fp32.

    Causality compares SLOT indices (physical storage order), which stays
    correct under left padding; rope/wpe positions are a separate notion
    (real position = cumsum of the mask) handled by the caller.
    """
    if q_slots.ndim == 1:
        q_slots = q_slots[None, :]
    causal = q_slots[:, :, None] >= k_slots[None, None, :]
    visible = causal & (key_mask[:, None, :] > 0)
    return jnp.where(visible, 0.0, NEG_INF)[:, None, :, :].astype(jnp.float32)


class TransformerLM:
    """Functional causal LM: explicit params, scan-over-layers forward.

    params pytree:
      embed:  {wte, [wpe]}
      blocks: every Block param stacked with leading axis n_layer
      ln_f:   final norm
      [lm_head]: untied output projection

    Not an nn.Module by design — explicit params let the PPO hydra branch
    (`forward_from_layer` over a sliced param stack) and per-layer freeze
    masks operate on the tree directly (SURVEY.md §2.5 ModelBranch
    collapse).
    """

    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg
        self.embed = Embedding(cfg)
        self.block = Block(cfg)
        self.ln_f = Norm(cfg)  # stateless: also applied with ln_embed params
        self.lm_head = None if cfg.tie_word_embeddings else LMHead(cfg)
        # set by the trainer when cfg.attention_impl == "ring": the device
        # mesh whose `sp` axis carries the sequence shards
        self.mesh = None

    def _ring_mesh(self, batch: int, seq: int, cache) -> Optional[Any]:
        """The mesh to run ring attention over, or None for the XLA/pallas
        paths. Static (trace-time) decision: ring needs a full
        teacher-forced forward, a plain causal+padding bias, and shapes
        divisible by the mesh axes shard_map will split them over."""
        cfg = self.cfg
        if cfg.attention_impl != "ring" or self.mesh is None or cache is not None:
            return None
        if (
            cfg.attn_scale is not None
            or cfg.pos_embed == "alibi"
            or cfg.local_window is not None
        ):
            return None
        m = self.mesh.shape
        if m.get("sp", 1) <= 1:
            return None
        if (
            seq % m["sp"]
            or batch % (m["dp"] * m["fsdp"])
            or cfg.n_head % m["tp"]
        ):
            # sp>1 was requested but this call can't ring-shard — falling
            # back to full attention materializes the O(T^2) bias the user
            # configured sp to avoid, so say so (warnings dedupe per site)
            import warnings

            warnings.warn(
                f"ring attention requested (sp={m['sp']}) but shapes "
                f"batch={batch}, seq={seq}, n_head={cfg.n_head} don't divide "
                f"mesh axes {dict(m)}; falling back to full XLA attention",
                stacklevel=3,
            )
            return None
        return self.mesh

    def _pp_microbatches(self, batch: int, cache) -> int:
        """Microbatch count for a pipelined forward, or 0 for the
        sequential scan. Static (trace-time) decision. Pipelining needs a
        teacher-forced forward (decode steps thread a KV cache through
        every layer sequentially anyway) and divisible shapes; ring
        attention (sp) composes with dp/fsdp/tp but not with pp —
        eligibility rules live in parallel.pipeline.pp_microbatch_count,
        shared with the seq2seq stacks."""
        from trlx_tpu.parallel.pipeline import pp_microbatch_count

        if cache is not None:
            return 0
        return pp_microbatch_count(
            self.mesh, self.cfg.n_layer, batch, self.cfg.pp_microbatches
        )

    def _pipeline_blocks(
        self,
        block_params: Dict,
        h: Array,
        attn_bias: Array,
        positions: Array,
        *,
        n_microbatch: int,
        remat: bool = False,
        key_mask: Optional[Array] = None,
        local_bias: Optional[Array] = None,
        capture_points: Tuple[int, ...] = (),
    ) -> Tuple[Array, Tuple[Array, ...]]:
        """The pipelined counterpart of `_scan_blocks` over the FULL layer
        stack: stages = contiguous slices of the stacked params on the
        mesh's `pp` axis, GPipe microbatch schedule, captures returned for
        the hydra/value branches (parallel/pipeline.py has the schedule)."""
        from trlx_tpu.parallel.pipeline import pipelined_layers

        cfg = self.cfg
        flags = self._layer_flags(cfg.n_layer, 0)
        xs: Dict[str, Any] = {"p": block_params}
        if flags is not None:
            xs["flag"] = flags
        ctx = {
            "bias": attn_bias,
            "pos": positions,
            "km": key_mask,
            "lb": local_bias,
        }

        def layer_apply(layer, h, ctx_mb):
            bias = ctx_mb["bias"]
            if "flag" in layer:
                bias = bias + layer["flag"] * ctx_mb["lb"]
            out, _ = self.block.apply(
                {"params": layer["p"]}, h, bias, ctx_mb["pos"], None,
                ctx_mb["km"], None,
            )
            return out

        return pipelined_layers(
            self.mesh,
            layer_apply,
            xs,
            h,
            ctx,
            n_microbatch=n_microbatch,
            capture_points=capture_points,
            remat=remat,
            schedule=cfg.pp_schedule,
        )

    # -- bias / embedding helpers ---------------------------------------

    def _build_bias(
        self, key_mask: Array, q_slots: Array, k_slots: Array
    ) -> Tuple[Array, Optional[Array]]:
        """(attn_bias, local_bias): the base causal+padding bias, with the
        per-key ALiBi term folded in for bloom-style models, plus the extra
        sliding-window bias applied only on "local" layers (gpt-neo)."""
        cfg = self.cfg
        bias = make_attention_bias(key_mask, q_slots, k_slots)
        if cfg.pos_embed == "alibi":
            key_pos = jnp.maximum(jnp.cumsum(key_mask, axis=1) - 1, 0)
            alibi = (
                alibi_slopes(cfg.n_head)[None, :, None, None]
                * key_pos.astype(jnp.float32)[:, None, None, :]
            )
            bias = bias + alibi * (key_mask[:, None, None, :] > 0)
        local_bias = None
        if cfg.local_window is not None:
            qs = q_slots if q_slots.ndim == 2 else q_slots[None, :]
            dist = qs[:, :, None] - k_slots[None, None, :]  # [1|B, T, S]
            local_bias = jnp.where(dist >= cfg.local_window, NEG_INF, 0.0)[
                :, None, :, :
            ].astype(jnp.float32)
        return bias, local_bias

    def _embed_h(self, params: Dict, input_ids: Array, positions: Array) -> Array:
        h = self.embed.apply({"params": params["embed"]}, input_ids, positions)
        if self.cfg.embed_layernorm:
            h = self.ln_f.apply({"params": params["ln_embed"]}, h)
        return h

    def _layer_flags(self, n: int, layer_offset: int) -> Optional[Array]:
        """1.0 for layers using the local sliding window, else 0.0 — for
        the n layers starting at layer_offset in the full stack."""
        cfg = self.cfg
        if cfg.attn_layers is None or cfg.local_window is None:
            return None
        kinds = cfg.attn_layers[layer_offset : layer_offset + n]
        return jnp.asarray(
            [1.0 if k == "local" else 0.0 for k in kinds], jnp.float32
        )

    # -- init ------------------------------------------------------------

    def init(self, rng: jax.Array) -> Dict:
        cfg = self.cfg
        B, T = 1, 8
        ids = jnp.zeros((B, T), jnp.int32)
        pos = jnp.arange(T)[None, :]
        bias = make_attention_bias(jnp.ones((B, T), jnp.int32), pos, jnp.arange(T))

        r_embed, r_block, r_head, r_lm = jax.random.split(rng, 4)
        embed_params = self.embed.init(r_embed, ids, pos)["params"]
        h = jnp.zeros((B, T, cfg.hidden_size), cfg.dtype)

        block_params = jax.vmap(
            lambda key: self.block.init(key, h, bias, pos)["params"]
        )(jax.random.split(r_block, cfg.n_layer))
        params = {
            "embed": embed_params,
            "blocks": block_params,
            "ln_f": self.ln_f.init(r_head, h)["params"],
        }
        if cfg.embed_layernorm:
            params["ln_embed"] = self.ln_f.init(r_head, h)["params"]
        if self.lm_head is not None:
            params["lm_head"] = self.lm_head.init(r_lm, h)["params"]
        return params

    # -- forward ---------------------------------------------------------

    def _scan_blocks(
        self,
        block_params: Dict,
        h: Array,
        attn_bias: Array,
        positions: Array,
        cache: Optional[Dict[str, Array]] = None,
        remat: bool = False,
        key_mask: Optional[Array] = None,
        local_bias: Optional[Array] = None,
        layer_offset: int = 0,
        ring_mesh=None,
    ) -> Tuple[Array, Optional[Dict[str, Array]]]:
        """lax.scan over the stacked layer params (and cache layers).
        `layer_offset` locates this slice within the full stack so
        per-layer attention kinds (gpt-neo global/local) line up.

        Cache path: the [L, B, S, Hkv, D] buffers are CARRIED through
        the scan; each layer's attention writes only its new
        [B, T, Hkv, D] column in place and attends against a slice of
        the updated buffer (update-carry-first — the full design
        history and measured costs are in Attention.__call__)."""
        n = jax.tree_util.tree_leaves(block_params)[0].shape[0]
        flags = self._layer_flags(n, layer_offset)

        if cache is not None and "pk" in cache:
            # paged cache: the scan carries the page POOLS; the page
            # table / slot positions / validity masks are per-forward
            # constants (the engine advances them between forwards), so
            # they ride the closure, not the carry
            pool_keys = tuple(
                name for name in ("pk", "pv", "pk_scale", "pv_scale")
                if name in cache
            )
            meta = {
                name: cache[name]
                for name in (
                    "page_table", "slot_pos", "lane_valid", "contiguous",
                    "attn_impl",
                )
                if name in cache
            }

            def paged_body(carry, layer):
                hidden = carry[0]
                layer_cache = dict(zip(pool_keys, carry[1:]), ix=layer["ix"], **meta)
                lp = layer["p"]
                bias = attn_bias
                if flags is not None:
                    bias = bias + layer["flag"] * local_bias
                out, new_kv = self.block.apply(
                    {"params": lp}, hidden, bias, positions, layer_cache,
                    key_mask, ring_mesh,
                )
                return (out,) + tuple(new_kv[k] for k in pool_keys), None

            from trlx_tpu.ops.remat import wrap_remat as _wrap

            paged_body = _wrap(paged_body, remat)
            # "layer_ixs" remaps this forward's layers onto pool layer
            # slots (gen_engine's spec-decode trunk sharing: the hydra
            # DRAFT's trunk layers index the policy pool's trunk — their
            # KV is identical by construction — while its branch layers
            # index the extension slots past the policy stack)
            layer_ixs = cache.get("layer_ixs")
            if layer_ixs is None:
                layer_ixs = jnp.arange(n)
            xs: Dict[str, Any] = {"p": block_params, "ix": layer_ixs}
            if flags is not None:
                xs["flag"] = flags
            carry, _ = jax.lax.scan(
                paged_body, (h,) + tuple(cache[k] for k in pool_keys), xs
            )
            new_cache = dict(cache, **dict(zip(pool_keys, carry[1:])))
            return carry[0], new_cache

        quant = cache is not None and "k_scale" in cache

        def body(carry, layer):
            if cache is not None:
                # hand the attention the FULL carried buffers + this
                # layer's row index: it writes its new column in place
                # and attends against a slice of the updated buffer (the
                # update-carry-first design; rationale in Attention)
                if quant:
                    hidden, ck, cv, cks = carry
                    layer_cache = {
                        "ck": ck, "cv": cv,
                        "ck_scale": cks,
                        # frozen per-layer V scales ride the scan's xs
                        # (sliced to this layer's [B, Hkv, D] row), not
                        # the carry: decode never updates them
                        "v_scale": layer["vs"],
                        "ix": layer["ix"], "index": cache["index"],
                    }
                else:
                    hidden, ck, cv = carry
                    layer_cache = {
                        "ck": ck,
                        "cv": cv,
                        "ix": layer["ix"],
                        "index": cache["index"],
                    }
                if "static_index" in cache:  # pallas prefill offset
                    layer_cache["static_index"] = cache["static_index"]
            else:
                hidden = carry
                layer_cache = None
            lp = layer["p"]
            bias = attn_bias
            if flags is not None:
                bias = bias + layer["flag"] * local_bias
            out, new_kv = self.block.apply(
                {"params": lp}, hidden, bias, positions, layer_cache, key_mask,
                ring_mesh,
            )
            if quant:
                return (out, new_kv["ck"], new_kv["cv"], new_kv["ck_scale"]), None
            if cache is not None:
                return (out, new_kv["ck"], new_kv["cv"]), None
            return out, None

        from trlx_tpu.ops.remat import wrap_remat

        body = wrap_remat(body, remat)

        xs: Dict[str, Any] = {"p": block_params}
        if cache is not None:
            xs["ix"] = jnp.arange(n)
        if flags is not None:
            xs["flag"] = flags
        if quant:
            xs["vs"] = cache["v_scale"]
            (h, ck, cv, cks), _ = jax.lax.scan(
                body,
                (h, cache["k"], cache["v"], cache["k_scale"]),
                xs,
            )
            new_cache = dict(
                k=ck, v=cv, k_scale=cks, v_scale=cache["v_scale"],
                index=cache["index"] + positions.shape[1],
                key_mask=cache["key_mask"],
            )
        elif cache is not None:
            (h, ck, cv), _ = jax.lax.scan(body, (h, cache["k"], cache["v"]), xs)
            new_cache = dict(
                k=ck, v=cv, index=cache["index"] + positions.shape[1],
                key_mask=cache["key_mask"],
            )
        else:
            h, _ = jax.lax.scan(body, h, xs)
            new_cache = None
        return h, new_cache

    def __call__(
        self,
        params: Dict,
        input_ids: Array,  # [B, T]
        attention_mask: Optional[Array] = None,  # [B, T]
        positions: Optional[Array] = None,
        cache: Optional[Dict[str, Array]] = None,
        remat: bool = False,
        prefix_embeds: Optional[Array] = None,  # [n, E] prompt tuning
        kv_prefix: Optional[Dict[str, Array]] = None,  # {k,v}: [L, n, Hkv, D]
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        """Full forward. Without `cache`: plain teacher-forced pass over a
        (possibly left-padded) sequence. With `cache`: the input occupies
        cache slots [index, index+T) and attends over the cache prefix —
        the same entry point serves prefill (T=prompt_len) and decode
        (T=1).

        Adapters (teacher-forced paths; generation warms the KV cache
        instead — see models/generation.py):
        - `prefix_embeds` (PROMPT tuning): n trainable soft tokens run as
          real leading sequence positions; outputs keep [B, T] shapes
          (the virtual rows are sliced off after the blocks).
        - `kv_prefix` (PREFIX tuning): trainable per-layer key/values,
          realized as a pre-warmed pseudo-cache so the attention path is
          untouched. Real-token positions shift by n in both cases
          (HF peft past-length semantics)."""
        B, T = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, T), jnp.int32)
        n_virtual = 0  # rows to slice off the outputs (prompt tuning)
        if prefix_embeds is not None and cache is None:
            # teacher-forced prompt tuning: soft tokens become real
            # leading positions; callers keep [B, T] output shapes
            n_virtual = prefix_embeds.shape[0]
            input_ids = jnp.concatenate(
                [jnp.zeros((B, n_virtual), input_ids.dtype), input_ids], axis=1
            )
            attention_mask = jnp.concatenate(
                [jnp.ones((B, n_virtual), jnp.int32), attention_mask], axis=1
            )
            positions = None  # recomputed over the extended mask below
            T = T + n_virtual
        if kv_prefix is not None and cache is None:
            # prefix tuning: trainable per-layer k/v realized as a
            # pre-warmed pseudo-cache occupying slots [0, n); the input
            # occupies [n, n+T) so the attention path is untouched
            n = kv_prefix["k"].shape[1]
            S = n + T
            shape = (self.cfg.n_layer, B, S) + kv_prefix["k"].shape[2:]

            def tiled(x):
                return jnp.broadcast_to(
                    x[:, None], (self.cfg.n_layer, B) + x.shape[1:]
                ).astype(self.cfg.dtype)

            cache = {
                "k": jax.lax.dynamic_update_slice_in_dim(
                    jnp.zeros(shape, self.cfg.dtype), tiled(kv_prefix["k"]), 0, axis=2
                ),
                "v": jax.lax.dynamic_update_slice_in_dim(
                    jnp.zeros(shape, self.cfg.dtype), tiled(kv_prefix["v"]), 0, axis=2
                ),
                "index": jnp.int32(n),
                "static_index": n,
                "key_mask": jnp.concatenate(
                    [jnp.ones((B, n), jnp.int32), attention_mask], axis=1
                ),
            }
            # pad-aware positions shifted past the prefix (HF past-length
            # semantics)
            positions = n + jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)
        ring = None
        if cache is not None and "pk" in cache:
            # paged cache (gen_engine): per-ROW slot positions — each
            # decode lane sits at its own depth, unlike the dense cache's
            # single scalar write index. The engine precomputes key_mask
            # to cover exactly the valid logical slots INCLUDING the T
            # incoming tokens; causality among those tokens falls out of
            # the slot-index comparison in make_attention_bias.
            S = cache["page_table"].shape[1] * cache["pk"].shape[2]
            q_slots = cache["slot_pos"][:, None] + jnp.arange(T)[None, :]
            if positions is None:
                positions = q_slots
            key_mask = cache["key_mask"].astype(jnp.int32)
            bias, local_bias = self._build_bias(key_mask, q_slots, jnp.arange(S))
            layer_cache = cache
        elif cache is not None:
            # bf16 cache: [L, B, S, Hkv, D]; int8 (quantized) cache:
            # [L, B, Hkv, S, D] (layout rationale: quantize_kv_cache)
            S = cache["k"].shape[3 if "k_scale" in cache else 2]
            q_slots = cache["index"] + jnp.arange(T)
            if positions is None:
                positions = q_slots[None, :] * jnp.ones((B, 1), jnp.int32)
            within = jnp.arange(S)[None, :] < cache["index"] + T  # [1, S]
            key_mask = (within & (cache["key_mask"] > 0)).astype(jnp.int32)
            bias, local_bias = self._build_bias(key_mask, q_slots, jnp.arange(S))
            layer_cache = cache
        else:
            if positions is None:
                positions = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)
            ring = self._ring_mesh(B, T, cache)
            if ring is not None:
                # the ring path masks via per-shard segment masks and global
                # position comparison — never materialize the [B,1,T,T] bias
                bias, local_bias = None, None
            else:
                bias, local_bias = self._build_bias(
                    attention_mask, jnp.arange(T), jnp.arange(T)
                )
            layer_cache = None

        h = self._embed_h(params, input_ids, positions)
        if prefix_embeds is not None:
            # the virtual slots were embedded as token 0 (+wpe): swap the
            # wte row for the trainable soft embedding, keeping wpe
            n_rows = n_virtual if n_virtual else h.shape[1]
            wte0 = params["embed"]["wte"][0].astype(h.dtype)
            soft = prefix_embeds[None, :n_rows].astype(h.dtype)
            h = jax.lax.dynamic_update_slice_in_dim(
                h, h[:, :n_rows] - wte0 + soft, 0, axis=1
            )
        n_mb = 0 if ring is not None else self._pp_microbatches(B, layer_cache)
        if n_mb:
            h, _ = self._pipeline_blocks(
                params["blocks"], h, bias, positions, n_microbatch=n_mb,
                remat=remat, key_mask=attention_mask, local_bias=local_bias,
            )
            new_cache = None
        else:
            h, new_cache = self._scan_blocks(
                params["blocks"], h, bias, positions, layer_cache, remat=remat,
                key_mask=key_mask if cache is not None else attention_mask,
                local_bias=local_bias,
                ring_mesh=None if cache is not None else ring,
            )
        hidden = self.ln_f.apply({"params": params["ln_f"]}, h)
        # compute_logits=False: callers using chunked-from-hidden losses
        # (train.logit_chunks) skip the full [B, T, V] projection here
        logits = self._logits(params, hidden) if compute_logits else None
        if n_virtual:
            hidden = hidden[:, n_virtual:]
            logits = logits[:, n_virtual:] if logits is not None else None
            positions = positions[:, n_virtual:]
        return {
            "logits": logits,
            "hidden_states": hidden,
            "cache": new_cache,
            "positions": positions,
        }

    def _logits(self, params: Dict, hidden: Array) -> Array:
        if self.lm_head is not None:
            return self.lm_head.apply({"params": params["lm_head"]}, hidden)
        return self.embed.apply(
            {"params": params["embed"]}, hidden, method=Embedding.attend
        )

    # -- hydra support ---------------------------------------------------

    def forward_with_branch_capture(
        self,
        params: Dict,
        input_ids: Array,
        attention_mask: Optional[Array],
        branch_at: int,
        remat: bool = False,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        """Forward that also returns the hidden state entering layer
        `branch_at`: the scan is split into [0, branch_at) + [branch_at,
        L), same total compute. The captured hidden feeds the frozen
        reference branch (`forward_from_layer`)."""
        B, T = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, T), jnp.int32)
        positions = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)
        ring = self._ring_mesh(B, T, None)
        if ring is not None:
            bias, local_bias = None, None
        else:
            bias, local_bias = self._build_bias(
                attention_mask, jnp.arange(T), jnp.arange(T)
            )
        h = self._embed_h(params, input_ids, positions)

        n_mb = 0 if ring is not None else self._pp_microbatches(B, None)
        if n_mb:
            h_top, (h_branch,) = self._pipeline_blocks(
                params["blocks"], h, bias, positions, n_microbatch=n_mb,
                remat=remat, key_mask=attention_mask, local_bias=local_bias,
                capture_points=(branch_at,),
            )
        else:
            bottom = jax.tree_util.tree_map(
                lambda x: x[:branch_at], params["blocks"]
            )
            top = jax.tree_util.tree_map(lambda x: x[branch_at:], params["blocks"])
            h_branch, _ = self._scan_blocks(
                bottom, h, bias, positions, remat=remat, key_mask=attention_mask,
                local_bias=local_bias, ring_mesh=ring,
            )
            h_top, _ = self._scan_blocks(
                top, h_branch, bias, positions, remat=remat, key_mask=attention_mask,
                local_bias=local_bias, layer_offset=branch_at, ring_mesh=ring,
            )
        hidden = self.ln_f.apply({"params": params["ln_f"]}, h_top)
        logits = self._logits(params, hidden) if compute_logits else None
        return {
            "logits": logits,
            "hidden_states": hidden,
            "branch_hidden": h_branch,
            "positions": positions,
            "attn_bias": bias,
            "local_bias": local_bias,
            "key_mask": attention_mask,
        }

    def forward_with_multi_capture(
        self,
        params: Dict,
        input_ids: Array,
        attention_mask: Optional[Array],
        points: Tuple[int, ...],
        remat: bool = False,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        """Forward capturing the hidden state entering each layer index in
        `points` (sorted ascending). Generalizes branch capture so the
        hydra reference branch and the trainable value branch
        (reference make_value_branch, modeling_ppo.py:255-263) can fork at
        different depths in ONE trunk pass."""
        B, T = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, T), jnp.int32)
        positions = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)
        ring = self._ring_mesh(B, T, None)
        if ring is not None:
            bias, local_bias = None, None
        else:
            bias, local_bias = self._build_bias(
                attention_mask, jnp.arange(T), jnp.arange(T)
            )
        h = self._embed_h(params, input_ids, positions)

        n_mb = 0 if ring is not None else self._pp_microbatches(B, None)
        if n_mb:
            # match the sequential path: points >= n_layer are omitted
            # (never captured), not returned as zeros
            in_range = tuple(p for p in points if p < self.cfg.n_layer)
            h, caps = self._pipeline_blocks(
                params["blocks"], h, bias, positions, n_microbatch=n_mb,
                remat=remat, key_mask=attention_mask, local_bias=local_bias,
                capture_points=in_range,
            )
            captures = list(caps)
        else:
            captures = []
            prev = 0
            for point in tuple(points) + (self.cfg.n_layer,):
                if point > prev:
                    seg = jax.tree_util.tree_map(
                        lambda x: x[prev:point], params["blocks"]
                    )
                    h, _ = self._scan_blocks(
                        seg, h, bias, positions, remat=remat,
                        key_mask=attention_mask,
                        local_bias=local_bias, layer_offset=prev, ring_mesh=ring,
                    )
                if point < self.cfg.n_layer:
                    captures.append(h)
                prev = point
        hidden = self.ln_f.apply({"params": params["ln_f"]}, h)
        logits = self._logits(params, hidden) if compute_logits else None
        return {
            "logits": logits,
            "hidden_states": hidden,
            "captures": captures,
            "positions": positions,
            "attn_bias": bias,
            "local_bias": local_bias,
            "key_mask": attention_mask,
        }

    def forward_from_layer(
        self,
        branch_params: Dict,
        branch_hidden: Array,
        attn_bias: Array,
        positions: Array,
        remat: bool = False,
        local_bias: Optional[Array] = None,
        key_mask: Optional[Array] = None,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        """Run only a top-k branch from a captured hidden state.

        `branch_params` holds {"blocks": stacked top-k params, "ln_f",
        "embed", ["lm_head"]} — the frozen in-process reference model
        (parity: hydra `forward_hydra`, reference modeling_ppo.py:410-453).
        The branch is always the TOP k layers, so per-layer attention
        kinds are aligned from the end of the stack. With `attn_bias=None`
        (ring-attention capture) the padding mask rides in `key_mask`.
        """
        k = jax.tree_util.tree_leaves(branch_params["blocks"])[0].shape[0]
        ring = None
        if attn_bias is None and key_mask is not None:
            B, T = branch_hidden.shape[:2]
            ring = self._ring_mesh(B, T, None)
        h, _ = self._scan_blocks(
            branch_params["blocks"], branch_hidden, attn_bias, positions,
            remat=remat, local_bias=local_bias,
            layer_offset=self.cfg.n_layer - k,
            key_mask=key_mask, ring_mesh=ring,
        )
        hidden = self.ln_f.apply({"params": branch_params["ln_f"]}, h)
        logits = self._logits(branch_params, hidden) if compute_logits else None
        return {"logits": logits, "hidden_states": hidden}

    # -- cache -----------------------------------------------------------

    def init_cache(self, batch: int, max_len: int, key_mask: Optional[Array] = None) -> Dict:
        """Preallocate a static-shape KV cache [L, B, S, Hkv, D].

        `static_index` mirrors `index` as a PYTHON int while the cache
        stays inside one trace: it lets the first forward (prefill, T>1)
        take the pallas kernel at a static slot offset. Forwards drop it
        from the cache they return (decode loops carry arrays only), and
        a cache that crosses a jit boundary loses its int-ness — both
        cases just fall back to the XLA path."""
        cfg = self.cfg
        shape = (cfg.n_layer, batch, max_len, cfg.n_kv_head, cfg.head_dim)
        return {
            "k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "index": jnp.int32(0),
            "static_index": 0,
            "key_mask": key_mask if key_mask is not None
            else jnp.ones((batch, max_len), jnp.int32),
        }


def extract_branch_params(params: Dict, branch_at: int) -> Dict:
    """Copy the top-(L-branch_at) layers + final norm + logit head as a
    frozen reference branch. Parity: the hydra 'frozen_head' build
    (reference modeling_ppo.py:475-499) without per-arch classes."""
    branch = {
        "blocks": jax.tree_util.tree_map(lambda x: x[branch_at:], params["blocks"]),
        "ln_f": params["ln_f"],
        "embed": params["embed"],
    }
    if "lm_head" in params:
        branch["lm_head"] = params["lm_head"]
    return jax.lax.stop_gradient(branch)
