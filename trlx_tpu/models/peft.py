"""Parameter-efficient fine-tuning adapters: LoRA, prompt tuning and
prefix tuning, first-party and TPU-shaped.

Parity: the reference delegates to HF `peft`
(/root/reference/trlx/models/modeling_base.py:124-275 threads
peft_config through from_pretrained; /root/reference/tests/test_peft.py
is the contract — note the reference itself only exercises
{LORA, PROMPT_TUNING, PREFIX_TUNING} x causal and LORA x seq2seq, since
peft 0.3.0's seq2seq prompt/prefix variants were broken).

Adapter param layouts (all live beside "base" in the trainer's param
tree; the base stays frozen via the update mask):

  lora    {path: {"a": [L?, in, r], "b": [L?, r, out]}}  (models/lora.py)
  prompt  {"embedding": [n_virtual, E]}    soft tokens, run as real
                                           leading sequence positions
  prefix  {"k": [L, n_virtual, Hkv, D],    direct per-layer key/values,
           "v": [L, n_virtual, Hkv, D]}    realized as a pre-warmed
                                           pseudo KV cache

The model-side mechanics live in TransformerLM.__call__
(prefix_embeds / kv_prefix kwargs) and models/generation.py (cache
warm-up)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from trlx_tpu.models.lora import DEFAULT_TARGETS, init_lora_params  # noqa: F401


def normalize_peft_config(peft_config: Any) -> Optional[Dict[str, Any]]:
    """Accept an HF-peft-style dict ({"peft_type": ..., ...}) and
    normalize to our fields. Returns None for no adapter."""
    if peft_config is None:
        return None
    cfg = dict(peft_config)
    peft_type = str(cfg.get("peft_type", "LORA")).upper()
    if peft_type == "LORA":
        return {
            "peft_type": "LORA",
            "r": int(cfg.get("r", 8)),
            "alpha": float(cfg.get("lora_alpha", cfg.get("alpha", 16))),
            "targets": cfg.get("target_modules") or DEFAULT_TARGETS,
        }
    if peft_type in ("PROMPT_TUNING", "PREFIX_TUNING"):
        return {
            "peft_type": peft_type,
            "num_virtual_tokens": int(cfg.get("num_virtual_tokens", 10)),
        }
    raise ValueError(
        f"peft_type {peft_type!r} not supported "
        "(LORA | PROMPT_TUNING | PREFIX_TUNING)"
    )


def init_prompt_params(rng: jax.Array, cfg, n_virtual: int) -> Dict[str, jnp.ndarray]:
    """Soft-token embeddings ~ N(0, 0.02) ([RANDOM] init, the reference
    test's prompt_tuning_init)."""
    return {
        "embedding": jax.random.normal(
            rng, (n_virtual, cfg.hidden_size), jnp.float32
        )
        * 0.02
    }


def init_prefix_params(rng: jax.Array, cfg, n_virtual: int) -> Dict[str, jnp.ndarray]:
    """Per-layer key/value prefixes ~ N(0, 0.02), stacked over layers to
    match the scan-stacked block params."""
    n_kv = cfg.n_kv_head or cfg.n_head
    head_dim = cfg.head_dim or cfg.hidden_size // cfg.n_head
    k_rng, v_rng = jax.random.split(rng)
    shape = (cfg.n_layer, n_virtual, n_kv, head_dim)
    return {
        "k": jax.random.normal(k_rng, shape, jnp.float32) * 0.02,
        "v": jax.random.normal(v_rng, shape, jnp.float32) * 0.02,
    }


ADAPTER_KEYS = ("lora", "prompt", "prefix")


def adapter_call_kwargs(params: Dict) -> Dict[str, Any]:
    """kwargs for TransformerLM.__call__ from a wrapper param tree —
    threads prompt/prefix adapters into the forward (LoRA merges into
    the base weights instead, see wrappers._effective_base)."""
    kw = {}
    if "prompt" in params:
        kw["prefix_embeds"] = params["prompt"]["embedding"]
    if "prefix" in params:
        kw["kv_prefix"] = params["prefix"]
    return kw


# ---------------------------------------------------------------------------
# HF-peft checkpoint interop
# ---------------------------------------------------------------------------
# Parity: the reference loads both fresh peft configs and already-trained
# adapter checkpoints, and saves adapters in the HF-peft layout
# (/root/reference/trlx/models/modeling_base.py:124-326, 347-353). Here
# the on-disk contract is the same (adapter_config.json +
# adapter_model.safetensors) while the in-memory layout stays the
# TPU-shaped stacked tree above.

# per-layer HF module prefixes for families with SEPARATE q/k/v
# projections (matching models/hf.py's weight naming); fused-attention
# families (gpt2 c_attn, neox/bloom query_key_value) fall back to the
# logical layout below, which round-trips through load_peft_adapter but
# needs name adaptation for HF-side serving
_HF_LORA_MODULES = {
    "gptj": ("transformer.h.{i}.attn.", {"q": "q_proj", "k": "k_proj",
                                         "v": "v_proj", "o": "out_proj"}),
    "llama": ("model.layers.{i}.self_attn.", {"q": "q_proj", "k": "k_proj",
                                              "v": "v_proj", "o": "o_proj"}),
    "mistral": ("model.layers.{i}.self_attn.", {"q": "q_proj", "k": "k_proj",
                                                "v": "v_proj", "o": "o_proj"}),
    "opt": ("model.decoder.layers.{i}.self_attn.", {"q": "q_proj", "k": "k_proj",
                                                    "v": "v_proj", "o": "out_proj"}),
}
# logical fallback (also what load_peft_adapter emits for foreign names)
_LOGICAL_MODULE = "layers.{i}.{mod}"

# foreign HF module name -> our block-local module
_HF_TO_OURS = {
    "q_proj": "q", "k_proj": "k", "v_proj": "v",
    "o_proj": "o", "out_proj": "o", "dense": "o",
    "q": "q", "k": "k", "v": "v", "o": "o",
    "fc_in": "fc_in", "fc_out": "fc_out", "fc_gate": "fc_gate",
    "gate_proj": "fc_gate", "up_proj": "fc_in", "down_proj": "fc_out",
}
_OUR_PATH = {
    "q": "blocks/attn/q/kernel", "k": "blocks/attn/k/kernel",
    "v": "blocks/attn/v/kernel", "o": "blocks/attn/o/kernel",
    "fc_in": "blocks/mlp/fc_in/kernel", "fc_gate": "blocks/mlp/fc_gate/kernel",
    "fc_out": "blocks/mlp/fc_out/kernel",
}


def save_peft_adapter(
    directory: str,
    adapter_params: Dict[str, Any],  # {"lora": ...} | {"prompt": ...} | {"prefix": ...}
    peft_cfg: Dict[str, Any],  # normalize_peft_config output
    cfg,  # TransformerConfig (layer count / head geometry)
    model_type: Optional[str] = None,
) -> None:
    """Write an HF-peft-format adapter checkpoint: adapter_config.json
    + adapter_model.safetensors (torch tensors, per-layer names)."""
    import json
    import os

    import numpy as np
    import torch
    from safetensors.torch import save_file

    os.makedirs(directory, exist_ok=True)
    tensors: Dict[str, torch.Tensor] = {}
    adapter_config: Dict[str, Any] = {
        "peft_type": peft_cfg["peft_type"],
        "task_type": "CAUSAL_LM",
        "base_model_name_or_path": model_type or "",
    }

    if peft_cfg["peft_type"] == "LORA":
        adapter_config.update(
            r=peft_cfg["r"], lora_alpha=peft_cfg["alpha"], lora_dropout=0.0,
        )
        prefix_fmt, name_map = _HF_LORA_MODULES.get(
            model_type or "", (None, None)
        )
        target_modules = set()
        for path, ab in adapter_params["lora"].items():
            mod = path.split("/")[-2]  # q | k | v | o | fc_in | ...
            a = np.asarray(ab["a"], np.float32)  # [L?, in, r]
            b = np.asarray(ab["b"], np.float32)  # [L?, r, out]
            if a.ndim == 2:  # unstacked (lm_head): single module
                a, b = a[None], b[None]
                layers = [None]
            else:
                layers = range(a.shape[0])
            for li in layers:
                i = 0 if li is None else li
                if li is None:
                    module = "lm_head"
                elif prefix_fmt is not None and mod in name_map:
                    module = prefix_fmt.format(i=i) + name_map[mod]
                else:
                    module = _LOGICAL_MODULE.format(i=i, mod=mod)
                target_modules.add(module.rsplit(".", 1)[-1])
                base = f"base_model.model.{module}"
                # torch Linear convention: lora_A.weight [r, in],
                # lora_B.weight [out, r]
                tensors[f"{base}.lora_A.weight"] = torch.from_numpy(
                    np.ascontiguousarray(a[i].T)
                )
                tensors[f"{base}.lora_B.weight"] = torch.from_numpy(
                    np.ascontiguousarray(b[i].T)
                )
        adapter_config["target_modules"] = sorted(target_modules)
    elif peft_cfg["peft_type"] in ("PROMPT_TUNING", "PREFIX_TUNING"):
        adapter_config["num_virtual_tokens"] = peft_cfg["num_virtual_tokens"]
        if peft_cfg["peft_type"] == "PROMPT_TUNING":
            emb = np.asarray(adapter_params["prompt"]["embedding"], np.float32)
        else:
            # peft prefix layout: [n_virtual, L*2*Hkv*D] with per-layer
            # (key, value) pairs consecutive on the middle axis
            k = np.asarray(adapter_params["prefix"]["k"], np.float32)
            v = np.asarray(adapter_params["prefix"]["v"], np.float32)
            L, n, Hkv, D = k.shape
            kv = np.stack([k, v], axis=1)  # [L, 2, n, Hkv, D]
            emb = kv.transpose(2, 0, 1, 3, 4).reshape(n, L * 2 * Hkv * D)
        tensors["prompt_embeddings"] = torch.from_numpy(emb)
    else:
        raise ValueError(f"cannot export peft_type {peft_cfg['peft_type']!r}")

    save_file(tensors, os.path.join(directory, "adapter_model.safetensors"))
    with open(os.path.join(directory, "adapter_config.json"), "w") as f:
        json.dump(adapter_config, f, indent=2)


def is_peft_checkpoint(path: Any) -> bool:
    import os

    return isinstance(path, str) and os.path.isfile(
        os.path.join(path, "adapter_config.json")
    )


def _layer_index(name: str) -> Optional[int]:
    """First integer path segment in an HF module name ('...h.3.attn...'
    -> 3); None for layer-less modules (lm_head)."""
    for seg in name.split("."):
        if seg.isdigit():
            return int(seg)
    return None


def load_peft_adapter(path: str, cfg) -> (dict, dict):
    """Read a trained HF-peft adapter checkpoint into the stacked
    in-memory layout. Returns (normalized peft cfg, adapter params to
    merge into the trainer tree, e.g. {"lora": {...}}).

    Handles separate-projection LoRA names (q_proj/k_proj/v_proj/
    o_proj/out_proj, plus our logical export names) and FUSED attention
    (c_attn / query_key_value): a fused module's shared lora_A feeds
    q/k/v adapters whose lora_B is the corresponding column block —
    mathematically exact, since the fused delta splits by columns.
    """
    import json
    import os

    import numpy as np

    with open(os.path.join(path, "adapter_config.json")) as f:
        raw_cfg = json.load(f)
    pc = normalize_peft_config(raw_cfg)

    st = os.path.join(path, "adapter_model.safetensors")
    if os.path.exists(st):
        from safetensors.numpy import load_file

        sd = {k: np.asarray(v) for k, v in load_file(st).items()}
    else:
        import torch

        sd = {
            k: t.detach().cpu().float().numpy()
            for k, t in torch.load(
                os.path.join(path, "adapter_model.bin"), map_location="cpu",
                weights_only=True,
            ).items()
        }

    if pc["peft_type"] == "PROMPT_TUNING":
        return pc, {"prompt": {"embedding": jnp.asarray(sd["prompt_embeddings"])}}
    if pc["peft_type"] == "PREFIX_TUNING":
        emb = np.asarray(sd["prompt_embeddings"], np.float32)
        n = emb.shape[0]
        Hkv = cfg.n_kv_head or cfg.n_head
        D = cfg.head_dim or cfg.hidden_size // cfg.n_head
        L = emb.shape[1] // (2 * Hkv * D)
        kv = emb.reshape(n, L, 2, Hkv, D).transpose(1, 2, 0, 3, 4)
        return pc, {"prefix": {"k": jnp.asarray(kv[:, 0]),
                               "v": jnp.asarray(kv[:, 1])}}

    # LORA: group (module, layer) -> {lora_A, lora_B}
    per_mod: Dict[str, Dict[int, Dict[str, np.ndarray]]] = {}
    for name, w in sd.items():
        if ".lora_A." not in name and ".lora_B." not in name:
            continue
        side = "a" if ".lora_A." in name else "b"
        module = name.split(".lora_")[0].split(".")[-1]
        li = _layer_index(name)
        w = np.asarray(w, np.float32).T  # a: [in, r]; b: [r, out]
        if module in ("c_attn", "query_key_value"):
            # fused qkv: shared A; B splits into equal q/k/v column
            # blocks (gpt2-style full fusion; kv-shared bigcode c_attn
            # is NOT supported here)
            if side == "a":
                for m in ("q", "k", "v"):
                    per_mod.setdefault(m, {}).setdefault(li, {})["a"] = w
            else:
                out = w.shape[1] // 3
                for j, m in enumerate(("q", "k", "v")):
                    per_mod.setdefault(m, {}).setdefault(li, {})["b"] = (
                        w[:, j * out : (j + 1) * out]
                    )
            continue
        ours = _HF_TO_OURS.get(module)
        if ours is None and module == "lm_head":
            ours = "lm_head"
        if ours is None:
            raise ValueError(
                f"cannot map adapter module {module!r} (from {name!r}) "
                "onto the transformer layout"
            )
        per_mod.setdefault(ours, {}).setdefault(li, {})[side] = w

    lora: Dict[str, Dict[str, jnp.ndarray]] = {}
    for mod, by_layer in per_mod.items():
        if mod == "lm_head":
            ab = by_layer[None]
            lora["lm_head/kernel"] = {
                "a": jnp.asarray(ab["a"]), "b": jnp.asarray(ab["b"]),
            }
            continue
        layers = sorted(by_layer)
        if layers != list(range(cfg.n_layer)):
            raise ValueError(
                f"adapter for {mod!r} covers layers {layers}, expected "
                f"all {cfg.n_layer} (partial-layer adapters aren't "
                "representable in the stacked layout)"
            )
        lora[_OUR_PATH[mod]] = {
            "a": jnp.asarray(np.stack([by_layer[i]["a"] for i in layers])),
            "b": jnp.asarray(np.stack([by_layer[i]["b"] for i in layers])),
        }
    return pc, {"lora": lora}
