"""Parameter-efficient fine-tuning adapters: LoRA, prompt tuning and
prefix tuning, first-party and TPU-shaped.

Parity: the reference delegates to HF `peft`
(/root/reference/trlx/models/modeling_base.py:124-275 threads
peft_config through from_pretrained; /root/reference/tests/test_peft.py
is the contract — note the reference itself only exercises
{LORA, PROMPT_TUNING, PREFIX_TUNING} x causal and LORA x seq2seq, since
peft 0.3.0's seq2seq prompt/prefix variants were broken).

Adapter param layouts (all live beside "base" in the trainer's param
tree; the base stays frozen via the update mask):

  lora    {path: {"a": [L?, in, r], "b": [L?, r, out]}}  (models/lora.py)
  prompt  {"embedding": [n_virtual, E]}    soft tokens, run as real
                                           leading sequence positions
  prefix  {"k": [L, n_virtual, Hkv, D],    direct per-layer key/values,
           "v": [L, n_virtual, Hkv, D]}    realized as a pre-warmed
                                           pseudo KV cache

The model-side mechanics live in TransformerLM.__call__
(prefix_embeds / kv_prefix kwargs) and models/generation.py (cache
warm-up)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from trlx_tpu.models.lora import DEFAULT_TARGETS, init_lora_params  # noqa: F401


def normalize_peft_config(peft_config: Any) -> Optional[Dict[str, Any]]:
    """Accept an HF-peft-style dict ({"peft_type": ..., ...}) and
    normalize to our fields. Returns None for no adapter."""
    if peft_config is None:
        return None
    cfg = dict(peft_config)
    peft_type = str(cfg.get("peft_type", "LORA")).upper()
    if peft_type == "LORA":
        return {
            "peft_type": "LORA",
            "r": int(cfg.get("r", 8)),
            "alpha": float(cfg.get("lora_alpha", cfg.get("alpha", 16))),
            "targets": cfg.get("target_modules") or DEFAULT_TARGETS,
        }
    if peft_type in ("PROMPT_TUNING", "PREFIX_TUNING"):
        return {
            "peft_type": peft_type,
            "num_virtual_tokens": int(cfg.get("num_virtual_tokens", 10)),
        }
    raise ValueError(
        f"peft_type {peft_type!r} not supported "
        "(LORA | PROMPT_TUNING | PREFIX_TUNING)"
    )


def init_prompt_params(rng: jax.Array, cfg, n_virtual: int) -> Dict[str, jnp.ndarray]:
    """Soft-token embeddings ~ N(0, 0.02) ([RANDOM] init, the reference
    test's prompt_tuning_init)."""
    return {
        "embedding": jax.random.normal(
            rng, (n_virtual, cfg.hidden_size), jnp.float32
        )
        * 0.02
    }


def init_prefix_params(rng: jax.Array, cfg, n_virtual: int) -> Dict[str, jnp.ndarray]:
    """Per-layer key/value prefixes ~ N(0, 0.02), stacked over layers to
    match the scan-stacked block params."""
    n_kv = cfg.n_kv_head or cfg.n_head
    head_dim = cfg.head_dim or cfg.hidden_size // cfg.n_head
    k_rng, v_rng = jax.random.split(rng)
    shape = (cfg.n_layer, n_virtual, n_kv, head_dim)
    return {
        "k": jax.random.normal(k_rng, shape, jnp.float32) * 0.02,
        "v": jax.random.normal(v_rng, shape, jnp.float32) * 0.02,
    }


ADAPTER_KEYS = ("lora", "prompt", "prefix")


def adapter_call_kwargs(params: Dict) -> Dict[str, Any]:
    """kwargs for TransformerLM.__call__ from a wrapper param tree —
    threads prompt/prefix adapters into the forward (LoRA merges into
    the base weights instead, see wrappers._effective_base)."""
    kw = {}
    if "prompt" in params:
        kw["prefix_embeds"] = params["prompt"]["embedding"]
    if "prefix" in params:
        kw["kv_prefix"] = params["prefix"]
    return kw
