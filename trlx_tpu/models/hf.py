"""HuggingFace checkpoint interop: config mapping + weight conversion.

Parity: /root/reference/trlx/models/modeling_base.py:124-326
(from_pretrained with sharded-index merging) — here torch state dicts are
converted into the stacked-layer functional param tree of
trlx_tpu.models.transformer, and back (HF export for deploy parity,
reference accelerate_ppo_trainer.py:526-553).

Supported model families: gpt2, gptj, gpt_neo, gpt_neox, gpt_bigcode,
llama, opt, bloom — the reference's full decoder dispatch table
(modeling_ppo.py:1598-1637). Each family is a declarative layout
description, not a separate model class.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.models.transformer import TransformerConfig, TransformerLM
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


# ---------------------------------------------------------------------------
# config mapping
# ---------------------------------------------------------------------------


def _activation_name(hf_name: str) -> str:
    """HF activation_function -> TransformerConfig.activation."""
    table = {
        "gelu_new": "gelu_new",
        "gelu_pytorch_tanh": "gelu_new",
        "gelu_fast": "gelu_new",
        "gelu": "gelu",
        "relu": "relu",
        "silu": "silu",
        "swish": "silu",
    }
    if hf_name not in table:
        raise ValueError(f"unsupported activation_function {hf_name!r}")
    return table[hf_name]


def config_from_hf(hf_config: Any, dtype=None, param_dtype=None) -> TransformerConfig:
    """Translate a transformers PretrainedConfig into a TransformerConfig."""
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    param_dtype = param_dtype or jnp.float32
    mt = hf_config.model_type

    if mt == "gpt2":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            n_layer=hf_config.n_layer,
            n_head=hf_config.n_head,
            n_positions=hf_config.n_positions,
            intermediate_size=hf_config.n_inner or 4 * hf_config.n_embd,
            pos_embed="learned",
            activation="gelu_new",
            layer_norm_epsilon=hf_config.layer_norm_epsilon,
            tie_word_embeddings=True,
            dtype=dtype,
            param_dtype=param_dtype,
        )
    if mt == "gptj":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            n_layer=hf_config.n_layer,
            n_head=hf_config.n_head,
            n_positions=hf_config.n_positions,
            intermediate_size=hf_config.n_inner or 4 * hf_config.n_embd,
            pos_embed="rotary",
            rotary_style="gptj",
            rotary_dim=hf_config.rotary_dim,
            activation="gelu_new",
            layer_norm_epsilon=hf_config.layer_norm_epsilon,
            parallel_residual=True,
            use_attn_bias=False,
            use_mlp_bias=True,
            tie_word_embeddings=False,
            dtype=dtype,
            param_dtype=param_dtype,
        )
    if mt == "gpt_neox":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            n_layer=hf_config.num_hidden_layers,
            n_head=hf_config.num_attention_heads,
            n_positions=hf_config.max_position_embeddings,
            intermediate_size=hf_config.intermediate_size,
            pos_embed="rotary",
            rotary_style="neox",
            rotary_dim=int(
                (hf_config.hidden_size // hf_config.num_attention_heads)
                * hf_config.rotary_pct
            ),
            rope_theta=getattr(hf_config, "rotary_emb_base", 10000.0),
            activation="gelu",
            layer_norm_epsilon=hf_config.layer_norm_eps,
            parallel_residual=getattr(hf_config, "use_parallel_residual", True),
            use_attn_bias=True,
            use_mlp_bias=True,
            tie_word_embeddings=False,
            dtype=dtype,
            param_dtype=param_dtype,
        )
    if mt == "llama":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            n_layer=hf_config.num_hidden_layers,
            n_head=hf_config.num_attention_heads,
            n_kv_head=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            n_positions=hf_config.max_position_embeddings,
            intermediate_size=hf_config.intermediate_size,
            pos_embed="rotary",
            rotary_style="neox",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            norm="rmsnorm",
            layer_norm_epsilon=hf_config.rms_norm_eps,
            activation="silu",
            mlp_gated=True,
            use_attn_bias=False,
            use_mlp_bias=False,
            use_norm_bias=False,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
            dtype=dtype,
            param_dtype=param_dtype,
        )
    if mt == "opt":
        # ref: OPTModelBranch (modeling_ppo.py:689-813). HF OPT computes
        # positions from the attention-mask cumsum (as we always do) and
        # offsets the learned table by 2 pad rows.
        if not getattr(hf_config, "do_layer_norm_before", True):
            raise ValueError("OPT variants with do_layer_norm_before=False (350m) unsupported")
        if getattr(hf_config, "word_embed_proj_dim", hf_config.hidden_size) != hf_config.hidden_size:
            raise ValueError("OPT word_embed_proj_dim != hidden_size unsupported")
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            n_layer=hf_config.num_hidden_layers,
            n_head=hf_config.num_attention_heads,
            n_positions=hf_config.max_position_embeddings,
            intermediate_size=hf_config.ffn_dim,
            pos_embed="learned",
            pos_offset=2,
            activation=_activation_name(hf_config.activation_function),
            layer_norm_epsilon=1e-5,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            dtype=dtype,
            param_dtype=param_dtype,
        )
    if mt == "bloom":
        # ref: BloomModelBranch (modeling_ppo.py:816-929). ALiBi position
        # bias, LayerNorm directly after word embeddings, per-head fused QKV.
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            n_layer=hf_config.n_layer,
            n_head=hf_config.n_head,
            n_positions=getattr(hf_config, "seq_length", 2048),
            pos_embed="alibi",
            embed_layernorm=True,
            activation="gelu_new",
            layer_norm_epsilon=hf_config.layer_norm_epsilon,
            tie_word_embeddings=True,
            dtype=dtype,
            param_dtype=param_dtype,
        )
    if mt == "gpt_bigcode":
        # ref: GPTBigCodeModelBranch (modeling_ppo.py:1079-1222).
        # Multi-query attention: a single shared KV head.
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            n_layer=hf_config.n_layer,
            n_head=hf_config.n_head,
            n_kv_head=1 if getattr(hf_config, "multi_query", True) else hf_config.n_head,
            n_positions=hf_config.n_positions,
            intermediate_size=hf_config.n_inner or 4 * hf_config.n_embd,
            pos_embed="learned",
            activation=_activation_name(hf_config.activation_function),
            layer_norm_epsilon=hf_config.layer_norm_epsilon,
            tie_word_embeddings=True,
            dtype=dtype,
            param_dtype=param_dtype,
        )
    if mt == "gpt_neo":
        # ref: GPTModelBranch covers gpt_neo (modeling_ppo.py:1598-1637).
        # Quirks: queries are NOT scaled by 1/sqrt(D); alternate layers use
        # a sliding local-attention window; q/k/v projections have no bias.
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            n_layer=hf_config.num_layers,
            n_head=hf_config.num_heads,
            n_positions=hf_config.max_position_embeddings,
            intermediate_size=hf_config.intermediate_size
            or 4 * hf_config.hidden_size,
            pos_embed="learned",
            activation=_activation_name(hf_config.activation_function),
            layer_norm_epsilon=hf_config.layer_norm_epsilon,
            attn_scale=1.0,
            local_window=hf_config.window_size,
            attn_layers=tuple(hf_config.attention_layers),
            use_attn_bias=False,
            use_attn_out_bias=True,
            tie_word_embeddings=True,
            dtype=dtype,
            param_dtype=param_dtype,
        )
    raise ValueError(
        f"unsupported model_type {mt!r} (supported: gpt2, gptj, gpt_neo, "
        "gpt_neox, gpt_bigcode, llama, opt, bloom)"
    )


def seq2seq_config_from_hf(hf_config: Any, dtype=None, param_dtype=None):
    """Translate an HF T5Config into a Seq2SeqConfig."""
    import jax.numpy as jnp

    from trlx_tpu.models.seq2seq import Seq2SeqConfig

    if hf_config.model_type not in ("t5", "mt5"):
        raise ValueError(f"unsupported seq2seq model_type {hf_config.model_type!r}")
    ff = getattr(hf_config, "feed_forward_proj", "relu")
    return Seq2SeqConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.d_model,
        n_layer=hf_config.num_layers,
        n_decoder_layer=getattr(hf_config, "num_decoder_layers", hf_config.num_layers),
        n_head=hf_config.num_heads,
        d_kv=hf_config.d_kv,
        d_ff=hf_config.d_ff,
        relative_attention_num_buckets=hf_config.relative_attention_num_buckets,
        relative_attention_max_distance=getattr(
            hf_config, "relative_attention_max_distance", 128
        ),
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        activation="gated-gelu" if "gated" in ff else "relu",
        tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", True),
        decoder_start_token_id=hf_config.decoder_start_token_id or 0,
        dtype=dtype or jnp.bfloat16,
        param_dtype=param_dtype or jnp.float32,
    )


def t5_params_from_state_dict(sd: Dict[str, Any], cfg) -> Dict:
    """Convert an HF T5 torch state_dict into the T5LM param tree."""
    H, Dk, D = cfg.n_head, cfg.d_kv, cfg.d_model

    def attn(prefix: str) -> Dict[str, Any]:
        return {
            "q": {"kernel": _np(sd[prefix + ".q.weight"]).T.reshape(D, H, Dk)},
            "k": {"kernel": _np(sd[prefix + ".k.weight"]).T.reshape(D, H, Dk)},
            "v": {"kernel": _np(sd[prefix + ".v.weight"]).T.reshape(D, H, Dk)},
            "o": {"kernel": _np(sd[prefix + ".o.weight"]).T.reshape(H, Dk, D)},
        }

    def mlp(prefix: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "fc_out": {"kernel": _np(sd[prefix + ".wo.weight"]).T}
        }
        if prefix + ".wi.weight" in sd:
            out["fc_in"] = {"kernel": _np(sd[prefix + ".wi.weight"]).T}
        else:  # gated (v1.1): wi_0 activated, wi_1 linear
            out["fc_in"] = {"kernel": _np(sd[prefix + ".wi_0.weight"]).T}
            out["fc_gate"] = {"kernel": _np(sd[prefix + ".wi_1.weight"]).T}
        return out

    def stack(side: str, n: int, is_decoder: bool) -> Dict[str, Any]:
        layers = []
        for i in range(n):
            b = f"{side}.block.{i}.layer"
            layer = {
                "ln_1": {"scale": _np(sd[f"{b}.0.layer_norm.weight"])},
                "self_attn": attn(f"{b}.0.SelfAttention"),
            }
            if is_decoder:
                layer["ln_cross"] = {"scale": _np(sd[f"{b}.1.layer_norm.weight"])}
                layer["cross_attn"] = attn(f"{b}.1.EncDecAttention")
                ff = 2
            else:
                ff = 1
            layer["ln_2"] = {"scale": _np(sd[f"{b}.{ff}.layer_norm.weight"])}
            layer["mlp"] = mlp(f"{b}.{ff}.DenseReluDense")
            layers.append(layer)
        return _stack(layers)

    params = {
        "shared": {"wte": _np(sd["shared.weight"])},
        "encoder": {
            "blocks": stack("encoder", cfg.n_layer, False),
            "ln_f": {"scale": _np(sd["encoder.final_layer_norm.weight"])},
            "rel_bias": _np(
                sd["encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
            ),
        },
        "decoder": {
            "blocks": stack("decoder", cfg.n_decoder_layer, True),
            "ln_f": {"scale": _np(sd["decoder.final_layer_norm.weight"])},
            "rel_bias": _np(
                sd["decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
            ),
        },
    }
    if not cfg.tie_word_embeddings and "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": _np(sd["lm_head.weight"]).T}
    return params


def t5_state_dict_from_params(params: Dict, cfg) -> Dict[str, np.ndarray]:
    """Inverse of t5_params_from_state_dict: T5LM param tree -> HF torch
    state_dict names (deploy artifact for seq2seq policies)."""
    H, Dk, D = cfg.n_head, cfg.d_kv, cfg.d_model
    out: Dict[str, np.ndarray] = {}

    def A(x):
        return np.asarray(x, dtype=np.float32)

    def attn_out(prefix: str, blk: Dict) -> None:
        out[prefix + ".q.weight"] = A(blk["q"]["kernel"]).reshape(D, H * Dk).T
        out[prefix + ".k.weight"] = A(blk["k"]["kernel"]).reshape(D, H * Dk).T
        out[prefix + ".v.weight"] = A(blk["v"]["kernel"]).reshape(D, H * Dk).T
        out[prefix + ".o.weight"] = A(blk["o"]["kernel"]).reshape(H * Dk, D).T

    def mlp_out(prefix: str, blk: Dict) -> None:
        out[prefix + ".wo.weight"] = A(blk["fc_out"]["kernel"]).T
        if "fc_gate" in blk:  # gated (v1.1)
            out[prefix + ".wi_0.weight"] = A(blk["fc_in"]["kernel"]).T
            out[prefix + ".wi_1.weight"] = A(blk["fc_gate"]["kernel"]).T
        else:
            out[prefix + ".wi.weight"] = A(blk["fc_in"]["kernel"]).T

    def stack_out(side: str, tree: Dict, n: int, is_decoder: bool) -> None:
        for i in range(n):
            b = f"{side}.block.{i}.layer"
            blk = {k: A_tree(v, i) for k, v in tree["blocks"].items()}
            out[f"{b}.0.layer_norm.weight"] = blk["ln_1"]["scale"]
            attn_out(f"{b}.0.SelfAttention", blk["self_attn"])
            if is_decoder:
                out[f"{b}.1.layer_norm.weight"] = blk["ln_cross"]["scale"]
                attn_out(f"{b}.1.EncDecAttention", blk["cross_attn"])
                ff = 2
            else:
                ff = 1
            out[f"{b}.{ff}.layer_norm.weight"] = blk["ln_2"]["scale"]
            mlp_out(f"{b}.{ff}.DenseReluDense", blk["mlp"])
        out[f"{side}.final_layer_norm.weight"] = A(tree["ln_f"]["scale"])
        # HF keeps the relative bias on block 0 only
        out[f"{side}.block.0.layer.0.SelfAttention.relative_attention_bias.weight"] = A(
            tree["rel_bias"]
        )

    shared = A(params["shared"]["wte"])
    out["shared.weight"] = shared
    out["encoder.embed_tokens.weight"] = shared
    out["decoder.embed_tokens.weight"] = shared
    stack_out("encoder", params["encoder"], cfg.n_layer, False)
    stack_out("decoder", params["decoder"], cfg.n_decoder_layer, True)
    if "lm_head" in params:
        out["lm_head.weight"] = A(params["lm_head"]["kernel"]).T
    else:  # tied: HF still carries the (shared) lm_head tensor
        out["lm_head.weight"] = shared
    return out


def load_pretrained_seq2seq(path: str, dtype=None, param_dtype=None):
    """Load an HF-layout T5 checkpoint directory -> (T5LM, params)."""
    import transformers

    from trlx_tpu.models.seq2seq import T5LM

    hf_config = transformers.AutoConfig.from_pretrained(path)
    cfg = seq2seq_config_from_hf(hf_config, dtype=dtype, param_dtype=param_dtype)
    sd = _read_state_dict(path)
    params = t5_params_from_state_dict(sd, cfg)
    return T5LM(cfg), params, hf_config.model_type


# ---------------------------------------------------------------------------
# weight conversion: torch state_dict -> stacked functional param tree
# ---------------------------------------------------------------------------


def _np(t) -> np.ndarray:
    # torch tensor or numpy array -> float32 numpy (bf16-safe via float())
    if hasattr(t, "detach"):
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t, dtype=np.float32)


def _stack(layers: List[Dict[str, Any]]) -> Dict[str, Any]:
    """[{'a': arr}, ...] per layer -> {'a': arr[L, ...]} stacked."""
    import jax

    return jax.tree_util.tree_map(lambda *xs: np.stack(xs, axis=0), *layers)


def params_from_state_dict(sd: Dict[str, Any], cfg: TransformerConfig, model_type: str) -> Dict:
    """Convert an HF torch state_dict to the functional param tree."""
    H, D, E = cfg.n_head, cfg.head_dim, cfg.hidden_size
    Hkv = cfg.n_kv_head

    def qkv_from_fused(w, b, order: str = "qkv"):
        """Fused c_attn [E, 3E] (+bias) -> q/k/v dicts with [E,H,D] kernels."""
        ws = np.split(w, 3, axis=-1)
        out = {}
        for name, wi in zip(order, ws):
            out[name] = {"kernel": wi.reshape(E, H, D)}
        if b is not None:
            bs = np.split(b, 3, axis=-1)
            for name, bi in zip(order, bs):
                out[name]["bias"] = bi.reshape(H, D)
        return out

    if model_type == "gpt2":
        # HF Conv1D stores [in, out] — same as our kernels, no transpose.
        pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        layers = []
        for i in range(cfg.n_layer):
            b = f"{pfx}h.{i}."
            attn = qkv_from_fused(_np(sd[b + "attn.c_attn.weight"]), _np(sd[b + "attn.c_attn.bias"]))
            attn["o"] = {
                "kernel": _np(sd[b + "attn.c_proj.weight"]).reshape(H, D, E),
                "bias": _np(sd[b + "attn.c_proj.bias"]),
            }
            layers.append(
                {
                    "ln_1": {"scale": _np(sd[b + "ln_1.weight"]), "bias": _np(sd[b + "ln_1.bias"])},
                    "attn": attn,
                    "ln_2": {"scale": _np(sd[b + "ln_2.weight"]), "bias": _np(sd[b + "ln_2.bias"])},
                    "mlp": {
                        "fc_in": {"kernel": _np(sd[b + "mlp.c_fc.weight"]), "bias": _np(sd[b + "mlp.c_fc.bias"])},
                        "fc_out": {"kernel": _np(sd[b + "mlp.c_proj.weight"]), "bias": _np(sd[b + "mlp.c_proj.bias"])},
                    },
                }
            )
        return {
            "embed": {"wte": _np(sd[pfx + "wte.weight"]), "wpe": _np(sd[pfx + "wpe.weight"])},
            "blocks": _stack(layers),
            "ln_f": {"scale": _np(sd[pfx + "ln_f.weight"]), "bias": _np(sd[pfx + "ln_f.bias"])},
        }

    if model_type == "gptj":
        pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        layers = []
        for i in range(cfg.n_layer):
            b = f"{pfx}h.{i}."
            attn = {}
            for ours, theirs in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj")):
                attn[ours] = {"kernel": _np(sd[f"{b}attn.{theirs}.weight"]).T.reshape(E, H, D)}
            attn["o"] = {"kernel": _np(sd[b + "attn.out_proj.weight"]).T.reshape(H, D, E)}
            layers.append(
                {
                    "ln_1": {"scale": _np(sd[b + "ln_1.weight"]), "bias": _np(sd[b + "ln_1.bias"])},
                    "attn": attn,
                    "mlp": {
                        "fc_in": {"kernel": _np(sd[b + "mlp.fc_in.weight"]).T, "bias": _np(sd[b + "mlp.fc_in.bias"])},
                        "fc_out": {"kernel": _np(sd[b + "mlp.fc_out.weight"]).T, "bias": _np(sd[b + "mlp.fc_out.bias"])},
                    },
                }
            )
        params = {
            "embed": {"wte": _np(sd[pfx + "wte.weight"])},
            "blocks": _stack(layers),
            "ln_f": {"scale": _np(sd[pfx + "ln_f.weight"]), "bias": _np(sd[pfx + "ln_f.bias"])},
            "lm_head": {"kernel": _np(sd["lm_head.weight"]).T},
        }
        return params

    if model_type == "gpt_neox":
        pfx = "gpt_neox." if any(k.startswith("gpt_neox.") for k in sd) else ""
        layers = []
        for i in range(cfg.n_layer):
            b = f"{pfx}layers.{i}."
            # fused qkv [3E, E], interleaved per head: [H, 3, D, E]
            w = _np(sd[b + "attention.query_key_value.weight"]).reshape(H, 3, D, E)
            bias = _np(sd[b + "attention.query_key_value.bias"]).reshape(H, 3, D)
            attn = {
                name: {
                    "kernel": np.moveaxis(w[:, j], -1, 0).reshape(E, H, D),
                    "bias": bias[:, j],
                }
                for j, name in enumerate("qkv")
            }
            attn["o"] = {
                "kernel": _np(sd[b + "attention.dense.weight"]).T.reshape(H, D, E),
                "bias": _np(sd[b + "attention.dense.bias"]),
            }
            layers.append(
                {
                    "ln_1": {
                        "scale": _np(sd[b + "input_layernorm.weight"]),
                        "bias": _np(sd[b + "input_layernorm.bias"]),
                    },
                    "attn": attn,
                    "ln_2": {
                        "scale": _np(sd[b + "post_attention_layernorm.weight"]),
                        "bias": _np(sd[b + "post_attention_layernorm.bias"]),
                    },
                    "mlp": {
                        "fc_in": {
                            "kernel": _np(sd[b + "mlp.dense_h_to_4h.weight"]).T,
                            "bias": _np(sd[b + "mlp.dense_h_to_4h.bias"]),
                        },
                        "fc_out": {
                            "kernel": _np(sd[b + "mlp.dense_4h_to_h.weight"]).T,
                            "bias": _np(sd[b + "mlp.dense_4h_to_h.bias"]),
                        },
                    },
                }
            )
        stacked = _stack(layers)
        if not getattr(cfg, "parallel_residual", True):
            pass  # ln_2 still present in sequential layout
        return {
            "embed": {"wte": _np(sd[pfx + "embed_in.weight"])},
            "blocks": stacked,
            "ln_f": {
                "scale": _np(sd[pfx + "final_layer_norm.weight"]),
                "bias": _np(sd[pfx + "final_layer_norm.bias"]),
            },
            "lm_head": {"kernel": _np(sd["embed_out.weight"]).T},
        }

    if model_type == "llama":
        pfx = "model." if any(k.startswith("model.") for k in sd) else ""
        layers = []
        for i in range(cfg.n_layer):
            b = f"{pfx}layers.{i}."
            attn = {
                "q": {"kernel": _np(sd[b + "self_attn.q_proj.weight"]).T.reshape(E, H, D)},
                "k": {"kernel": _np(sd[b + "self_attn.k_proj.weight"]).T.reshape(E, Hkv, D)},
                "v": {"kernel": _np(sd[b + "self_attn.v_proj.weight"]).T.reshape(E, Hkv, D)},
                "o": {"kernel": _np(sd[b + "self_attn.o_proj.weight"]).T.reshape(H, D, E)},
            }
            layers.append(
                {
                    "ln_1": {"scale": _np(sd[b + "input_layernorm.weight"])},
                    "attn": attn,
                    "ln_2": {"scale": _np(sd[b + "post_attention_layernorm.weight"])},
                    "mlp": {
                        # HF: gate_proj activated, up_proj linear; ours:
                        # fc_in activated, fc_gate linear multiplier
                        "fc_in": {"kernel": _np(sd[b + "mlp.gate_proj.weight"]).T},
                        "fc_gate": {"kernel": _np(sd[b + "mlp.up_proj.weight"]).T},
                        "fc_out": {"kernel": _np(sd[b + "mlp.down_proj.weight"]).T},
                    },
                }
            )
        params = {
            "embed": {"wte": _np(sd[pfx + "embed_tokens.weight"])},
            "blocks": _stack(layers),
            "ln_f": {"scale": _np(sd[pfx + "norm.weight"])},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"kernel": _np(sd["lm_head.weight"]).T}
        return params

    if model_type == "opt":
        pfx = (
            "model.decoder."
            if any(k.startswith("model.decoder.") for k in sd)
            else "decoder."
            if any(k.startswith("decoder.") for k in sd)
            else ""
        )
        layers = []
        for i in range(cfg.n_layer):
            b = f"{pfx}layers.{i}."
            attn = {}
            for ours, theirs in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj")):
                attn[ours] = {
                    "kernel": _np(sd[f"{b}self_attn.{theirs}.weight"]).T.reshape(E, H, D),
                    "bias": _np(sd[f"{b}self_attn.{theirs}.bias"]).reshape(H, D),
                }
            attn["o"] = {
                "kernel": _np(sd[b + "self_attn.out_proj.weight"]).T.reshape(H, D, E),
                "bias": _np(sd[b + "self_attn.out_proj.bias"]),
            }
            layers.append(
                {
                    "ln_1": {
                        "scale": _np(sd[b + "self_attn_layer_norm.weight"]),
                        "bias": _np(sd[b + "self_attn_layer_norm.bias"]),
                    },
                    "attn": attn,
                    "ln_2": {
                        "scale": _np(sd[b + "final_layer_norm.weight"]),
                        "bias": _np(sd[b + "final_layer_norm.bias"]),
                    },
                    "mlp": {
                        "fc_in": {"kernel": _np(sd[b + "fc1.weight"]).T, "bias": _np(sd[b + "fc1.bias"])},
                        "fc_out": {"kernel": _np(sd[b + "fc2.weight"]).T, "bias": _np(sd[b + "fc2.bias"])},
                    },
                }
            )
        params = {
            # wpe keeps OPT's full table (2 leading pad rows; cfg.pos_offset=2)
            "embed": {
                "wte": _np(sd[pfx + "embed_tokens.weight"]),
                "wpe": _np(sd[pfx + "embed_positions.weight"]),
            },
            "blocks": _stack(layers),
            "ln_f": {
                "scale": _np(sd[pfx + "final_layer_norm.weight"]),
                "bias": _np(sd[pfx + "final_layer_norm.bias"]),
            },
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"kernel": _np(sd["lm_head.weight"]).T}
        return params

    if model_type == "bloom":
        pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        layers = []
        for i in range(cfg.n_layer):
            b = f"{pfx}h.{i}."
            # fused qkv [3E, E], per-head interleave: rows view as [H, 3, D]
            w = _np(sd[b + "self_attention.query_key_value.weight"]).reshape(H, 3, D, E)
            bias = _np(sd[b + "self_attention.query_key_value.bias"]).reshape(H, 3, D)
            attn = {
                name: {
                    "kernel": np.moveaxis(w[:, j], -1, 0).reshape(E, H, D),
                    "bias": bias[:, j],
                }
                for j, name in enumerate("qkv")
            }
            attn["o"] = {
                "kernel": _np(sd[b + "self_attention.dense.weight"]).T.reshape(H, D, E),
                "bias": _np(sd[b + "self_attention.dense.bias"]),
            }
            layers.append(
                {
                    "ln_1": {
                        "scale": _np(sd[b + "input_layernorm.weight"]),
                        "bias": _np(sd[b + "input_layernorm.bias"]),
                    },
                    "attn": attn,
                    "ln_2": {
                        "scale": _np(sd[b + "post_attention_layernorm.weight"]),
                        "bias": _np(sd[b + "post_attention_layernorm.bias"]),
                    },
                    "mlp": {
                        "fc_in": {
                            "kernel": _np(sd[b + "mlp.dense_h_to_4h.weight"]).T,
                            "bias": _np(sd[b + "mlp.dense_h_to_4h.bias"]),
                        },
                        "fc_out": {
                            "kernel": _np(sd[b + "mlp.dense_4h_to_h.weight"]).T,
                            "bias": _np(sd[b + "mlp.dense_4h_to_h.bias"]),
                        },
                    },
                }
            )
        return {
            "embed": {"wte": _np(sd[pfx + "word_embeddings.weight"])},
            "ln_embed": {
                "scale": _np(sd[pfx + "word_embeddings_layernorm.weight"]),
                "bias": _np(sd[pfx + "word_embeddings_layernorm.bias"]),
            },
            "blocks": _stack(layers),
            "ln_f": {
                "scale": _np(sd[pfx + "ln_f.weight"]),
                "bias": _np(sd[pfx + "ln_f.bias"]),
            },
        }

    if model_type == "gpt_bigcode":
        pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        kv_dim = Hkv * D
        layers = []
        for i in range(cfg.n_layer):
            b = f"{pfx}h.{i}."
            # c_attn is a Linear [E + 2*kv_dim, E]: q rows then shared k, v
            w = _np(sd[b + "attn.c_attn.weight"]).T  # [E, E + 2*kv_dim]
            bias = _np(sd[b + "attn.c_attn.bias"])
            attn = {
                "q": {
                    "kernel": w[:, :E].reshape(E, H, D),
                    "bias": bias[:E].reshape(H, D),
                },
                "k": {
                    "kernel": w[:, E : E + kv_dim].reshape(E, Hkv, D),
                    "bias": bias[E : E + kv_dim].reshape(Hkv, D),
                },
                "v": {
                    "kernel": w[:, E + kv_dim :].reshape(E, Hkv, D),
                    "bias": bias[E + kv_dim :].reshape(Hkv, D),
                },
                "o": {
                    "kernel": _np(sd[b + "attn.c_proj.weight"]).T.reshape(H, D, E),
                    "bias": _np(sd[b + "attn.c_proj.bias"]),
                },
            }
            layers.append(
                {
                    "ln_1": {"scale": _np(sd[b + "ln_1.weight"]), "bias": _np(sd[b + "ln_1.bias"])},
                    "attn": attn,
                    "ln_2": {"scale": _np(sd[b + "ln_2.weight"]), "bias": _np(sd[b + "ln_2.bias"])},
                    "mlp": {
                        "fc_in": {"kernel": _np(sd[b + "mlp.c_fc.weight"]).T, "bias": _np(sd[b + "mlp.c_fc.bias"])},
                        "fc_out": {"kernel": _np(sd[b + "mlp.c_proj.weight"]).T, "bias": _np(sd[b + "mlp.c_proj.bias"])},
                    },
                }
            )
        return {
            "embed": {"wte": _np(sd[pfx + "wte.weight"]), "wpe": _np(sd[pfx + "wpe.weight"])},
            "blocks": _stack(layers),
            "ln_f": {"scale": _np(sd[pfx + "ln_f.weight"]), "bias": _np(sd[pfx + "ln_f.bias"])},
        }

    if model_type == "gpt_neo":
        pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        layers = []
        for i in range(cfg.n_layer):
            b = f"{pfx}h.{i}."
            attn = {
                ours: {"kernel": _np(sd[f"{b}attn.attention.{theirs}.weight"]).T.reshape(E, H, D)}
                for ours, theirs in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj"))
            }
            attn["o"] = {
                "kernel": _np(sd[b + "attn.attention.out_proj.weight"]).T.reshape(H, D, E),
                "bias": _np(sd[b + "attn.attention.out_proj.bias"]),
            }
            layers.append(
                {
                    "ln_1": {"scale": _np(sd[b + "ln_1.weight"]), "bias": _np(sd[b + "ln_1.bias"])},
                    "attn": attn,
                    "ln_2": {"scale": _np(sd[b + "ln_2.weight"]), "bias": _np(sd[b + "ln_2.bias"])},
                    "mlp": {
                        "fc_in": {"kernel": _np(sd[b + "mlp.c_fc.weight"]).T, "bias": _np(sd[b + "mlp.c_fc.bias"])},
                        "fc_out": {"kernel": _np(sd[b + "mlp.c_proj.weight"]).T, "bias": _np(sd[b + "mlp.c_proj.bias"])},
                    },
                }
            )
        return {
            "embed": {"wte": _np(sd[pfx + "wte.weight"]), "wpe": _np(sd[pfx + "wpe.weight"])},
            "blocks": _stack(layers),
            "ln_f": {"scale": _np(sd[pfx + "ln_f.weight"]), "bias": _np(sd[pfx + "ln_f.bias"])},
        }

    raise ValueError(f"unsupported model_type {model_type!r}")


# ---------------------------------------------------------------------------
# checkpoint IO
# ---------------------------------------------------------------------------


def _read_state_dict(path: str) -> Dict[str, Any]:
    """Read torch-format weights from an HF-layout directory, merging
    sharded checkpoints via the index file when present (parity:
    reference modeling_base.py:277-315)."""
    single_bins = ["pytorch_model.bin", "model.safetensors"]
    index_files = ["pytorch_model.bin.index.json", "model.safetensors.index.json"]

    def _load_file(fp: str) -> Dict[str, Any]:
        if fp.endswith(".safetensors"):
            from safetensors import safe_open

            out = {}
            with safe_open(fp, framework="np") as f:
                for key in f.keys():
                    out[key] = f.get_tensor(key)
            return out
        import torch

        return torch.load(fp, map_location="cpu", weights_only=True)

    for idx_name in index_files:
        idx_fp = os.path.join(path, idx_name)
        if os.path.exists(idx_fp):
            with open(idx_fp) as f:
                index = json.load(f)
            sd: Dict[str, Any] = {}
            for shard in sorted(set(index["weight_map"].values())):
                sd.update(_load_file(os.path.join(path, shard)))
            return sd
    for bin_name in single_bins:
        fp = os.path.join(path, bin_name)
        if os.path.exists(fp):
            return _load_file(fp)
    raise FileNotFoundError(f"no model weights found under {path}")


def load_pretrained(
    path: str, dtype=None, param_dtype=None
) -> Tuple[TransformerLM, Dict, str]:
    """Load an HF-layout local checkpoint directory.

    Returns (model, params, model_type). `params` leaves are numpy arrays
    (host memory) — the trainer device_puts them with shardings.
    """
    import transformers

    hf_config = transformers.AutoConfig.from_pretrained(path)
    cfg = config_from_hf(hf_config, dtype=dtype, param_dtype=param_dtype)
    sd = _read_state_dict(path)
    params = params_from_state_dict(sd, cfg, hf_config.model_type)
    return TransformerLM(cfg), params, hf_config.model_type


def save_pretrained_hf(
    params: Dict, cfg: TransformerConfig, model_type: str, hf_config: Any, path: str
) -> None:
    """Export the param tree as a plain HF torch checkpoint (deploy
    artifact parity: reference accelerate_base_trainer save_pretrained)."""
    import torch

    os.makedirs(path, exist_ok=True)
    if model_type in ("t5", "mt5"):
        sd = t5_state_dict_from_params(params, cfg)
    else:
        sd = state_dict_from_params(params, cfg, model_type)
    torch.save({k: torch.from_numpy(np.asarray(v)) for k, v in sd.items()},
               os.path.join(path, "pytorch_model.bin"))
    hf_config.save_pretrained(path)


def state_dict_from_params(params: Dict, cfg: TransformerConfig, model_type: str) -> Dict[str, np.ndarray]:
    """Inverse of params_from_state_dict (all supported causal families)."""
    H, D, E = cfg.n_head, cfg.head_dim, cfg.hidden_size
    Hkv = cfg.n_kv_head
    out: Dict[str, np.ndarray] = {}

    def A(x):
        return np.asarray(x, dtype=np.float32)

    blocks = params["blocks"]
    if model_type == "gpt2":
        out["transformer.wte.weight"] = A(params["embed"]["wte"])
        out["transformer.wpe.weight"] = A(params["embed"]["wpe"])
        for i in range(cfg.n_layer):
            b = f"transformer.h.{i}."
            blk = {k: A_tree(v, i) for k, v in blocks.items()}
            out[b + "ln_1.weight"] = blk["ln_1"]["scale"]
            out[b + "ln_1.bias"] = blk["ln_1"]["bias"]
            qkv_w = np.concatenate(
                [blk["attn"][n]["kernel"].reshape(E, E) for n in "qkv"], axis=-1
            )
            qkv_b = np.concatenate(
                [blk["attn"][n]["bias"].reshape(E) for n in "qkv"], axis=-1
            )
            out[b + "attn.c_attn.weight"] = qkv_w
            out[b + "attn.c_attn.bias"] = qkv_b
            out[b + "attn.c_proj.weight"] = blk["attn"]["o"]["kernel"].reshape(E, E)
            out[b + "attn.c_proj.bias"] = blk["attn"]["o"]["bias"]
            out[b + "ln_2.weight"] = blk["ln_2"]["scale"]
            out[b + "ln_2.bias"] = blk["ln_2"]["bias"]
            out[b + "mlp.c_fc.weight"] = blk["mlp"]["fc_in"]["kernel"]
            out[b + "mlp.c_fc.bias"] = blk["mlp"]["fc_in"]["bias"]
            out[b + "mlp.c_proj.weight"] = blk["mlp"]["fc_out"]["kernel"]
            out[b + "mlp.c_proj.bias"] = blk["mlp"]["fc_out"]["bias"]
        out["transformer.ln_f.weight"] = A(params["ln_f"]["scale"])
        out["transformer.ln_f.bias"] = A(params["ln_f"]["bias"])
        out["lm_head.weight"] = out["transformer.wte.weight"]
        return out

    if model_type == "llama":
        out["model.embed_tokens.weight"] = A(params["embed"]["wte"])
        for i in range(cfg.n_layer):
            b = f"model.layers.{i}."
            blk = {k: A_tree(v, i) for k, v in blocks.items()}
            out[b + "input_layernorm.weight"] = blk["ln_1"]["scale"]
            out[b + "self_attn.q_proj.weight"] = blk["attn"]["q"]["kernel"].reshape(E, H * D).T
            out[b + "self_attn.k_proj.weight"] = blk["attn"]["k"]["kernel"].reshape(E, Hkv * D).T
            out[b + "self_attn.v_proj.weight"] = blk["attn"]["v"]["kernel"].reshape(E, Hkv * D).T
            out[b + "self_attn.o_proj.weight"] = blk["attn"]["o"]["kernel"].reshape(H * D, E).T
            out[b + "post_attention_layernorm.weight"] = blk["ln_2"]["scale"]
            out[b + "mlp.gate_proj.weight"] = blk["mlp"]["fc_in"]["kernel"].T
            out[b + "mlp.up_proj.weight"] = blk["mlp"]["fc_gate"]["kernel"].T
            out[b + "mlp.down_proj.weight"] = blk["mlp"]["fc_out"]["kernel"].T
        out["model.norm.weight"] = A(params["ln_f"]["scale"])
        if "lm_head" in params:
            out["lm_head.weight"] = A(params["lm_head"]["kernel"]).T
        else:
            out["lm_head.weight"] = out["model.embed_tokens.weight"]
        return out

    if model_type == "gptj":
        out["transformer.wte.weight"] = A(params["embed"]["wte"])
        for i in range(cfg.n_layer):
            b = f"transformer.h.{i}."
            blk = {k: A_tree(v, i) for k, v in blocks.items()}
            out[b + "ln_1.weight"] = blk["ln_1"]["scale"]
            out[b + "ln_1.bias"] = blk["ln_1"]["bias"]
            for ours, theirs in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj")):
                out[b + f"attn.{theirs}.weight"] = (
                    blk["attn"][ours]["kernel"].reshape(E, H * D).T
                )
            out[b + "attn.out_proj.weight"] = blk["attn"]["o"]["kernel"].reshape(H * D, E).T
            out[b + "mlp.fc_in.weight"] = blk["mlp"]["fc_in"]["kernel"].T
            out[b + "mlp.fc_in.bias"] = blk["mlp"]["fc_in"]["bias"]
            out[b + "mlp.fc_out.weight"] = blk["mlp"]["fc_out"]["kernel"].T
            out[b + "mlp.fc_out.bias"] = blk["mlp"]["fc_out"]["bias"]
        out["transformer.ln_f.weight"] = A(params["ln_f"]["scale"])
        out["transformer.ln_f.bias"] = A(params["ln_f"]["bias"])
        out["lm_head.weight"] = A(params["lm_head"]["kernel"]).T
        out["lm_head.bias"] = np.zeros(cfg.vocab_size, np.float32)
        return out

    if model_type == "gpt_neox":
        out["gpt_neox.embed_in.weight"] = A(params["embed"]["wte"])
        for i in range(cfg.n_layer):
            b = f"gpt_neox.layers.{i}."
            blk = {k: A_tree(v, i) for k, v in blocks.items()}
            out[b + "input_layernorm.weight"] = blk["ln_1"]["scale"]
            out[b + "input_layernorm.bias"] = blk["ln_1"]["bias"]
            # fused qkv [3E, E], interleaved per head: [H, 3, D, E]
            w = np.stack(
                [np.moveaxis(blk["attn"][n]["kernel"], 0, -1) for n in "qkv"], axis=1
            )  # [H, 3, D, E]
            out[b + "attention.query_key_value.weight"] = w.reshape(3 * E, E)
            bias = np.stack([blk["attn"][n]["bias"] for n in "qkv"], axis=1)
            out[b + "attention.query_key_value.bias"] = bias.reshape(3 * E)
            out[b + "attention.dense.weight"] = blk["attn"]["o"]["kernel"].reshape(H * D, E).T
            out[b + "attention.dense.bias"] = blk["attn"]["o"]["bias"]
            out[b + "post_attention_layernorm.weight"] = blk["ln_2"]["scale"]
            out[b + "post_attention_layernorm.bias"] = blk["ln_2"]["bias"]
            out[b + "mlp.dense_h_to_4h.weight"] = blk["mlp"]["fc_in"]["kernel"].T
            out[b + "mlp.dense_h_to_4h.bias"] = blk["mlp"]["fc_in"]["bias"]
            out[b + "mlp.dense_4h_to_h.weight"] = blk["mlp"]["fc_out"]["kernel"].T
            out[b + "mlp.dense_4h_to_h.bias"] = blk["mlp"]["fc_out"]["bias"]
        out["gpt_neox.final_layer_norm.weight"] = A(params["ln_f"]["scale"])
        out["gpt_neox.final_layer_norm.bias"] = A(params["ln_f"]["bias"])
        out["embed_out.weight"] = A(params["lm_head"]["kernel"]).T
        return out

    if model_type == "opt":
        out["model.decoder.embed_tokens.weight"] = A(params["embed"]["wte"])
        out["model.decoder.embed_positions.weight"] = A(params["embed"]["wpe"])
        for i in range(cfg.n_layer):
            b = f"model.decoder.layers.{i}."
            blk = {k: A_tree(v, i) for k, v in blocks.items()}
            out[b + "self_attn_layer_norm.weight"] = blk["ln_1"]["scale"]
            out[b + "self_attn_layer_norm.bias"] = blk["ln_1"]["bias"]
            for ours, theirs in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj")):
                out[b + f"self_attn.{theirs}.weight"] = blk["attn"][ours]["kernel"].reshape(E, H * D).T
                out[b + f"self_attn.{theirs}.bias"] = blk["attn"][ours]["bias"].reshape(H * D)
            out[b + "self_attn.out_proj.weight"] = blk["attn"]["o"]["kernel"].reshape(H * D, E).T
            out[b + "self_attn.out_proj.bias"] = blk["attn"]["o"]["bias"]
            out[b + "final_layer_norm.weight"] = blk["ln_2"]["scale"]
            out[b + "final_layer_norm.bias"] = blk["ln_2"]["bias"]
            out[b + "fc1.weight"] = blk["mlp"]["fc_in"]["kernel"].T
            out[b + "fc1.bias"] = blk["mlp"]["fc_in"]["bias"]
            out[b + "fc2.weight"] = blk["mlp"]["fc_out"]["kernel"].T
            out[b + "fc2.bias"] = blk["mlp"]["fc_out"]["bias"]
        out["model.decoder.final_layer_norm.weight"] = A(params["ln_f"]["scale"])
        out["model.decoder.final_layer_norm.bias"] = A(params["ln_f"]["bias"])
        if "lm_head" in params:
            out["lm_head.weight"] = A(params["lm_head"]["kernel"]).T
        else:
            out["lm_head.weight"] = out["model.decoder.embed_tokens.weight"]
        return out

    if model_type == "bloom":
        out["transformer.word_embeddings.weight"] = A(params["embed"]["wte"])
        out["transformer.word_embeddings_layernorm.weight"] = A(params["ln_embed"]["scale"])
        out["transformer.word_embeddings_layernorm.bias"] = A(params["ln_embed"]["bias"])
        for i in range(cfg.n_layer):
            b = f"transformer.h.{i}."
            blk = {k: A_tree(v, i) for k, v in blocks.items()}
            out[b + "input_layernorm.weight"] = blk["ln_1"]["scale"]
            out[b + "input_layernorm.bias"] = blk["ln_1"]["bias"]
            # [H, 3, D, E] per-head interleave -> fused [3E, E]
            w = np.stack(
                [np.moveaxis(blk["attn"][n]["kernel"], 0, -1) for n in "qkv"], axis=1
            )
            out[b + "self_attention.query_key_value.weight"] = w.reshape(3 * E, E)
            bias = np.stack([blk["attn"][n]["bias"] for n in "qkv"], axis=1)
            out[b + "self_attention.query_key_value.bias"] = bias.reshape(3 * E)
            out[b + "self_attention.dense.weight"] = blk["attn"]["o"]["kernel"].reshape(H * D, E).T
            out[b + "self_attention.dense.bias"] = blk["attn"]["o"]["bias"]
            out[b + "post_attention_layernorm.weight"] = blk["ln_2"]["scale"]
            out[b + "post_attention_layernorm.bias"] = blk["ln_2"]["bias"]
            out[b + "mlp.dense_h_to_4h.weight"] = blk["mlp"]["fc_in"]["kernel"].T
            out[b + "mlp.dense_h_to_4h.bias"] = blk["mlp"]["fc_in"]["bias"]
            out[b + "mlp.dense_4h_to_h.weight"] = blk["mlp"]["fc_out"]["kernel"].T
            out[b + "mlp.dense_4h_to_h.bias"] = blk["mlp"]["fc_out"]["bias"]
        out["transformer.ln_f.weight"] = A(params["ln_f"]["scale"])
        out["transformer.ln_f.bias"] = A(params["ln_f"]["bias"])
        out["lm_head.weight"] = out["transformer.word_embeddings.weight"]
        return out

    if model_type == "gpt_bigcode":
        out["transformer.wte.weight"] = A(params["embed"]["wte"])
        out["transformer.wpe.weight"] = A(params["embed"]["wpe"])
        kv_dim = Hkv * D
        for i in range(cfg.n_layer):
            b = f"transformer.h.{i}."
            blk = {k: A_tree(v, i) for k, v in blocks.items()}
            out[b + "ln_1.weight"] = blk["ln_1"]["scale"]
            out[b + "ln_1.bias"] = blk["ln_1"]["bias"]
            w = np.concatenate(
                [
                    blk["attn"]["q"]["kernel"].reshape(E, H * D),
                    blk["attn"]["k"]["kernel"].reshape(E, kv_dim),
                    blk["attn"]["v"]["kernel"].reshape(E, kv_dim),
                ],
                axis=-1,
            )
            out[b + "attn.c_attn.weight"] = w.T
            out[b + "attn.c_attn.bias"] = np.concatenate(
                [
                    blk["attn"]["q"]["bias"].reshape(H * D),
                    blk["attn"]["k"]["bias"].reshape(kv_dim),
                    blk["attn"]["v"]["bias"].reshape(kv_dim),
                ]
            )
            out[b + "attn.c_proj.weight"] = blk["attn"]["o"]["kernel"].reshape(H * D, E).T
            out[b + "attn.c_proj.bias"] = blk["attn"]["o"]["bias"]
            out[b + "ln_2.weight"] = blk["ln_2"]["scale"]
            out[b + "ln_2.bias"] = blk["ln_2"]["bias"]
            out[b + "mlp.c_fc.weight"] = blk["mlp"]["fc_in"]["kernel"].T
            out[b + "mlp.c_fc.bias"] = blk["mlp"]["fc_in"]["bias"]
            out[b + "mlp.c_proj.weight"] = blk["mlp"]["fc_out"]["kernel"].T
            out[b + "mlp.c_proj.bias"] = blk["mlp"]["fc_out"]["bias"]
        out["transformer.ln_f.weight"] = A(params["ln_f"]["scale"])
        out["transformer.ln_f.bias"] = A(params["ln_f"]["bias"])
        out["lm_head.weight"] = out["transformer.wte.weight"]
        return out

    if model_type == "gpt_neo":
        out["transformer.wte.weight"] = A(params["embed"]["wte"])
        out["transformer.wpe.weight"] = A(params["embed"]["wpe"])
        for i in range(cfg.n_layer):
            b = f"transformer.h.{i}."
            blk = {k: A_tree(v, i) for k, v in blocks.items()}
            out[b + "ln_1.weight"] = blk["ln_1"]["scale"]
            out[b + "ln_1.bias"] = blk["ln_1"]["bias"]
            for ours, theirs in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj")):
                out[b + f"attn.attention.{theirs}.weight"] = blk["attn"][ours]["kernel"].reshape(E, H * D).T
            out[b + "attn.attention.out_proj.weight"] = blk["attn"]["o"]["kernel"].reshape(H * D, E).T
            out[b + "attn.attention.out_proj.bias"] = blk["attn"]["o"]["bias"]
            out[b + "ln_2.weight"] = blk["ln_2"]["scale"]
            out[b + "ln_2.bias"] = blk["ln_2"]["bias"]
            out[b + "mlp.c_fc.weight"] = blk["mlp"]["fc_in"]["kernel"].T
            out[b + "mlp.c_fc.bias"] = blk["mlp"]["fc_in"]["bias"]
            out[b + "mlp.c_proj.weight"] = blk["mlp"]["fc_out"]["kernel"].T
            out[b + "mlp.c_proj.bias"] = blk["mlp"]["fc_out"]["bias"]
        out["transformer.ln_f.weight"] = A(params["ln_f"]["scale"])
        out["transformer.ln_f.bias"] = A(params["ln_f"]["bias"])
        out["lm_head.weight"] = out["transformer.wte.weight"]
        return out

    raise ValueError(f"export not implemented for {model_type!r}")


def A_tree(tree, i: int):
    """Select layer i from a stacked subtree, as float32 numpy."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: np.asarray(x[i], dtype=np.float32), tree
    )
