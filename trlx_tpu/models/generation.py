"""Jitted autoregressive generation: static-shape prefill + decode scan.

Parity: the reference delegates sampling to HF `model.generate`
(/root/reference/trlx/trainer/accelerate_base_trainer.py:256-288) and to a
custom token-by-token loop for ILQL
(/root/reference/trlx/models/modeling_ilql.py:325-412). Here generation is
one jitted function: a KV-cache prefill over the (left-padded) prompt and
a `lax.scan` over `max_new_tokens` single-token steps.

TPU design notes:
- Static shapes everywhere: the cache is preallocated to
  prompt_len + max_new_tokens; finished sequences keep stepping but emit
  `pad_token_id` (the reference needed `synced_gpus` / no-early-break
  hacks for ZeRO-3 — SPMD makes "all devices run the full loop" the
  default, and the mask bookkeeping makes it correct).
- Sampling is `jax.random.categorical` over processed logits
  (temperature / top-k / top-p) — fp32 on the VPU, fused by XLA.
- An optional `logits_processor(hidden, logits) -> logits` hook runs
  inside the loop; ILQL's `pi_beta + beta*(minQ - V)` shaping plugs in
  here without a separate decode implementation.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.models.transformer import TransformerLM, logit_projection
from trlx_tpu.ops.common import topk_mask

Array = jnp.ndarray

# HF `generate` kwargs this sampler deliberately does not implement.
# Reference configs pass HF gen_kwargs verbatim (ref
# trlx/data/default_configs.py gen_kwargs), so these degrade with a
# warning — at config load (from_gen_kwargs) and per-call
# (BaseTrainer.generate consults the same set) — instead of loading
# fine then crashing evaluate() mid-sweep. Names outside this set are
# either sampler/processor-owned (validated by the trainer, which knows
# the processor's signature) or unknown.
HF_GEN_KWARGS_UNIMPLEMENTED = frozenset({
    "num_beams", "num_beam_groups", "penalty_alpha", "use_cache",
    "typical_p", "epsilon_cutoff", "eta_cutoff", "diversity_penalty",
    "repetition_penalty", "encoder_repetition_penalty", "length_penalty",
    "no_repeat_ngram_size", "bad_words_ids", "force_words_ids",
    "renormalize_logits", "constraints", "forced_bos_token_id",
    "forced_eos_token_id", "remove_invalid_values", "early_stopping",
    "exponential_decay_length_penalty", "suppress_tokens",
    "begin_suppress_tokens", "forced_decoder_ids", "num_return_sequences",
    "output_attentions", "output_hidden_states", "output_scores",
    "return_dict_in_generate", "min_length", "min_new_tokens",
    "max_length", "max_time",
})


@dataclass(frozen=True)
class SamplerSettings:
    """Static sampling hyperparameters (hashable: usable as jit statics).

    Mirrors the reference's HF `gen_kwargs` surface
    (default_configs.py:36: max_new_tokens / top_k / top_p / do_sample /
    temperature, plus eos/pad ids resolved by the trainer).
    """

    max_new_tokens: int
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    do_sample: bool = True
    eos_token_id: int = -1  # -1: never stops early
    pad_token_id: int = 0

    @classmethod
    def from_gen_kwargs(cls, gen_kwargs: Dict, eos_token_id=None, pad_token_id=None):
        kw = dict(gen_kwargs)
        eos = kw.pop("eos_token_id", eos_token_id)
        pad = kw.pop("pad_token_id", pad_token_id)
        known = {f.name for f in dataclasses.fields(cls)}
        # HF gen_kwargs this sampler doesn't implement are ignored
        # rather than fatal, so reference configs run unmodified — with
        # a warning for recognized-HF names (the same set the trainer's
        # generate() warns on per-call). Other unknown names (e.g. beta,
        # ILQL's shaping strength consumed by the logits processor) pass
        # silently here: only the trainer knows its processor signature.
        dropped_hf = set(kw) & HF_GEN_KWARGS_UNIMPLEMENTED
        if dropped_hf:
            from trlx_tpu.utils import logging

            logging.get_logger(__name__).warning(
                "SamplerSettings: ignoring HF gen_kwargs this sampler "
                f"does not implement: {sorted(dropped_hf)}"
            )
        kw = {k: v for k, v in kw.items() if k in known}
        return cls(
            **kw,
            eos_token_id=-1 if eos is None else int(eos),
            pad_token_id=0 if pad is None else int(pad),
        )


def top_p_mask(logits: Array, p: float) -> Array:
    """Nucleus filtering: mask logits outside the smallest set with
    cumulative probability >= p (always keeps the argmax)."""
    sorted_desc = -jnp.sort(-logits, axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # a sorted position is kept while the mass *before* it is < p
    keep = cum - probs < p
    cutoff = jnp.where(keep, sorted_desc, jnp.inf).min(axis=-1, keepdims=True)
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def process_logits(logits: Array, settings: SamplerSettings) -> Array:
    """Temperature / top-k / top-p pipeline in fp32."""
    logits = logits.astype(jnp.float32)
    if settings.temperature != 1.0:
        logits = logits / max(settings.temperature, 1e-6)
    if settings.top_k:
        logits = topk_mask(logits, settings.top_k)
    if settings.top_p < 1.0:
        logits = top_p_mask(logits, settings.top_p)
    return logits


def sample_token(rng: jax.Array, logits: Array, settings: SamplerSettings) -> Array:
    """Draw next tokens [B] from last-position logits [B, V]."""
    logits = process_logits(logits, settings)
    if not settings.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def lane_keys(base: jax.Array, lane_ids: Array) -> jax.Array:
    """Per-lane PRNG keys: fold a vector of ids into one base key.

    The decode engine (models/gen_engine.py) keys every sampling event
    on (prompt index, token position, event kind) folded into the call's
    base key, so a prompt's sampled continuation is INDEPENDENT of which
    slot served it, how the batch was composed, and whether speculative
    decoding was on — the property the golden-equivalence tests pin."""
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(
        lane_ids.astype(jnp.uint32)
    )


def sample_token_lanes(
    keys: jax.Array,  # [B] per-lane keys (lane_keys)
    logits: Array,  # [B, V]
    settings: SamplerSettings,
) -> Array:
    """Per-lane sampling: like `sample_token` but each row draws from
    its own key (gumbel-max == categorical, one lane at a time)."""
    logits = process_logits(logits, settings)
    if not settings.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    g = jax.vmap(lambda k: jax.random.gumbel(k, (logits.shape[-1],)))(keys)
    return jnp.argmax(logits + g, axis=-1).astype(jnp.int32)


def categorical_lanes(keys: jax.Array, probs: Array) -> Array:
    """Per-lane categorical draw from probability rows [B, V] (used by
    the speculative residual re-draw; probs need not be normalized)."""
    logp = jnp.log(jnp.maximum(probs, 1e-30))
    g = jax.vmap(lambda k: jax.random.gumbel(k, (probs.shape[-1],)))(keys)
    return jnp.argmax(logp + g, axis=-1).astype(jnp.int32)



def cast_params_for_decode(params: Dict, compute_dtype) -> Dict:
    """Hoist the per-matmul param casts out of a decode loop: every step
    re-reads every weight, so pre-casting MATMUL leaves to the compute
    dtype halves decode weight traffic when params are stored fp32
    (training precision). Only rank>=2 kernels/embeddings are cast — the
    model casts exactly those at each use (flax dtype=cfg.dtype) — and
    1-D norm scales/biases and the T5 rel_bias table stay fp32 BY DESIGN
    (their math runs in fp32).

    Numerics: bit-identical to the uncast forward for rotary/alibi/none
    position embeddings. For `pos_embed="learned"` the uncast forward
    adds take(wte)+take(wpe) in fp32 *before* rounding to the compute
    dtype, while the pre-cast version adds two pre-rounded operands — an
    ulp-level divergence in the sampled policy only. PPO correctness is
    unaffected: old/new logprob ratios both come from the teacher-forced
    scorer (which never sees pre-cast params), so the ratio is computed
    consistently either way; we keep the cast because the tied wte is
    the largest single matrix read per decode step (e.g. 39% of GPT-2's
    weights). Shared by the causal and seq2seq samplers."""

    # whitelist exactly the weights the forward casts per use (flax
    # DenseGeneral kernels + embedding tables); norm scales (stacked
    # [L, E] under blocks), biases and rel_bias tables keep fp32
    matmul_keys = ("kernel", "wte", "wpe")

    def needs_cast(path, x):
        if not jnp.issubdtype(x.dtype, jnp.floating) or x.dtype == compute_dtype:
            return False
        last = getattr(path[-1], "key", None) if path else None
        return last in matmul_keys

    # already-compute-dtype params (bf16 deployment checkpoints, or a
    # caller that pre-cast): return the SAME tree — at 1.3B the cast
    # copy is +2.6 GB of HBM that would sit next to the KV cache for
    # the whole rollout, for zero bandwidth benefit
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    if not any(needs_cast(path, x) for path, x in flat):
        return params

    return jax.tree_util.tree_map_with_path(
        lambda path, x: x.astype(compute_dtype) if needs_cast(path, x) else x,
        params,
    )


def generate(
    model: TransformerLM,
    params: Dict,
    input_ids: Array,  # [B, P] int32, LEFT-padded
    attention_mask: Array,  # [B, P] int32
    rng: jax.Array,
    settings: SamplerSettings,
    logits_processor: Optional[Callable[[Array, Array], Array]] = None,
    soft_prompt: Optional[Array] = None,  # [n, E] prompt-tuning tokens
    kv_prefix: Optional[Dict[str, Array]] = None,  # prefix-tuning k/v
    row_budget: Optional[Array] = None,  # [B] per-row max_new cap (<= N)
) -> Dict[str, Array]:
    """Sample up to `settings.max_new_tokens` continuations.

    Returns:
      sequences:      [B, P+N] prompt ++ response (response right-padded)
      response_ids:   [B, N]
      response_mask:  [B, N] 1 for real response tokens (incl. the EOS)

    `logits_processor(hidden_last, logits) -> logits` (both [B, ...]) runs
    before temperature/top-k/top-p — the ILQL advantage-shaping hook.

    Adapters warm the KV cache: soft-prompt tokens run one extra prefill
    segment over slots [0, n); kv prefixes are written into the cache
    directly. Either way the prompt then occupies slots [n, n+P) and
    sampled tokens follow — the decode loop is adapter-oblivious.
    """
    B, P = input_ids.shape
    N = settings.max_new_tokens
    if N < 1:
        raise ValueError("max_new_tokens must be >= 1")
    params = cast_params_for_decode(params, model.cfg.dtype)
    # decode runs the sequential layer scan even when training is
    # pipelined; gather each stage's layer slice ONCE here instead of
    # on every decode step (parallel/sharding.py:unshard_axis)
    from trlx_tpu.parallel.sharding import unshard_for_decode

    params = unshard_for_decode(params, getattr(model, "mesh", None))
    if getattr(model.cfg, "decode_weights_quant", None) == "int8":
        # rollout-policy weight quantization: block kernels go int8 +
        # per-channel scale (QDense picks the scale up via
        # has_variable). One-time cost per generate call (a read+write
        # of the block weights), amortized over prefill + every decode
        # step; see transformer.quantize_decode_weights for numerics.
        from trlx_tpu.models.transformer import quantize_decode_weights

        params = quantize_decode_weights(params)
    n_virt = 0
    if soft_prompt is not None:
        n_virt = soft_prompt.shape[0]
    elif kv_prefix is not None:
        n_virt = kv_prefix["k"].shape[1]
    # pallas only: round the cache up to 128 slots — Mosaic needs a
    # 128-aligned cache length to lower the prefill's chunked loads (the
    # pad slots stay masked below and decode never reaches them). Gated
    # on the prefill actually qualifying for the kernel (Attention also
    # needs 8-row-aligned queries, P % 8 == 0): when the prefill will
    # fall back to XLA anyway, the pad would just inflate cache memory
    # and every decode step's masked score width for nothing — same
    # reason the plain XLA path skips it.
    total = n_virt + P + N
    pad_slots = (
        (-total) % 128
        if model.cfg.attention_impl == "pallas" and P % 8 == 0
        else 0
    )
    total += pad_slots

    # response slots count as attendable keys once written
    key_mask = jnp.concatenate(
        [
            jnp.ones((B, n_virt), jnp.int32),
            attention_mask.astype(jnp.int32),
            jnp.ones((B, N), jnp.int32),
            jnp.zeros((B, pad_slots), jnp.int32),
        ],
        axis=1,
    )
    cache = model.init_cache(B, total, key_mask)
    if kv_prefix is not None:
        L = cache["k"].shape[0]

        def tiled(x):
            return jnp.broadcast_to(
                x[:, None], (L, B) + x.shape[1:]
            ).astype(cache["k"].dtype)

        cache = dict(
            cache,
            k=jax.lax.dynamic_update_slice_in_dim(
                cache["k"], tiled(kv_prefix["k"]), 0, axis=2
            ),
            v=jax.lax.dynamic_update_slice_in_dim(
                cache["v"], tiled(kv_prefix["v"]), 0, axis=2
            ),
            index=jnp.int32(n_virt),
            static_index=n_virt,
        )
    elif soft_prompt is not None:
        warm = model(
            params,
            jnp.zeros((B, n_virt), input_ids.dtype),
            cache=cache,
            prefix_embeds=soft_prompt,
            compute_logits=False,  # cache warm only; nothing samples here
        )
        # forwards drop the static index from the cache they return;
        # re-attach it so the main prefill keeps the pallas path
        cache = dict(warm["cache"], static_index=n_virt)

    # real positions (rope/wpe) run over non-pad tokens only, offset past
    # any virtual prefix (HF past-length semantics)
    positions = n_virt + jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)
    # compute_logits=False: only the LAST position samples, so the full
    # [B, P, V] prefill logits (3.3 GB fp32 at b8/seq2048/vocab50257 —
    # and ~7% of prefill FLOPs) are never materialized; the one needed
    # row is projected from the final hidden below
    out = model(
        params, input_ids, attention_mask, positions=positions, cache=cache,
        compute_logits=False,
    )
    prompt_len = n_virt + attention_mask.sum(axis=1)  # [B] next real position

    def pick_next(rng, hidden_last, logits_last, finished):
        if logits_processor is not None:
            logits_last = logits_processor(hidden_last, logits_last)
        tok = sample_token(rng, logits_last, settings)
        tok = jnp.where(finished, jnp.int32(settings.pad_token_id), tok)
        now_finished = finished | (tok == settings.eos_token_id)
        return tok, now_finished

    rng, sub = jax.random.split(rng)
    finished0 = jnp.zeros((B,), bool)
    h_last = out["hidden_states"][:, -1]
    logits_last = logit_projection(params)(h_last)
    tok0, finished0 = pick_next(sub, h_last, logits_last, finished0)
    if row_budget is not None:
        # per-row response budgets (serving-style per-request
        # max_tokens; also how the bench builds honestly-ragged decode
        # workloads): a row that hits its budget finishes like an EOS
        budget = jnp.asarray(row_budget, jnp.int32)
        finished0 = finished0 | (budget <= 1)

    decode_cache = out["cache"]
    if model.cfg.kv_cache_quant in ("int8", "int8_kernel"):
        # quantize ONCE after prefill (prefill numerics/pallas path stay
        # untouched); every decode step then reads an int8 cache stream
        # — half the HBM traffic of bf16, which is what bounds decode at
        # large batch×seq (models/transformer.py:quantize_kv_cache)
        from trlx_tpu.models.transformer import quantize_kv_cache

        decode_cache = quantize_kv_cache(decode_cache)

    if N > 1:
        pos0 = prompt_len  # next token's real position
        ids_buf = jnp.full((B, N), jnp.int32(settings.pad_token_id))
        mask_buf = jnp.zeros((B, N), bool)
        ids_buf = ids_buf.at[:, 0].set(tok0)
        mask_buf = mask_buf.at[:, 0].set(True)

        # lax.while_loop instead of a fixed-trip scan: once every row has
        # emitted its EOS the loop exits early — real tasks' responses
        # average well under max_new_tokens, and SPMD makes the early
        # exit safe (every host runs the same global condition; the
        # reference needed synced_gpus/no-early-break workarounds —
        # SURVEY §7 hard parts)
        def cond(state):
            _, _, _, finished, t, _, _, _ = state
            return (t < N) & ~jnp.all(finished)

        def body(state):
            cache, tok, pos, finished, t, rng, ids_buf, mask_buf = state
            step_out = model(
                params, tok[:, None], positions=pos[:, None], cache=cache
            )
            rng, sub = jax.random.split(rng)
            next_tok, now_finished = pick_next(
                sub, step_out["hidden_states"][:, -1], step_out["logits"][:, -1],
                finished,
            )
            if row_budget is not None:
                now_finished = now_finished | (budget <= t + 1)
            real = ~finished  # next_tok is real iff not finished before it
            ids_buf = jax.lax.dynamic_update_slice_in_dim(
                ids_buf, next_tok[:, None], t, axis=1
            )
            mask_buf = jax.lax.dynamic_update_slice_in_dim(
                mask_buf, real[:, None], t, axis=1
            )
            return (
                step_out["cache"], next_tok, pos + 1, now_finished, t + 1,
                rng, ids_buf, mask_buf,
            )

        state = (decode_cache, tok0, pos0, finished0, jnp.int32(1), rng,
                 ids_buf, mask_buf)
        (_, _, _, _, _, _, response_ids, response_mask) = jax.lax.while_loop(
            cond, body, state
        )
    else:
        response_ids = tok0[:, None]
        response_mask = jnp.ones((B, 1), bool)

    sequences = jnp.concatenate([input_ids, response_ids], axis=1)
    return {
        "sequences": sequences,
        "response_ids": response_ids,
        "response_mask": response_mask.astype(jnp.int32),
    }


def make_generate_fn(
    model: TransformerLM,
    settings: SamplerSettings,
    logits_processor: Optional[Callable] = None,
):
    """Build a jitted `(params, input_ids, attention_mask, rng) -> dict`
    sampler. Shapes are static per (B, P); XLA caches one executable per
    distinct prompt padding length (trainers pad prompts to a fixed
    max_prompt_length so there is exactly one)."""

    @partial(jax.jit, donate_argnums=())
    def fn(params, input_ids, attention_mask, rng):
        return generate(
            model, params, input_ids, attention_mask, rng, settings,
            logits_processor=logits_processor,
        )

    return fn
