"""Serving-grade rollout decode engine: continuous batching over a
paged (optionally int8) KV cache, with reference-drafted speculative
decoding.

The static sampler (models/generation.py) steps the WHOLE batch until
every row finishes: one long response stalls the batch, and by the tail
of the loop a single live row pays a full-width decode step. This
engine replaces that loop for rollout collection with a slot-based
design:

  * **Continuous batching** — a fixed set of `slots` decode lanes is
    fed from a device-resident prompt queue. The whole queue is
    processed by ONE jitted `lax.while_loop`: whenever a lane finishes
    (EOS / its token budget), the next iteration's refill phase
    (`lax.cond`, so it costs nothing on iterations with no refill)
    prefills the next queued prompt INTO that slot and decoding
    continues at full occupancy. `queue size >> slots` is the intended
    shape: the step batch stays dense for the whole rollout phase
    instead of decaying to one live row.
  * **Paged int8 KV** (ops/paged_kv.py) — slots index fixed-size pages
    through a page table; a refilled slot's pages return to a free
    stack and are reused, and response pages are allocated lazily, so
    short responses never pay max-length KV. `paged=False` keeps the
    indirection out (a contiguous per-slot layout the gather collapses
    through) so the two pillars are separable in benchmarks.
  * **Speculative decoding** — a draft model (the frozen PPO reference:
    the policy is one KL-constrained step away from it, so acceptance
    is high) drafts `draft_k` tokens autoregressively; the policy
    verifies all of them in ONE `T=draft_k` forward (one weight read
    amortized over k tokens) with standard rejection sampling, which
    leaves the sampled distribution exactly the policy's. Greedy mode
    accepts iff the draft token equals the policy argmax, so greedy
    output is token-for-token the non-speculative stream.

RNG contract: every sampling event is keyed on (queue row, response
index, event kind) folded into the call's base key — NOT on the slot or
the step. A prompt therefore samples the same continuation regardless
of batch composition, slot assignment, refill order, or whether
speculative decoding is enabled (when draft == policy, acceptance is
certain and the streams are bit-identical). tests/test_gen_engine.py
pins all of these.

Scope (v1): causal LMs, single data group (the rollout-worker geometry
of the disaggregated actor–learner plan — ROADMAP item 1); no soft
prompts / prefix tuning; multihost and seq2seq fall back to the static
sampler in trainer/base.generate.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from trlx_tpu.models.generation import (
    SamplerSettings,
    cast_params_for_decode,
    categorical_lanes,
    lane_keys,
    process_logits,
    sample_token_lanes,
)
from trlx_tpu.models.transformer import TransformerLM, logit_projection
from trlx_tpu.ops import paged_kv

Array = jnp.ndarray


@dataclass(frozen=True)
class GenEngineConfig:
    """`ppo.gen_engine.*` — user-facing engine configuration (plain dict
    in YAML; parsed here so unknown keys fail at config load).

    enabled       route PPO rollout generation through the engine
                  (default off: byte-identical rollouts to the static
                  sampler's RNG stream are NOT preserved across the
                  switch — the engine keys RNG per (prompt, position)).
    slots         decode lanes per step; 0 = the generate() call's
                  batch width (chunk size), i.e. refills only help a
                  ragged tail. Real wins come from slots < chunk.
    page_size     tokens per KV page.
    paged         False = contiguous per-slot layout (no indirection,
                  no lazy allocation — the continuous-batching-only
                  configuration benchmarks attribute against).
    pool_pages    total pages in the pool; 0 = worst case
                  (slots * pages_per_slot + null page), which can only
                  be undersized deliberately.
    refill_width  prompts prefilled per refill event; 0 = slots.
    spec_decode   draft with the frozen reference, verify with the
                  policy (exact via rejection sampling).
    draft_k       drafted tokens per speculative round.
    kv_quant      "int8" | "none"; None follows the model's
                  kv_cache_quant (the production rollout default).
    paged_attention_impl  "xla" (gather path) | "pallas" (the paged
                  decode kernel: pages stream from the pool via the
                  page table as block index map — nothing S-wide is
                  ever gathered). Applies to the paged layout only;
                  the contiguous layout always takes the XLA path (its
                  gather is already a fused reshape). On TPU the
                  pallas impl needs page_size % 128 == 0.
    data_groups   independent engine LANE GROUPS per call: the queue
                  splits into this many shards, each with its own
                  slots/pool/page-table/allocator, run as one stacked
                  dispatch (group state shards over the mesh's data
                  axes when the geometry divides). RNG stays keyed on
                  the GLOBAL queue row, so greedy output is
                  token-for-token the single-group stream.
    """

    enabled: bool = False
    slots: int = 0
    page_size: int = 128
    paged: bool = True
    pool_pages: int = 0
    refill_width: int = 0
    spec_decode: bool = False
    draft_k: int = 4
    kv_quant: Optional[str] = None
    paged_attention_impl: str = "xla"
    data_groups: int = 1

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "GenEngineConfig":
        d = dict(d or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"ppo.gen_engine: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        cfg = cls(**d)
        if cfg.page_size < 1:
            raise ValueError("ppo.gen_engine.page_size must be >= 1")
        if cfg.draft_k < 1:
            raise ValueError("ppo.gen_engine.draft_k must be >= 1")
        if cfg.kv_quant not in (None, "none", "int8"):
            raise ValueError(
                f"ppo.gen_engine.kv_quant must be none/int8, got {cfg.kv_quant!r}"
            )
        if cfg.paged_attention_impl not in ("xla", "pallas"):
            raise ValueError(
                "ppo.gen_engine.paged_attention_impl must be xla/pallas, "
                f"got {cfg.paged_attention_impl!r}"
            )
        if cfg.data_groups < 1:
            raise ValueError("ppo.gen_engine.data_groups must be >= 1")
        return cfg

    def resolve(self, batch: int, model_cfg) -> "EngineSpec":
        """Concretize against a call's batch width and the model."""
        quant = self.kv_quant
        if quant is None:
            quant = "int8" if model_cfg.kv_cache_quant in (
                "int8", "int8_kernel"
            ) else "none"
        slots = self.slots or batch
        if batch:
            slots = min(slots, batch)
        groups = self.data_groups
        if batch:
            groups = max(1, min(groups, batch))
        return EngineSpec(
            slots=slots,
            page_size=self.page_size,
            paged=self.paged,
            pool_pages=self.pool_pages,
            refill_width=self.refill_width or slots,
            spec_decode=self.spec_decode,
            draft_k=self.draft_k,
            kv_quant=None if quant == "none" else quant,
            paged_attention_impl=self.paged_attention_impl,
            data_groups=groups,
        )


@dataclass(frozen=True)
class EngineSpec:
    """Static engine geometry (hashable: keys the jit cache).

    ``draft_shared_layers`` is DERIVED, not user config: with a hydra
    (policy-trunk + frozen-branch) speculative draft, the draft's
    bottom ``draft_shared_layers`` layers are the policy's trunk — the
    trainer sets it from the composed reference's branch depth so the
    engine stores trunk KV ONCE (the pool's layer axis extends by only
    the branch depth instead of doubling; see engine_generate). It is
    only valid when ``compose_draft_params`` built the draft — a
    full-copy draft shares nothing and must leave it 0."""

    slots: int
    page_size: int = 128
    paged: bool = True
    pool_pages: int = 0
    refill_width: int = 0
    spec_decode: bool = False
    draft_k: int = 4
    kv_quant: Optional[str] = None
    paged_attention_impl: str = "xla"
    data_groups: int = 1
    draft_shared_layers: int = 0


def hydra_shared_trunk_layers(n_layer: int, ref_branch_layers) -> int:
    """Trunk layers a composed hydra draft shares with the policy pool:
    ``L - k`` when the frozen reference is a top-``k`` branch
    (0 < k < L); 0 for a full-copy reference (its layers all diverge
    from the policy's the moment training moves) and for k == 0. The
    ONE derivation shared by the trainer (`_engine_spec`) and the
    memory-doctor planners, so the spec the jit traces and the bytes
    the preflight admits can't disagree."""
    k = ref_branch_layers
    if k is None or k <= 0 or k >= n_layer:
        return 0
    return n_layer - k


def _round_up(x: int, to: int) -> int:
    return x + (-x) % to


def compose_draft_params(cfg, policy_params: Dict, ref_params: Dict) -> Dict:
    """The speculative draft model = the frozen PPO reference.

    With a full-copy reference (num_layers_unfrozen=-1) the reference IS
    a standalone model — return it. With a hydra branch the reference is
    only the top-k layers; the draft composes the policy's trunk (the
    bottom layers are shared and frozen-equivalent at the branch point)
    with the frozen branch into a full stack. The concat materializes a
    trunk copy inside the trace — acceptable per generate call at small
    scale; at multi-GB scale prefer a full-copy reference when drafting.
    """
    k = jax.tree_util.tree_leaves(ref_params["blocks"])[0].shape[0]
    if k == cfg.n_layer:
        return ref_params
    trunk = jax.tree_util.tree_map(
        lambda x: x[: cfg.n_layer - k], policy_params["blocks"]
    )
    blocks = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b.astype(a.dtype)], axis=0),
        trunk, ref_params["blocks"],
    )
    return dict(ref_params, blocks=blocks)


def engine_generate(
    model: TransformerLM,
    params: Dict,
    q_ids: Array,  # [Q, P] int32, LEFT-padded prompt queue
    q_mask: Array,  # [Q, P] int32
    rng: jax.Array,
    settings: SamplerSettings,
    spec: EngineSpec,
    draft_params: Optional[Dict] = None,
    row_budget: Optional[Array] = None,  # [Q] per-row max_new (<= N)
    warm: Optional[Dict[str, Array]] = None,
    q_pin: Optional[Array] = None,  # [Q] bool: keep pages at finish
    q_ready: Optional[Array] = None,  # [Q] page-aligned shared prefix len
    q_rng_row: Optional[Array] = None,  # [Q] per-row RNG id base
    rng_space: Optional[int] = None,  # id-space width (default Q): the
    # GLOBAL queue size when this call serves one shard of a grouped
    # run, so the acceptance/residual RNG offsets match the
    # single-group stream exactly
) -> Dict[str, Array]:
    """Generate a continuation for every queue row through the engine.

    Returns the static sampler's output contract (sequences [Q, P+N],
    response_ids [Q, N], response_mask [Q, N]) plus `gen_stats`, a dict
    of device scalars: decode_steps, refills, real_tokens,
    occupancy (real tokens / (decode_steps * slots)), truncated (rows
    that hit their budget without EOS), oom_truncated (lanes killed by
    page-pool exhaustion — 0 unless pool_pages was undersized),
    reclaimed_pages (prompt-pad compaction: pages holding nothing but
    left-pad KV, released back to the free stack at refill), and in
    speculative mode drafted / accepted / spec_rounds.

    Serving mode (``warm`` given — the trlx_tpu/serve/ tier): the call
    enters with a PERSISTENT page pool instead of a fresh one.
    ``warm`` carries ``pool`` (pre-populated leaves), ``free``/``ntop``
    (the host's free stack, minus every page a cached prefix/session
    entry holds), ``refcnt`` (per-page counts, paged_kv.init_refcounts
    contract) and ``row_table`` [Q, MP] (each row's shared-page
    mapping; entries past ``q_ready[q] // page_size`` must be 0).
    A row with ``q_ready[q] = A`` has its first A slot positions
    already present in shared pages: refill maps those pages
    read-only, pops fresh pages only for the rest, and the prefill
    scatter is gated off positions < A (copy-on-write: the divergent
    suffix always lands in the row's own pages). Rows with
    ``q_pin[q]`` keep ALL their pages at finish — the final table row
    and KV length come back in ``kv_state.saved_tables`` /
    ``saved_len`` for the host to adopt into its session/prefix cache
    — and are counted in ``gen_stats.pinned_pages``, NEVER in
    ``reclaimed_pages`` or ``oom_truncated`` (a pin is a normal
    finish, not a truncation, and the pages are alive, not reclaimed).
    ``q_rng_row`` replaces the queue index in the RNG id space so a
    request's sampled stream is invariant to which call/batch serves
    it. The output gains ``kv_state`` = the end-of-call pool + free
    stack + refcounts for the host to carry into the next call.
    """
    Q, P = q_ids.shape
    N = settings.max_new_tokens
    if N < 1:
        raise ValueError("max_new_tokens must be >= 1")
    cfg = model.cfg
    SLOTS = max(1, min(spec.slots, Q))
    PS = spec.page_size
    K = spec.draft_k if spec.spec_decode else 0
    # speculative rounds may draft past a lane's budget before the
    # verifier truncates; give every slot K slack positions so those
    # writes land in real (masked, later-cleared) slots
    MP = paged_kv.pages_per_slot(P, N + K, PS)
    S = MP * PS
    PP = -(-P // PS)  # prompt pages per refill (pads included)
    # contiguous layout needs its full static page range; only the
    # paged layout can run on a deliberately undersized pool
    NP = (spec.pool_pages or (1 + SLOTS * MP)) if spec.paged else (
        1 + SLOTS * MP
    )
    if NP < 1 + SLOTS * PP:
        raise ValueError(
            f"pool_pages={NP} cannot hold {SLOTS} slots' prompts "
            f"({PP} pages each + null page)"
        )
    R = max(1, min(spec.refill_width or SLOTS, SLOTS))
    quant = spec.kv_quant
    eos = jnp.int32(settings.eos_token_id)
    pad = jnp.int32(settings.pad_token_id)
    if spec.spec_decode and draft_params is None:
        raise ValueError("spec_decode needs draft_params (the reference)")
    # spec-decode trunk-KV sharing (hydra draft = policy trunk + frozen
    # branch): the draft's trunk KV is IDENTICAL to the policy's by
    # construction — same weights, same token inputs, same positions —
    # so instead of a full second pool the ONE pool's layer axis
    # extends by just the draft's BRANCH depth. Trunk pages are held
    # once; the pool refcounts account for the two logical holders
    # (policy stream + draft stream) of every page.
    shared = spec.draft_shared_layers if spec.spec_decode else 0
    if shared:
        if not 0 < shared < cfg.n_layer:
            raise ValueError(
                f"draft_shared_layers={shared} must be in (0, n_layer="
                f"{cfg.n_layer})"
            )
        KB = cfg.n_layer - shared  # draft branch layers stored past L
        draft_layer_ixs = jnp.concatenate(
            [
                jnp.arange(shared, dtype=jnp.int32),
                cfg.n_layer + jnp.arange(KB, dtype=jnp.int32),
            ]
        )
    else:
        KB = 0
        draft_layer_ixs = None
    pool_layers = cfg.n_layer + KB
    # every spec-decode page is held by BOTH streams (trunk layers by
    # construction; branch layers ride the same physical page of the
    # extended pool), so page lifetime runs through the refcount
    # machinery: +2 at allocation, two decrements at release
    refcounted = spec.spec_decode and spec.paged
    serving = warm is not None
    if serving:
        if not spec.paged:
            raise ValueError("serving (warm pool) requires spec.paged")
        if spec.spec_decode:
            raise ValueError(
                "serving (warm pool) does not compose with spec_decode "
                "in v1 (the draft pool has no shared-page story yet)"
            )
        if q_pin is None:
            q_pin = jnp.zeros((Q,), bool)
        q_pin = q_pin.astype(bool)
        if q_ready is None:
            q_ready = jnp.zeros((Q,), jnp.int32)
        q_ready = q_ready.astype(jnp.int32)

    params = cast_params_for_decode(params, cfg.dtype)
    from trlx_tpu.parallel.sharding import unshard_for_decode

    params = unshard_for_decode(params, getattr(model, "mesh", None))
    if getattr(cfg, "decode_weights_quant", None) == "int8":
        from trlx_tpu.models.transformer import quantize_decode_weights

        params = quantize_decode_weights(params)
    if draft_params is not None:
        draft_params = cast_params_for_decode(draft_params, cfg.dtype)
        draft_params = unshard_for_decode(
            draft_params, getattr(model, "mesh", None)
        )
        if getattr(cfg, "decode_weights_quant", None) == "int8":
            from trlx_tpu.models.transformer import quantize_decode_weights

            draft_params = quantize_decode_weights(draft_params)

    q_ids = q_ids.astype(jnp.int32)
    q_mask = q_mask.astype(jnp.int32)
    if row_budget is None:
        row_budget = jnp.full((Q,), N, jnp.int32)
    row_budget = jnp.clip(row_budget.astype(jnp.int32), 1, N)

    # RNG id spaces: token draws at r*N + j; acceptance and residual
    # draws in disjoint ranges above them. rng_space widens the id
    # space to the GLOBAL queue size under grouped lanes, so a shard's
    # offsets land exactly where the single-group run's do.
    Qr = rng_space or Q
    OFF_ACC = (Qr + 1) * N
    OFF_RES = 2 * (Qr + 1) * N

    def _rng_ids(ix: Array) -> Array:
        """RNG id base per queue row: the queue index by default; the
        caller-supplied per-request id in serving mode, so a request's
        sampled stream is invariant to batch composition across calls."""
        if q_rng_row is None:
            return ix
        return q_rng_row.astype(jnp.int32)[jnp.clip(ix, 0, Q - 1)]

    # pallas prefill wants a 128-aligned temp cache + 8-row-aligned
    # queries, mirroring generate()'s gate; otherwise it falls back to
    # XLA inside the same code path
    Pc = _round_up(P, 128) if (cfg.attention_impl == "pallas" and P % 8 == 0) else P

    def _contig_table() -> Array:
        base = 1 + jnp.arange(SLOTS * MP, dtype=jnp.int32).reshape(SLOTS, MP)
        return base

    def _init_state() -> Dict[str, Any]:
        if serving:
            state: Dict[str, Any] = {"pool": dict(warm["pool"])}
            state["free"] = warm["free"]
            state["ntop"] = warm["ntop"].astype(jnp.int32)
            state["refcnt"] = warm["refcnt"].astype(jnp.int32)
            state["table"] = jnp.zeros((SLOTS, MP), jnp.int32)
            state["saved_tables"] = jnp.zeros((Q, MP), jnp.int32)
            state["saved_len"] = jnp.zeros((Q,), jnp.int32)
            state["pinned"] = jnp.int32(0)
        else:
            pool = paged_kv.init_pool(
                pool_layers, NP, PS, cfg.n_kv_head, cfg.head_dim, quant,
                cfg.dtype,
            )
            state = {"pool": pool}
            if spec.spec_decode and not shared:
                # full-copy draft: nothing is shared — it keeps its own
                # full-depth pool over the same page ids (the historic
                # 2x layout, now only paid when it is actually needed)
                state["dpool"] = paged_kv.init_pool(
                    cfg.n_layer, NP, PS, cfg.n_kv_head, cfg.head_dim, quant,
                    cfg.dtype,
                )
            if spec.paged:
                free, ntop = paged_kv.init_alloc(NP)
                state["free"], state["ntop"] = free, ntop
                state["table"] = jnp.zeros((SLOTS, MP), jnp.int32)
                if refcounted:
                    state["refcnt"] = paged_kv.init_refcounts(NP)
            else:
                state["table"] = _contig_table()
        state.update(
            pos=jnp.zeros((SLOTS,), jnp.int32),
            npad=jnp.zeros((SLOTS,), jnp.int32),
            new=jnp.zeros((SLOTS,), jnp.int32),
            budget=jnp.ones((SLOTS,), jnp.int32),
            active=jnp.zeros((SLOTS,), bool),
            pidx=jnp.zeros((SLOTS,), jnp.int32),
            cur=jnp.zeros((SLOTS,), jnp.int32),
            kmask=jnp.zeros((SLOTS, S), jnp.int32),
            qnext=jnp.int32(0),
            resp_ids=jnp.full((Q, N), pad, jnp.int32),
            resp_mask=jnp.zeros((Q, N), jnp.int32),
            decode_steps=jnp.int32(0),
            lane_steps=jnp.int32(0),
            refills=jnp.int32(0),
            emitted=jnp.int32(0),
            truncated=jnp.int32(0),
            oom=jnp.int32(0),
            reclaimed=jnp.int32(0),
            rounds=jnp.int32(0),
            drafted=jnp.int32(0),
            accepted=jnp.int32(0),
        )
        return state

    def _paged_cache(pool, state, slot_pos, key_mask, draft=False):
        cache = dict(
            pool,
            page_table=state["table"],
            slot_pos=slot_pos,
            key_mask=key_mask,
            lane_valid=state["active"],
        )
        if not spec.paged:
            cache["contiguous"] = True
        elif spec.paged_attention_impl != "xla":
            cache["attn_impl"] = spec.paged_attention_impl
        if draft and shared:
            # the draft's trunk layers read/write the POLICY pool's
            # trunk slots; its branch layers the extension slots
            cache["layer_ixs"] = draft_layer_ixs
        return cache

    def _draft_pool(state):
        return state["pool"] if shared else state["dpool"]

    def _with_draft_pool(state, pool):
        return dict(state, pool=pool) if shared else dict(state, dpool=pool)

    def _note_alloc(state, ids):
        """Freshly popped pages enter with refcount 2 in spec-decode
        mode: one hold per stream (policy + draft) of the page."""
        if not refcounted:
            return state
        return dict(
            state,
            refcnt=state["refcnt"].at[ids].add(
                2 * (ids > 0).astype(jnp.int32)
            ),
        )

    def _free_slot_pages(state, pages, is_real):
        """Return pages to the free stack. Spec-decode mode releases
        through the refcount machinery — one decrement per stream, the
        second (count-zero) release pushes the page — so trunk pages
        are provably held ONCE and `free + held == pool` balances."""
        if refcounted:
            free, ntop, rc = paged_kv.release_refcounted(
                state["free"], state["ntop"], state["refcnt"], pages, is_real
            )
            free, ntop, rc = paged_kv.release_refcounted(
                free, ntop, rc, pages, is_real
            )
            return dict(state, free=free, ntop=ntop, refcnt=rc)
        free, ntop = paged_kv.push_free(
            state["free"], state["ntop"], pages, is_real
        )
        return dict(state, free=free, ntop=ntop)

    def _prefill_into_slots(
        prms, pool, state, ids, mask, posns, slot, do, ready=None,
        branch_only=False,
    ):
        """Dense prefill of [R, P] prompts, scattered into `slot`'s
        pages. Returns (pool, last_hidden [R, E]). ``ready`` [R] gates
        the scatter off slot positions < ready (serving: those
        positions live in SHARED pages, already prefilled by the
        request that created the cache entry — this v1 recomputes their
        KV transiently in the temp cache but never writes it, which is
        what makes the shared pages safely read-only). ``branch_only``
        (trunk-sharing draft prefill) scatters just the draft's BRANCH
        layers into the pool's extension slots: its trunk KV is the
        policy prefill's, already written."""
        key_mask = jnp.concatenate(
            [mask, jnp.zeros((R, Pc - P), jnp.int32)], axis=1
        ) if Pc != P else mask
        tmp = model.init_cache(R, Pc, key_mask)
        out = model(
            prms, ids, mask, positions=posns, cache=tmp, compute_logits=False
        )
        ck = out["cache"]["k"][:, :, :P]  # [L, R, P, Hkv, D]
        cv = out["cache"]["v"][:, :, :P]
        lsel = None
        if branch_only:
            ck = ck[cfg.n_layer - KB:]
            cv = cv[cfg.n_layer - KB:]
            lsel = cfg.n_layer + jnp.arange(KB, dtype=jnp.int32)
        elif shared:
            # extended pool: the policy stack fills layers 0..L-1, the
            # extension slots belong to the draft branch
            lsel = jnp.arange(cfg.n_layer, dtype=jnp.int32)
        tbl = state["table"][jnp.clip(slot, 0, SLOTS - 1)]
        prompt_pos = jnp.broadcast_to(
            jnp.arange(P, dtype=jnp.int32)[None, :], (R, P)
        )
        pids, offs = paged_kv.write_positions(tbl, prompt_pos, PS, lane_valid=do)
        if ready is not None:
            # copy-on-write boundary: shared positions route to the
            # null page (their KV is already in the shared pages)
            pids = jnp.where(prompt_pos < ready[:, None], 0, pids)
        if quant == "int8":
            kq, ks = paged_kv.quantize_rows(ck)
            vq, vs = paged_kv.quantize_rows(cv)
            pool = dict(
                pool,
                pk=paged_kv.scatter_prefill(
                    pool["pk"], pids, offs, kq, layer_ixs=lsel
                ),
                pv=paged_kv.scatter_prefill(
                    pool["pv"], pids, offs, vq, layer_ixs=lsel
                ),
                pk_scale=paged_kv.scatter_prefill(
                    pool["pk_scale"], pids, offs, ks, layer_ixs=lsel
                ),
                pv_scale=paged_kv.scatter_prefill(
                    pool["pv_scale"], pids, offs, vs, layer_ixs=lsel
                ),
            )
        else:
            pool = dict(
                pool,
                pk=paged_kv.scatter_prefill(
                    pool["pk"], pids, offs, ck, layer_ixs=lsel
                ),
                pv=paged_kv.scatter_prefill(
                    pool["pv"], pids, offs, cv, layer_ixs=lsel
                ),
            )
        return pool, out["hidden_states"][:, -1]

    def _refill(state: Dict[str, Any]) -> Dict[str, Any]:
        active, qnext = state["active"], state["qnext"]
        order = jnp.argsort(active.astype(jnp.int32), stable=True)
        cand = order[:R]
        navail = jnp.minimum(
            jnp.minimum((~active).sum().astype(jnp.int32), Q - qnext),
            jnp.int32(R),
        )
        if spec.paged:
            navail = jnp.minimum(navail, state["ntop"] // PP)
        do = jnp.arange(R, dtype=jnp.int32) < navail
        slot = jnp.where(do, cand, SLOTS)  # OOB -> scatter drops
        qrow = jnp.where(do, qnext + jnp.arange(R, dtype=jnp.int32), Q)
        qc = jnp.clip(qrow, 0, Q - 1)
        ids = q_ids[qc]
        mask = q_mask[qc]

        ready_r = None
        ready_pg = None
        if serving:
            ready_r = jnp.where(do, q_ready[qc], 0)
            ready_pg = ready_r // PS
        if spec.paged:
            # return the refilled slots' old pages, then allocate fresh
            # prompt pages (often the very pages just freed)
            old = state["table"][jnp.clip(slot, 0, SLOTS - 1)]
            state = _free_slot_pages(
                state, old.reshape(-1), jnp.repeat(do, MP)
            )
            free, ntop = state["free"], state["ntop"]
            table = state["table"].at[slot].set(0, mode="drop")
            pgrid_pp = jnp.arange(PP, dtype=jnp.int32)[None, :]
            if serving:
                # pop fresh pages only for the NON-shared prompt part;
                # the shared prefix maps the cache entry's pages
                want = do[:, None] & (pgrid_pp >= ready_pg[:, None])
                got, free, ntop = paged_kv.pop_pages(
                    free, ntop, want.reshape(-1)
                )
                shared_rows = warm["row_table"][qc][:, :PP]
                entries = jnp.where(
                    pgrid_pp < ready_pg[:, None], shared_rows,
                    got.reshape(R, PP),
                )
            else:
                got, free, ntop = paged_kv.pop_pages(
                    free, ntop, jnp.repeat(do, PP)
                )
                entries = got.reshape(R, PP)
            table = table.at[slot[:, None], pgrid_pp].set(
                entries, mode="drop"
            )
            state = _note_alloc(
                dict(state, free=free, ntop=ntop, table=table), got
            )

        posns = jnp.maximum(jnp.cumsum(mask, axis=1) - 1, 0)
        pool, h_last = _prefill_into_slots(
            params, state["pool"], state, ids, mask, posns, slot, do,
            ready=ready_r,
        )
        state = dict(state, pool=pool)
        if spec.spec_decode:
            dpool, _ = _prefill_into_slots(
                draft_params, _draft_pool(state), state, ids, mask, posns,
                slot, do, branch_only=bool(shared),
            )
            state = _with_draft_pool(state, dpool)

        if spec.paged:
            # prompt-pad page COMPACTION: a prompt page holding nothing
            # but pad KV (every position in it has mask 0, so its kmask
            # bit is 0 forever) is dead weight parked on the lane from
            # refill to finish. Release such pages right after prefill:
            # reads of those positions gather the null page under a
            # zero key mask, and neither prefill (done) nor decode
            # (writes only at >= P) ever touches them again. This
            # lowers the engine's HBM floor on ragged prompt mixes —
            # the pool only has to hold REAL tokens plus page-rounding,
            # not the pad overhang of the widest prompt. Detection is
            # per-page over the mask (covers the leading left-pad block
            # AND the serving tier's internal pad gap between a shared
            # prefix and the divergent suffix); shared-prefix entries
            # (< ready_pg) are never candidates — their pages belong to
            # the cache, not this lane.
            mask_pp = jnp.concatenate(
                [mask, jnp.zeros((R, PP * PS - P), jnp.int32)], axis=1
            ) if PP * PS != P else mask
            page_has_real = mask_pp.reshape(R, PP, PS).sum(axis=2) > 0
            pgrid = jnp.arange(PP, dtype=jnp.int32)[None, :]
            is_dead = ~page_has_real & do[:, None]  # [R, PP]
            if serving:
                is_dead = is_dead & (pgrid >= ready_pg[:, None])
            rows_tbl = state["table"][jnp.clip(slot, 0, SLOTS - 1)][:, :PP]
            # the freed pages are this refill's own fresh pops (never a
            # cache entry's), so the release is exact: refcount-free
            # push, or both stream holds dropped in spec-decode mode
            state = _free_slot_pages(
                state, rows_tbl.reshape(-1),
                (is_dead & (rows_tbl > 0)).reshape(-1),
            )
            reclaimed_now = (is_dead & (rows_tbl > 0)).sum().astype(jnp.int32)
            table = state["table"].at[slot[:, None], pgrid].set(
                jnp.where(is_dead, 0, rows_tbl), mode="drop"
            )
            state = dict(
                state, table=table,
                reclaimed=state["reclaimed"] + reclaimed_now,
            )

        logits0 = logit_projection(params)(h_last)
        keys0 = lane_keys(rng, _rng_ids(qc) * N)
        tok0 = sample_token_lanes(keys0, logits0, settings)
        bud = row_budget[qc]
        eos0 = tok0 == eos
        fin0 = eos0 | (bud <= 1)

        def upd(name, val):
            return state[name].at[slot].set(val, mode="drop")

        npad = P - mask.sum(axis=1).astype(jnp.int32)
        state = dict(
            state,
            pos=upd("pos", jnp.full((R,), P, jnp.int32)),
            npad=upd("npad", npad),
            new=upd("new", jnp.ones((R,), jnp.int32)),
            budget=upd("budget", bud),
            active=upd("active", ~fin0),
            pidx=upd("pidx", qc),
            cur=upd("cur", tok0),
            kmask=state["kmask"].at[slot].set(
                jnp.concatenate(
                    [mask, jnp.zeros((R, S - P), jnp.int32)], axis=1
                ),
                mode="drop",
            ),
            resp_ids=state["resp_ids"].at[qrow, 0].set(tok0, mode="drop"),
            resp_mask=state["resp_mask"].at[qrow, 0].set(1, mode="drop"),
            qnext=qnext + navail,
            refills=state["refills"] + navail,
            emitted=state["emitted"] + navail,
            truncated=state["truncated"]
            + (do & fin0 & ~eos0).sum().astype(jnp.int32),
        )
        # lanes that finish AT refill (instant EOS / budget 1) must
        # release their freshly-allocated pages immediately, or a fully
        # EOS-degenerate policy parks every page on idle lanes and the
        # refill gate (ntop >= PP) wedges the queue closed
        fin_lanes = (
            jnp.zeros((SLOTS,), bool).at[slot].set(do & fin0, mode="drop")
        )
        return _release_pages(state, fin_lanes)

    def _release_pages(state: Dict[str, Any], lanes: Array) -> Dict[str, Any]:
        """Return `lanes`' pages to the free stack the moment the lane
        finishes: a finished response's KV is dead, and reclaiming it
        immediately is what lets the refill gate (`ntop >= PP`) admit
        the next prompt without a separate scavenging pass.

        Serving mode: a PINNED lane (multi-turn session / a request
        adopted into the prefix cache) keeps every page — its final
        table row and KV length are saved for the host to adopt, and
        its page count lands in the ``pinned_pages`` stat (a pin is a
        normal finish: deliberately NOT counted as reclaimed or
        truncated). Unpinned lanes release through the refcounted path,
        so a shared prefix page only ever decrements down to the
        cache's own hold."""
        if not spec.paged:
            return state
        rows = state["table"]
        if serving:
            pidx = jnp.clip(state["pidx"], 0, Q - 1)
            pin = q_pin[pidx] & lanes
            wrow = jnp.where(pin, state["pidx"], Q)
            saved_tables = state["saved_tables"].at[wrow].set(
                rows, mode="drop"
            )
            saved_len = state["saved_len"].at[wrow].set(
                state["pos"], mode="drop"
            )
            pinned = state["pinned"] + (
                (rows > 0) & pin[:, None]
            ).sum().astype(jnp.int32)
            release = lanes & ~pin
            free, ntop, refcnt = paged_kv.release_refcounted(
                state["free"], state["ntop"], state["refcnt"],
                rows.reshape(-1), jnp.repeat(release, MP),
            )
            return dict(
                state, free=free, ntop=ntop, refcnt=refcnt,
                table=jnp.where(lanes[:, None], 0, rows),
                saved_tables=saved_tables, saved_len=saved_len,
                pinned=pinned,
            )
        state = _free_slot_pages(
            state, rows.reshape(-1), jnp.repeat(lanes, MP)
        )
        return dict(state, table=jnp.where(lanes[:, None], 0, rows))

    def _ensure_page(state: Dict[str, Any], position: Array) -> Dict[str, Any]:
        """Lazy response-page allocation for each active lane's write at
        `position` [SLOTS]; lanes the pool cannot serve are force-
        finished (counted as oom_truncated)."""
        if not spec.paged:
            return state
        active = state["active"]
        pi = jnp.clip(position // PS, 0, MP - 1)
        have = jnp.take_along_axis(state["table"], pi[:, None], axis=1)[:, 0]
        miss = active & (have == 0)
        got, free, ntop = paged_kv.pop_pages(state["free"], state["ntop"], miss)
        table = state["table"].at[
            jnp.arange(SLOTS), pi
        ].set(jnp.where(miss & (got > 0), got, have))
        starve = miss & (got == 0)
        state = _note_alloc(
            dict(
                state,
                free=free,
                ntop=ntop,
                table=table,
                active=active & ~starve,
                oom=state["oom"] + starve.sum().astype(jnp.int32),
                truncated=state["truncated"] + starve.sum().astype(jnp.int32),
            ),
            got,
        )
        return _release_pages(state, starve)

    def _decode_step(state: Dict[str, Any]) -> Dict[str, Any]:
        state = _ensure_page(state, state["pos"])
        active = state["active"]
        p = jnp.clip(state["pos"], 0, S - 1)
        km = state["kmask"].at[jnp.arange(SLOTS), p].max(active.astype(jnp.int32))
        cache = _paged_cache(state["pool"], dict(state, active=active), p, km)
        out = model(
            params,
            state["cur"][:, None],
            positions=jnp.maximum(p - state["npad"], 0)[:, None],
            cache=cache,
        )
        pool = {
            k: out["cache"][k]
            for k in ("pk", "pv", "pk_scale", "pv_scale")
            if k in out["cache"]
        }
        j = jnp.clip(state["new"], 0, N - 1)
        keys = lane_keys(rng, _rng_ids(state["pidx"]) * N + j)
        tok = sample_token_lanes(keys, out["logits"][:, -1], settings)
        eos_hit = tok == eos
        budget_hit = state["new"] + 1 >= state["budget"]
        fin = eos_hit | budget_hit
        wrow = jnp.where(active, state["pidx"], Q)
        na = active.sum().astype(jnp.int32)
        state = dict(
            state,
            pool=pool,
            kmask=km,
            resp_ids=state["resp_ids"].at[wrow, j].set(tok, mode="drop"),
            resp_mask=state["resp_mask"].at[wrow, j].set(1, mode="drop"),
            pos=state["pos"] + active,
            new=state["new"] + active,
            cur=jnp.where(active, tok, state["cur"]),
            active=active & ~fin,
            decode_steps=state["decode_steps"] + 1,
            lane_steps=state["lane_steps"] + na,
            emitted=state["emitted"] + na,
            truncated=state["truncated"]
            + (active & budget_hit & ~eos_hit).sum().astype(jnp.int32),
        )
        return _release_pages(state, active & fin)

    def _spec_round(state: Dict[str, Any]) -> Dict[str, Any]:
        # pages for the whole draft window [pos, pos+K)
        for j in range(K):
            state = _ensure_page(state, state["pos"] + j)
        active = state["active"]
        p = jnp.clip(state["pos"], 0, S - K)
        window = p[:, None] + jnp.arange(K, dtype=jnp.int32)[None, :]
        km = state["kmask"].at[
            jnp.arange(SLOTS)[:, None], window
        ].max(jnp.broadcast_to(active.astype(jnp.int32)[:, None], (SLOTS, K)))
        base_pos = jnp.maximum(p - state["npad"], 0)

        # -- draft: K single-token steps off the reference ---------------
        def dbody(carry, j):
            dpool, tok_in = carry
            cache = _paged_cache(
                dpool, dict(state, active=active), p + j, km, draft=True
            )
            out = model(
                draft_params, tok_in[:, None],
                positions=(base_pos + j)[:, None], cache=cache,
            )
            dpool = {
                k: out["cache"][k]
                for k in ("pk", "pv", "pk_scale", "pv_scale")
                if k in out["cache"]
            }
            ql = process_logits(out["logits"][:, -1], settings)
            keys = lane_keys(
                rng, _rng_ids(state["pidx"]) * N + state["new"] + j
            )
            if settings.do_sample:
                g = jax.vmap(lambda k2: jax.random.gumbel(k2, (ql.shape[-1],)))(
                    keys
                )
                x = jnp.argmax(ql + g, axis=-1).astype(jnp.int32)
            else:
                x = jnp.argmax(ql, axis=-1).astype(jnp.int32)
            return (dpool, x), (x, jax.nn.softmax(ql, axis=-1))

        (dpool, _), (xs, qprobs) = jax.lax.scan(
            dbody, (_draft_pool(state), state["cur"]),
            jnp.arange(K, dtype=jnp.int32),
        )
        xs = xs.transpose(1, 0)  # [SLOTS, K]

        # -- verify: ONE policy forward over the k drafted inputs --------
        # Trunk sharing: the verify runs on the POST-draft pool (the
        # draft just wrote its branch KV into the extension layers —
        # and its trunk writes, which the verify's own update-carry-
        # first scatter overwrites with the identical values).
        ver_in = jnp.concatenate([state["cur"][:, None], xs[:, : K - 1]], axis=1)
        ver_pool = dpool if shared else state["pool"]
        cache = _paged_cache(ver_pool, dict(state, active=active), p, km)
        out = model(
            params, ver_in,
            positions=base_pos[:, None] + jnp.arange(K, dtype=jnp.int32)[None, :],
            cache=cache,
        )
        pool = {
            k: out["cache"][k]
            for k in ("pk", "pv", "pk_scale", "pv_scale")
            if k in out["cache"]
        }
        pl_ = process_logits(out["logits"], settings)  # [SLOTS, K, V]
        pprobs = jax.nn.softmax(pl_, axis=-1)

        # -- rejection sampling (exact: accepted + residual draws leave
        # the marginal of every emitted token the POLICY's) -------------
        still = active
        fin = jnp.zeros((SLOTS,), bool)
        m = jnp.zeros((SLOTS,), jnp.int32)
        last = state["cur"]
        resp_ids, resp_mask = state["resp_ids"], state["resp_mask"]
        truncated = state["truncated"]
        drafted = state["drafted"]
        accepted = state["accepted"]
        emitted = state["emitted"]
        for j in range(K):
            xj = xs[:, j]
            pj = pprobs[:, j]
            qj = qprobs[j]
            if settings.do_sample:
                ukeys = lane_keys(
                    rng,
                    OFF_ACC + _rng_ids(state["pidx"]) * N + state["new"] + j,
                )
                u = jax.vmap(lambda k2: jax.random.uniform(k2, ()))(ukeys)
                px = jnp.take_along_axis(pj, xj[:, None], axis=1)[:, 0]
                qx = jnp.take_along_axis(qj, xj[:, None], axis=1)[:, 0]
                acc = u * qx <= px
                res = jnp.maximum(pj - qj, 0.0)
                rs = res.sum(axis=-1, keepdims=True)
                res = jnp.where(rs > 1e-12, res / jnp.maximum(rs, 1e-30), pj)
                rkeys = lane_keys(
                    rng,
                    OFF_RES + _rng_ids(state["pidx"]) * N + state["new"] + j,
                )
                tok_rej = categorical_lanes(rkeys, res)
            else:
                am = jnp.argmax(pj, axis=-1).astype(jnp.int32)
                acc = xj == am
                tok_rej = am
            tok = jnp.where(acc, xj, tok_rej)
            emit = still
            wrow = jnp.where(emit, state["pidx"], Q)
            wcol = jnp.clip(state["new"] + j, 0, N - 1)
            resp_ids = resp_ids.at[wrow, wcol].set(tok, mode="drop")
            resp_mask = resp_mask.at[wrow, wcol].set(1, mode="drop")
            eos_hit = tok == eos
            budget_hit = state["new"] + j + 1 >= state["budget"]
            fin_now = emit & (eos_hit | budget_hit)
            m = m + emit
            last = jnp.where(emit, tok, last)
            truncated = truncated + (fin_now & ~eos_hit).sum().astype(jnp.int32)
            drafted = drafted + emit.sum().astype(jnp.int32)
            accepted = accepted + (emit & acc).sum().astype(jnp.int32)
            emitted = emitted + emit.sum().astype(jnp.int32)
            still = still & acc & ~fin_now
            fin = fin | fin_now

        # kmask in the draft window becomes "consumed inputs only": the
        # first m positions stay attendable (their KV is final), stale
        # slots from rejected/over-budget drafts are cleared. Window
        # positions all sit at >= pos, so inactive lanes' real bits are
        # untouched (their window bits were never set).
        keep = (
            jnp.arange(K, dtype=jnp.int32)[None, :] < m[:, None]
        ) & active[:, None]
        km = km.at[jnp.arange(SLOTS)[:, None], window].set(
            keep.astype(jnp.int32)
        )
        # shared mode: `pool` (the verify output) already carries the
        # draft's branch-layer writes — there is no second buffer
        state = dict(
            state,
            pool=pool,
            **({} if shared else {"dpool": dpool}),
            kmask=km,
            resp_ids=resp_ids,
            resp_mask=resp_mask,
            pos=state["pos"] + m,
            new=state["new"] + m,
            cur=last,
            active=active & ~fin,
            decode_steps=state["decode_steps"] + K + 1,
            lane_steps=state["lane_steps"] + (K + 1) * active.sum().astype(jnp.int32),
            rounds=state["rounds"] + 1,
            emitted=emitted,
            truncated=truncated,
            drafted=drafted,
            accepted=accepted,
        )
        return _release_pages(state, active & fin)

    step_fn = _spec_round if spec.spec_decode else _decode_step

    def cond(state):
        can_refill = (~state["active"]).any() & (state["qnext"] < Q)
        if spec.paged:
            can_refill = can_refill & (state["ntop"] >= PP)
        return state["active"].any() | can_refill

    def body(state):
        need = (~state["active"]).any() & (state["qnext"] < Q)
        if spec.paged:
            need = need & (state["ntop"] >= PP)
        state = jax.lax.cond(need, _refill, lambda s: s, state)
        state = jax.lax.cond(
            state["active"].any(), step_fn, lambda s: s, state
        )
        return state

    final = jax.lax.while_loop(cond, body, _init_state())

    resp_ids = jnp.where(final["resp_mask"] > 0, final["resp_ids"], pad)
    steps_f = jnp.maximum(final["decode_steps"].astype(jnp.float32), 1.0)
    stats = {
        "decode_steps": final["decode_steps"],
        "refills": final["refills"],
        "real_tokens": final["emitted"],
        "occupancy": final["lane_steps"].astype(jnp.float32)
        / (steps_f * SLOTS),
        "truncated": final["truncated"],
        "oom_truncated": final["oom"],
        "reclaimed_pages": final["reclaimed"],
        "unserved": Q - final["qnext"],
    }
    if spec.paged:
        # end-of-call free-stack depth: with every lane finished this
        # must equal pool - 1 (the null page) — the `free + held ==
        # pool` balance the spec-decode accounting tests pin
        stats["free_pages"] = final["ntop"]
    if refcounted:
        # pages still refcount-held at exit (0 after a drained chunk):
        # free_pages + held_pages + 1 null page == pool, always
        stats["held_pages"] = (final["refcnt"] > 0).sum().astype(jnp.int32)
    if spec.spec_decode:
        stats.update(
            spec_rounds=final["rounds"],
            drafted=final["drafted"],
            accepted=final["accepted"],
        )
    out = {
        "sequences": jnp.concatenate([q_ids, resp_ids], axis=1),
        "response_ids": resp_ids,
        "response_mask": final["resp_mask"],
        "gen_stats": stats,
    }
    if serving:
        stats["pinned_pages"] = final["pinned"]
        # the persistent pool state the serving host carries into the
        # next call (plus per-row pin adoptions)
        out["kv_state"] = {
            "pool": final["pool"],
            "free": final["free"],
            "ntop": final["ntop"],
            "refcnt": final["refcnt"],
            "saved_tables": final["saved_tables"],
            "saved_len": final["saved_len"],
        }
    return out


def engine_generate_grouped(
    model: TransformerLM,
    params: Dict,
    q_ids: Array,  # [Q, P]
    q_mask: Array,  # [Q, P]
    rng: jax.Array,
    settings: SamplerSettings,
    spec: EngineSpec,
    draft_params: Optional[Dict] = None,
    row_budget: Optional[Array] = None,
    group_sharding=None,
) -> Dict[str, Array]:
    """Run the engine as ``spec.data_groups`` INDEPENDENT lane groups.

    The queue splits into G contiguous shards; each shard gets its own
    full engine instance — slots, page pool, page table, free stack —
    and all G run as ONE stacked dispatch (`jax.vmap` over the group
    axis). With ``group_sharding`` (a `NamedSharding` whose axis 0 spec
    names mesh data axes) the stacked queue is sharding-constrained so
    GSPMD places each group's engine state — pools, tables, slot lanes
    — on that group's device slice: the engine's control flow stays one
    program, but its memory and per-step compute shard over the mesh
    instead of replicating (multi-chip rollout workers / serve
    frontends, ROADMAP item 3's second half).

    Output equivalence is structural: RNG ids are the GLOBAL queue row
    (``q_rng_row``) and the acceptance/residual offsets use the global
    id space (``rng_space``), so greedy output is token-for-token the
    single-group engine's, and sampled streams are the same draws. A
    queue not divisible by G is padded with dummy rows (one real token,
    budget 1 — the serving tier's padding trick); their emissions are
    trimmed from the outputs and subtracted from the stats.
    """
    G = spec.data_groups
    if G <= 1:
        return engine_generate(
            model, params, q_ids, q_mask, rng, settings, spec,
            draft_params=draft_params, row_budget=row_budget,
        )
    Q, P = q_ids.shape
    N = settings.max_new_tokens
    Qg = -(-Q // G)
    npad = G * Qg - Q
    q_ids = q_ids.astype(jnp.int32)
    q_mask = q_mask.astype(jnp.int32)
    if row_budget is None:
        row_budget = jnp.full((Q,), N, jnp.int32)
    row_budget = jnp.clip(row_budget.astype(jnp.int32), 1, N)
    if npad:
        pad_ids = jnp.full(
            (npad, P), settings.pad_token_id, jnp.int32
        ).at[:, -1].set(0)
        pad_mask = jnp.zeros((npad, P), jnp.int32).at[:, -1].set(1)
        q_ids = jnp.concatenate([q_ids, pad_ids])
        q_mask = jnp.concatenate([q_mask, pad_mask])
        row_budget = jnp.concatenate(
            [row_budget, jnp.ones((npad,), jnp.int32)]
        )
    rng_rows = jnp.arange(G * Qg, dtype=jnp.int32)

    def split(x):
        return x.reshape((G, Qg) + x.shape[1:])

    gq_ids, gq_mask = split(q_ids), split(q_mask)
    g_budget, g_rows = split(row_budget), split(rng_rows)
    if group_sharding is not None:
        gq_ids = jax.lax.with_sharding_constraint(gq_ids, group_sharding)
        gq_mask = jax.lax.with_sharding_constraint(gq_mask, group_sharding)
    # an EXPLICIT pool_pages is the TOTAL page budget (same meaning as
    # the single-group run): each group gets its ceil(1/G) share. Note
    # the one caveat this implies: under a DELIBERATELY undersized
    # budget, which lanes oom-truncate can differ from the single-group
    # run (allocation is per-group, not global) — the token-for-token
    # guarantee is for pools that don't starve, and the default
    # worst-case sizing (pool_pages=0) never starves.
    sub = dataclasses.replace(
        spec, data_groups=1,
        pool_pages=-(-spec.pool_pages // G) if spec.pool_pages else 0,
    )
    SLOTS = max(1, min(sub.slots, Qg))

    def one_group(ids, mask, budget, rows):
        return engine_generate(
            model, params, ids, mask, rng, settings, sub,
            draft_params=draft_params, row_budget=budget,
            q_rng_row=rows, rng_space=Q,
        )

    out = jax.vmap(one_group)(gq_ids, gq_mask, g_budget, g_rows)
    merged = {
        k: out[k].reshape((G * Qg,) + out[k].shape[2:])[:Q]
        for k in ("sequences", "response_ids", "response_mask")
    }
    g = out["gen_stats"]  # every stat is [G]
    steps = g["decode_steps"].sum()
    lane_steps = g["occupancy"] * g["decode_steps"].astype(jnp.float32) * SLOTS
    stats: Dict[str, Array] = {
        "decode_steps": steps,
        "refills": g["refills"].sum(),
        "real_tokens": g["real_tokens"].sum(),
        "occupancy": lane_steps.sum()
        / jnp.maximum(steps.astype(jnp.float32) * SLOTS, 1.0),
        "truncated": g["truncated"].sum(),
        "oom_truncated": g["oom_truncated"].sum(),
        "reclaimed_pages": g["reclaimed_pages"].sum(),
        "unserved": g["unserved"].sum(),
    }
    for k in ("free_pages", "held_pages", "spec_rounds", "drafted", "accepted"):
        if k in g:
            stats[k] = g[k].sum()
    if npad:
        # dummy-row corrections: each pad row emits exactly its single
        # budgeted token through one refill, and counts truncated
        # unless that token happened to be EOS
        dummy_tok = out["response_ids"].reshape(G * Qg, -1)[Q:, 0]
        dummy_eos = (dummy_tok == jnp.int32(settings.eos_token_id)).sum(
            dtype=jnp.int32
        )
        stats["real_tokens"] = stats["real_tokens"] - npad
        stats["refills"] = stats["refills"] - npad
        stats["truncated"] = stats["truncated"] - (npad - dummy_eos)
    merged["gen_stats"] = stats
    return merged


def make_engine_fn(
    model: TransformerLM,
    settings: SamplerSettings,
    spec: EngineSpec,
):
    """Jitted engine entry: `(params[, draft_params], q_ids, q_mask,
    rng[, row_budget]) -> outputs`. One executable per (Q, P) shape.
    Routes through the grouped wrapper when the spec asks for sharded
    lane groups (`data_groups > 1`)."""
    run = (
        engine_generate_grouped if spec.data_groups > 1 else engine_generate
    )
    if spec.spec_decode:

        @partial(jax.jit, static_argnums=())
        def fn(params, draft_params, q_ids, q_mask, rng, row_budget=None):
            return run(
                model, params, q_ids, q_mask, rng, settings, spec,
                draft_params=draft_params, row_budget=row_budget,
            )

        return fn

    @partial(jax.jit, static_argnums=())
    def fn(params, q_ids, q_mask, rng, row_budget=None):
        return run(
            model, params, q_ids, q_mask, rng, settings, spec,
            row_budget=row_budget,
        )

    return fn
