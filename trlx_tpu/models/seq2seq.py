"""TPU-native encoder-decoder (T5-family) model.

Parity: the reference's seq2seq support — value-head wrappers
(/root/reference/trlx/models/modeling_ppo.py:1242-1480), the frozen `T5Branch`
(modeling_ppo.py:1483-1592) and ILQL seq2seq (modeling_ilql.py:481-666) all
wrap HF T5. Here the model itself is first-party: one functional
encoder/decoder with scan-stacked layers, mirroring
trlx_tpu.models.transformer's design (static shapes, explicit param
trees, KV-cache decode, branch capture for the hydra reference).

T5 specifics honored: RMS layer norm without bias, no attention scaling
(folded into init), relative position bias shared across layers (a
single [n_buckets, n_head] table per stack), optional gated-GELU MLP
(v1.1), logits scaled by d_model^-0.5 when embeddings are tied.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from trlx_tpu.models.transformer import NEG_INF, QDense

Array = jnp.ndarray


@dataclass(frozen=True)
class Seq2SeqConfig:
    vocab_size: int
    d_model: int
    n_layer: int  # encoder layers
    n_decoder_layer: Optional[int] = None  # default n_layer
    n_head: int = 8
    d_kv: int = 64
    d_ff: int = 2048
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    activation: str = "relu"  # "relu" | "gated-gelu"
    tie_word_embeddings: bool = True
    decoder_start_token_id: int = 0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # "xla" | "pallas": fused flash kernels for teacher-forced encoder
    # and decoder self-attention (ops/flash_attention.flash_attention_bias
    # — carries the learned relative position bias with a proper dbias
    # backward) and for cross-attention (padding-mask-only kernel).
    # Decode steps (KV cache) and shapes not divisible by 128 fall back
    # to XLA. The per-layer [B, H, T, S] score tensor never materializes
    # on this path — long-context summarization training's memory win.
    attention_impl: str = "xla"
    # None | "int8": generate_seq2seq rewrites the DECODER block kernels
    # to int8 + per-output-channel scales (QDense) for the decode loop —
    # the decoder weights are the stream every step re-reads, while the
    # encoder runs once per sample at full precision. Same contract as
    # TransformerConfig.decode_weights_quant.
    decode_weights_quant: Optional[str] = None
    # pipeline parallelism: microbatches per pipelined stack when the
    # mesh has a pp axis > 1 (0 = one per stage); raise to shrink the
    # (pp-1)/(M+pp-1) bubble — mirrors TransformerConfig.pp_microbatches
    pp_microbatches: int = 0
    pp_schedule: str = "gpipe"  # mirrors TransformerConfig.pp_schedule

    def __post_init__(self):
        if self.n_decoder_layer is None:
            object.__setattr__(self, "n_decoder_layer", self.n_layer)

    def replace(self, **kw) -> "Seq2SeqConfig":
        return dataclasses.replace(self, **kw)


def relative_position_bucket(
    relative_position: Array, bidirectional: bool, num_buckets: int, max_distance: int
) -> Array:
    """T5's log-binned relative position bucketing."""
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


def compute_position_bias(
    rel_bias_table: Array,  # [n_buckets, n_head]
    q_pos: Array,  # [T]
    k_pos: Array,  # [S]
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> Array:
    """[1, n_head, T, S] additive attention bias.

    The gather is head-major ([H, T, S] directly, NOT [T, S, H] then
    transpose): a [T*S, H] intermediate has an H-wide minor dim that the
    TPU lane layout pads to 128 — 16x inflation, a 34 GB allocation at
    8k/8-head where the real tensor is 2 GB."""
    rel = k_pos[None, :] - q_pos[:, None]  # [T, S]
    buckets = relative_position_bucket(rel, bidirectional, num_buckets, max_distance)
    bias = jnp.take(rel_bias_table.transpose(1, 0), buckets, axis=1)  # [H, T, S]
    return bias[None].astype(jnp.float32)


def compute_position_bias_dense(
    rel_bias_table: Array,  # [n_buckets, n_head]
    T: int,
    S: int,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> Array:
    """[1, n_head, T, S] bias for CONSECUTIVE positions (arange(T) vs
    arange(S)) — every teacher-forced stack call.

    Exploits the Toeplitz structure (the bias depends only on s - t):
    bucket and gather a tiny [H, T+S-1] relative vector, then expand to
    [H, T, S] with vmapped dynamic slices. A direct [T, S]-indexed
    gather (and its scatter-add transpose for the trainable table's
    gradient) lowers to a [T*S, H]-shaped buffer whose 8-wide minor dim
    the TPU lane layout pads 16x — a 34 GB allocation at 8k tokens
    (measured); this construction never builds a lane-padded buffer in
    either direction."""
    R = T + S - 1
    rel_vec = jnp.arange(R) - (T - 1)  # s - t for each diagonal
    buckets = relative_position_bucket(
        rel_vec, bidirectional, num_buckets, max_distance
    )
    bias_rel = jnp.take(
        rel_bias_table.transpose(1, 0), buckets, axis=1
    )  # [H, R]

    def row(t):
        return jax.lax.dynamic_slice_in_dim(bias_rel, (T - 1) - t, S, axis=1)

    bias = jax.vmap(row)(jnp.arange(T)).transpose(1, 0, 2)  # [H, T, S]
    return bias[None].astype(jnp.float32)


class T5Norm(nn.Module):
    cfg: Seq2SeqConfig

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x32 = x.astype(jnp.float32)
        scale = self.param(
            "scale", nn.initializers.ones, (self.cfg.d_model,), self.cfg.param_dtype
        )
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        return (x32 * jax.lax.rsqrt(var + self.cfg.layer_norm_epsilon) * scale).astype(
            x.dtype
        )


class T5Attention(nn.Module):
    cfg: Seq2SeqConfig

    @nn.compact
    def __call__(
        self,
        x: Array,  # [B, T, D] queries
        kv: Array,  # [B, S, D] keys/values source
        bias: Optional[Array],  # [B or 1, H, T, S] additive — None takes
        # the fused pallas path (the caller gated shapes) with the
        # structured pieces below instead
        cache: Optional[Dict[str, Array]] = None,
        pos_bias: Optional[Array] = None,  # [1, H, T, S] learned rel bias
        # (rank-4 with a leading broadcast dim so pipeline-parallel ctx
        # splitting never mistakes the head axis for a batch axis)
        key_mask: Optional[Array] = None,  # [B, S] 1 = attendable
        causal: bool = False,
    ) -> Tuple[Array, Optional[Dict[str, Array]]]:
        cfg = self.cfg
        H, Dk = cfg.n_head, cfg.d_kv
        dense = partial(
            QDense,
            axis=-1,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            use_bias=False,
            kernel_init=nn.initializers.normal(cfg.d_model**-0.5),
        )
        q = dense(features=(H, Dk), name="q")(x)
        k = dense(features=(H, Dk), name="k")(kv)
        v = dense(features=(H, Dk), name="v")(kv)

        new_kv = None
        if cache is not None:
            # update-carry-first, same as the causal stack (rationale and
            # measured design history in TransformerLM Attention): write
            # this layer's new column into the scan-carried stacked
            # buffer, then attend against a slice of the UPDATED buffer —
            # one full cache read + one column write per step, no
            # per-layer updated-row copy
            idx = cache["index"]
            ix = cache["ix"]
            ck = jax.lax.dynamic_update_slice(
                cache["ck"], k[None].astype(cache["ck"].dtype), (ix, 0, idx, 0, 0)
            )
            cv = jax.lax.dynamic_update_slice(
                cache["cv"], v[None].astype(cache["cv"].dtype), (ix, 0, idx, 0, 0)
            )
            new_kv = {"ck": ck, "cv": cv}
            k = jax.lax.dynamic_index_in_dim(ck, ix, 0, keepdims=False).astype(cfg.dtype)
            v = jax.lax.dynamic_index_in_dim(cv, ix, 0, keepdims=False).astype(cfg.dtype)

        if bias is None:
            # fused path (NOTE: T5 has no 1/sqrt(d) — sm_scale=1.0):
            # self-attention carries the learned rel bias through
            # flash_attention_bias (dbias flows back to the table);
            # cross-attention has padding masking only, so the plain
            # kernel serves it
            from trlx_tpu.ops.flash_attention import (
                flash_attention,
                flash_attention_bias,
            )

            qT = q.transpose(0, 2, 1, 3)
            kT = k.transpose(0, 2, 1, 3)
            vT = v.transpose(0, 2, 1, 3)
            if pos_bias is not None:
                out = flash_attention_bias(
                    qT, kT, vT, key_mask, pos_bias[0], causal=causal,
                    sm_scale=1.0,
                )
            else:
                out = flash_attention(
                    qT, kT, vT, key_mask, causal=False, sm_scale=1.0
                )
            out = out.transpose(0, 2, 1, 3).astype(cfg.dtype)
        else:
            scores = jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32)
            scores = scores + bias
            probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bhts,bshd->bthd", probs, v)
        proj = QDense(
            features=cfg.d_model,
            axis=(-2, -1),
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            use_bias=False,
            kernel_init=nn.initializers.normal((H * Dk) ** -0.5),
            name="o",
        )
        return proj(out), new_kv


class T5MLP(nn.Module):
    cfg: Seq2SeqConfig

    @nn.compact
    def __call__(self, x: Array) -> Array:
        cfg = self.cfg
        dense = partial(
            QDense,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            use_bias=False,
            kernel_init=nn.initializers.normal(cfg.d_model**-0.5),
        )
        if cfg.activation == "gated-gelu":
            h = jax.nn.gelu(dense(features=cfg.d_ff, name="fc_in")(x), approximate=True)
            h = h * dense(features=cfg.d_ff, name="fc_gate")(x)
        else:
            h = jax.nn.relu(dense(features=cfg.d_ff, name="fc_in")(x))
        return dense(features=cfg.d_model, name="fc_out",
                     kernel_init=nn.initializers.normal(cfg.d_ff**-0.5))(h)


class T5Block(nn.Module):
    cfg: Seq2SeqConfig
    is_decoder: bool

    @nn.compact
    def __call__(
        self,
        x: Array,
        self_bias: Optional[Array],
        enc_out: Optional[Array] = None,
        cross_bias: Optional[Array] = None,
        pos_bias: Optional[Array] = None,  # pallas path (self_bias None)
        skey_mask: Optional[Array] = None,
        ckey_mask: Optional[Array] = None,
        cache: Optional[Dict[str, Array]] = None,
    ) -> Tuple[Array, Optional[Dict[str, Array]]]:
        cfg = self.cfg
        h = T5Norm(cfg, name="ln_1")(x)
        attn_out, new_kv = T5Attention(cfg, name="self_attn")(
            h, h, self_bias, cache, pos_bias=pos_bias, key_mask=skey_mask,
            causal=self.is_decoder,
        )
        x = x + attn_out
        if self.is_decoder and enc_out is not None:
            h = T5Norm(cfg, name="ln_cross")(x)
            cross_out, _ = T5Attention(cfg, name="cross_attn")(
                h, enc_out, cross_bias, key_mask=ckey_mask
            )
            x = x + cross_out
        x = x + T5MLP(cfg, name="mlp")(T5Norm(cfg, name="ln_2")(x))
        return x, new_kv


class T5LM:
    """Functional encoder-decoder LM with stacked-layer scan stacks.

    params:
      shared:  {wte [V, D]}
      encoder: {blocks (stacked), ln_f, rel_bias [n_buckets, H]}
      decoder: {blocks (stacked), ln_f, rel_bias [n_buckets, H]}
      [lm_head: {kernel [D, V]}]
    """

    def __init__(self, cfg: Seq2SeqConfig):
        self.cfg = cfg
        self.enc_block = T5Block(cfg, is_decoder=False)
        self.dec_block = T5Block(cfg, is_decoder=True)
        self.norm = T5Norm(cfg)
        # set by the trainer when the mesh has a pp axis > 1: encoder and
        # decoder stacks pipeline over it (parallel/pipeline.py); decode
        # steps (cache path) stay sequential
        self.mesh = None

    # -- init ------------------------------------------------------------

    def init(self, rng: jax.Array) -> Dict:
        cfg = self.cfg
        B, T = 1, 4
        x = jnp.zeros((B, T, cfg.d_model), cfg.dtype)
        bias = jnp.zeros((1, cfg.n_head, T, T), jnp.float32)
        keys = jax.random.split(rng, 6)

        enc_blocks = jax.vmap(lambda k: self.enc_block.init(k, x, bias)["params"])(
            jax.random.split(keys[0], cfg.n_layer)
        )
        dec_blocks = jax.vmap(
            lambda k: self.dec_block.init(k, x, bias, x, bias)["params"]
        )(jax.random.split(keys[1], cfg.n_decoder_layer))

        n_b = cfg.relative_attention_num_buckets
        params = {
            "shared": {
                "wte": jax.random.normal(keys[2], (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
                * 1.0
            },
            "encoder": {
                "blocks": enc_blocks,
                "ln_f": self.norm.init(keys[3], x)["params"],
                "rel_bias": jax.random.normal(keys[4], (n_b, cfg.n_head), cfg.param_dtype) * 0.1,
            },
            "decoder": {
                "blocks": dec_blocks,
                "ln_f": self.norm.init(keys[3], x)["params"],
                "rel_bias": jax.random.normal(keys[5], (n_b, cfg.n_head), cfg.param_dtype) * 0.1,
            },
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {
                "kernel": jax.random.normal(keys[4], (cfg.d_model, cfg.vocab_size), cfg.param_dtype)
                * cfg.d_model**-0.5
            }
        return params

    # -- helpers ---------------------------------------------------------

    def _embed(self, params: Dict, ids: Array) -> Array:
        return jnp.take(params["shared"]["wte"], ids, axis=0).astype(self.cfg.dtype)

    def _scan(self, block: nn.Module, stacked: Dict, h: Array, *args, cache=None,
              remat=False):
        """Cache path mirrors TransformerLM._scan_blocks: the [L, ...]
        cache buffers are CARRIED and each layer's attention writes its
        new column in place then attends against a slice of the updated
        buffer (update-carry-first; design history in TransformerLM
        Attention)."""
        def body(carry, layer):
            if cache is not None:
                hidden, ck, cv = carry
                lp, ix = layer
                layer_cache = {
                    "ck": ck,
                    "cv": cv,
                    "ix": ix,
                    "index": cache["index"],
                }
            else:
                hidden, lp, layer_cache = carry, layer, None
            out, new_kv = block.apply({"params": lp}, hidden, *args, cache=layer_cache)
            if cache is not None:
                return (out, new_kv["ck"], new_kv["cv"]), None
            return out, None

        if cache is None:
            from trlx_tpu.ops.remat import wrap_remat

            body = wrap_remat(body, remat)
            h, _ = jax.lax.scan(body, h, stacked)
            return h, None
        n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        (h, ck, cv), _ = jax.lax.scan(
            body, (h, cache["k"], cache["v"]), (stacked, jnp.arange(n))
        )
        return h, dict(k=ck, v=cv, index=cache["index"] + 1)

    def _pp_microbatches(self, n_layer: int, batch: int) -> int:
        """Microbatch count for a pipelined stack, or 0 for the
        sequential scan — same shared gate as TransformerLM
        (parallel.pipeline.pp_microbatch_count)."""
        from trlx_tpu.parallel.pipeline import pp_microbatch_count

        return pp_microbatch_count(
            self.mesh, n_layer, batch, self.cfg.pp_microbatches
        )

    def _pp_scan(
        self,
        block: nn.Module,
        stacked: Dict,
        h: Array,
        args: tuple,
        n_microbatch: int,
        capture_points: tuple = (),
        remat=False,
    ):
        """Pipelined counterpart of `_scan` for teacher-forced stacks:
        `args` (biases / encoder hidden) ride as per-microbatch ctx."""
        from trlx_tpu.parallel.pipeline import pipelined_layers

        def layer_apply(layer, h, ctx_mb):
            out, _ = block.apply({"params": layer["p"]}, h, *ctx_mb, cache=None)
            return out

        return pipelined_layers(
            self.mesh,
            layer_apply,
            {"p": stacked},
            h,
            tuple(args),
            n_microbatch=n_microbatch,
            capture_points=capture_points,
            remat=remat,
            schedule=self.cfg.pp_schedule,
        )

    def _logits(self, params: Dict, hidden: Array) -> Array:
        if "lm_head" in params:
            kernel = params["lm_head"]["kernel"]
        else:
            kernel = params["shared"]["wte"].T
            hidden = hidden * (self.cfg.d_model**-0.5)  # tied-embedding scale
        return jnp.einsum(
            "btd,dv->btv", hidden, kernel.astype(hidden.dtype),
            preferred_element_type=jnp.float32,
        )

    # -- forward ---------------------------------------------------------

    def _pallas_ok(self, *seq_dims) -> bool:
        """Static gate for the fused-attention path: teacher-forced
        shapes with 128-divisible sequence dims (Mosaic lane/DMA
        alignment); decode steps (cache) never come through here."""
        return self.cfg.attention_impl == "pallas" and all(
            d % 128 == 0 for d in seq_dims
        )

    def _self_attn_args(self, params, stack: str, T: int, key_mask, causal,
                        use_pallas: bool):
        """(self_bias, pos_bias, skey_mask) for a self-attention stack:
        the combined additive [.., T, T] bias on the XLA path, or the
        structured (learned bias, padding mask) pieces on the pallas one
        — where the combined tensor is exactly what must NOT be built."""
        cfg = self.cfg
        pos = jnp.arange(T)
        pb = compute_position_bias_dense(
            params[stack]["rel_bias"], T, T, not causal,
            cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance,
        )  # [1, H, T, T]
        if use_pallas:
            return None, pb, key_mask
        bias = pb
        if causal:
            causal_ok = pos[:, None] >= pos[None, :]
            bias = bias + jnp.where(causal_ok[None, None], 0.0, NEG_INF)
        if key_mask is not None:
            bias = bias + jnp.where(key_mask[:, None, None, :] > 0, 0.0, NEG_INF)
        return bias, None, None

    def _decoder_args(self, params, B, T, S_enc, decoder_attention_mask,
                      attention_mask, encoder_hidden):
        """The decoder stacks' shared 6-tuple of block args (combined
        biases on the XLA path; structured pos-bias/key-mask pieces on
        the pallas one) — one place, so the teacher-forced and
        hydra-capture paths cannot diverge."""
        use_pallas = self._pallas_ok(T, S_enc)
        self_bias, pos_bias, skey_mask = self._self_attn_args(
            params, "decoder", T, decoder_attention_mask, causal=True,
            use_pallas=use_pallas,
        )
        if use_pallas and skey_mask is None:
            skey_mask = jnp.ones((B, T), jnp.int32)
        if use_pallas:
            cross_bias, ckey_mask = None, attention_mask
        else:
            cross_bias = jnp.where(
                attention_mask[:, None, None, :] > 0, 0.0, NEG_INF
            )
            ckey_mask = None
        return (self_bias, encoder_hidden, cross_bias, pos_bias, skey_mask,
                ckey_mask)

    def encode(self, params: Dict, input_ids: Array, attention_mask: Array,
               remat=False) -> Array:
        cfg = self.cfg
        T = input_ids.shape[1]
        use_pallas = self._pallas_ok(T)
        self_bias, pos_bias, skey_mask = self._self_attn_args(
            params, "encoder", T, attention_mask, causal=False,
            use_pallas=use_pallas,
        )
        args = (self_bias, None, None, pos_bias, skey_mask, None)
        h = self._embed(params, input_ids)
        n_mb = self._pp_microbatches(cfg.n_layer, h.shape[0])
        if n_mb:
            h, _ = self._pp_scan(
                self.enc_block, params["encoder"]["blocks"], h, args, n_mb,
                remat=remat,
            )
        else:
            h, _ = self._scan(self.enc_block, params["encoder"]["blocks"], h,
                              *args, remat=remat)
        return self.norm.apply({"params": params["encoder"]["ln_f"]}, h)

    def __call__(
        self,
        params: Dict,
        input_ids: Array,  # [B, S_enc]
        attention_mask: Array,  # [B, S_enc]
        decoder_input_ids: Array,  # [B, T]
        decoder_attention_mask: Optional[Array] = None,
        encoder_hidden: Optional[Array] = None,
        remat: bool = False,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        """Teacher-forced forward. `encoder_hidden` may be reused across
        calls (e.g. computed once during rollout generation)."""
        cfg = self.cfg
        if encoder_hidden is None:
            encoder_hidden = self.encode(params, input_ids, attention_mask,
                                         remat=remat)
        B, T = decoder_input_ids.shape
        args = self._decoder_args(
            params, B, T, encoder_hidden.shape[1], decoder_attention_mask,
            attention_mask, encoder_hidden,
        )

        h = self._embed(params, decoder_input_ids)
        n_mb = self._pp_microbatches(cfg.n_decoder_layer, B)
        if n_mb:
            h, _ = self._pp_scan(
                self.dec_block, params["decoder"]["blocks"], h,
                args, n_mb, remat=remat,
            )
        else:
            h, _ = self._scan(
                self.dec_block, params["decoder"]["blocks"], h, *args,
                remat=remat,
            )
        hidden = self.norm.apply({"params": params["decoder"]["ln_f"]}, h)
        return {
            "logits": self._logits(params, hidden) if compute_logits else None,
            "hidden_states": hidden,
            "encoder_hidden": encoder_hidden,
        }

    # -- hydra support ---------------------------------------------------

    def forward_with_branch_capture(
        self,
        params: Dict,
        input_ids: Array,
        attention_mask: Array,
        decoder_input_ids: Array,
        decoder_attention_mask: Optional[Array],
        branch_at: int,
        remat=False,
        compute_logits: bool = True,
    ) -> Dict[str, Array]:
        """Teacher-forced forward that also returns the decoder hidden
        state entering layer `branch_at` plus the biases needed to re-run
        the top branch (parity: the reference's frozen `T5Branch`,
        modeling_ppo.py:1483-1592, which re-runs top decoder blocks)."""
        cfg = self.cfg
        encoder_hidden = self.encode(params, input_ids, attention_mask,
                                     remat=remat)
        B, T = decoder_input_ids.shape
        args = self._decoder_args(
            params, B, T, encoder_hidden.shape[1], decoder_attention_mask,
            attention_mask, encoder_hidden,
        )
        (self_bias, _, cross_bias, pos_bias, skey_mask, ckey_mask) = args

        h = self._embed(params, decoder_input_ids)
        n_mb = self._pp_microbatches(cfg.n_decoder_layer, B)
        if n_mb:
            h_top, (h_branch,) = self._pp_scan(
                self.dec_block, params["decoder"]["blocks"], h,
                args, n_mb, capture_points=(branch_at,), remat=remat,
            )
        else:
            bottom = jax.tree_util.tree_map(
                lambda x: x[:branch_at], params["decoder"]["blocks"]
            )
            top = jax.tree_util.tree_map(
                lambda x: x[branch_at:], params["decoder"]["blocks"]
            )
            h_branch, _ = self._scan(
                self.dec_block, bottom, h, *args, remat=remat,
            )
            h_top, _ = self._scan(
                self.dec_block, top, h_branch, *args, remat=remat,
            )
        hidden = self.norm.apply({"params": params["decoder"]["ln_f"]}, h_top)
        return {
            "logits": self._logits(params, hidden) if compute_logits else None,
            "hidden_states": hidden,
            "branch_hidden": h_branch,
            "self_bias": self_bias,
            "cross_bias": cross_bias,
            "pos_bias": pos_bias,
            "skey_mask": skey_mask,
            "ckey_mask": ckey_mask,
            "encoder_hidden": encoder_hidden,
        }

    def forward_from_layer(
        self,
        branch_params: Dict,
        branch_hidden: Array,
        self_bias: Optional[Array],
        encoder_hidden: Array,
        cross_bias: Optional[Array],
        remat=False,
        compute_logits: bool = True,
        pos_bias: Optional[Array] = None,
        skey_mask: Optional[Array] = None,
        ckey_mask: Optional[Array] = None,
    ) -> Dict[str, Array]:
        """Run a frozen top-k decoder branch from a captured hidden state.
        Under the pallas path the combined biases are None and the
        structured (pos_bias, key-mask) pieces ride instead."""
        h, _ = self._scan(
            self.dec_block, branch_params["blocks"], branch_hidden, self_bias,
            encoder_hidden, cross_bias, pos_bias, skey_mask, ckey_mask,
            remat=remat,
        )
        hidden = self.norm.apply({"params": branch_params["ln_f"]}, h)
        return {
            "logits": self._logits(branch_params, hidden) if compute_logits else None,
            "hidden_states": hidden,
        }


    # -- decoding --------------------------------------------------------

    def init_cache(self, batch: int, max_len: int) -> Dict:
        cfg = self.cfg
        shape = (cfg.n_decoder_layer, batch, max_len, cfg.n_head, cfg.d_kv)
        return {
            "k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "index": jnp.int32(0),
        }

    def decode_step(
        self,
        params: Dict,
        token: Array,  # [B, 1]
        encoder_hidden: Array,
        attention_mask: Array,  # [B, S_enc]
        cache: Dict,
    ) -> Tuple[Dict[str, Array], Dict]:
        """One decoder step at cache position `cache['index']`."""
        cfg = self.cfg
        S = cache["k"].shape[2]
        t = cache["index"]
        k_pos = jnp.arange(S)
        self_bias = compute_position_bias(
            params["decoder"]["rel_bias"], t[None], k_pos, False,
            cfg.relative_attention_num_buckets, cfg.relative_attention_max_distance,
        )
        visible = k_pos[None, None, None, :] <= t
        self_bias = jnp.where(visible, self_bias, NEG_INF)
        cross_bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, NEG_INF)

        h = self._embed(params, token)
        h, new_cache = self._scan(
            self.dec_block, params["decoder"]["blocks"], h, self_bias,
            encoder_hidden, cross_bias, cache=cache,
        )
        hidden = self.norm.apply({"params": params["decoder"]["ln_f"]}, h)
        return {"logits": self._logits(params, hidden), "hidden_states": hidden}, new_cache


def t5_logit_projection(params: Dict, cfg):
    """hidden -> fp32 logits closure over a T5LM param tree, matching
    `T5LM._logits` numerics exactly (tied-embedding d_model^-0.5 scale,
    compute-dtype matmul, fp32 accumulation). Feeds
    `ops.common.chunked_logprobs` so losses can avoid materializing
    full [B, T, V] logits."""
    if "lm_head" in params:
        kernel = params["lm_head"]["kernel"]

        def proj(h: Array) -> Array:
            return jnp.einsum(
                "...d,dv->...v", h, kernel.astype(h.dtype),
                preferred_element_type=jnp.float32,
            )

        return proj
    wte = params["shared"]["wte"]
    scale = cfg.d_model ** -0.5

    def proj(h: Array) -> Array:
        return jnp.einsum(
            "...d,vd->...v", h * scale, wte.astype(h.dtype),
            preferred_element_type=jnp.float32,
        )

    return proj


def extract_t5_branch_params(params: Dict, branch_at: int) -> Dict:
    """Frozen top decoder branch + final norm + logit head (deep-copied:
    trainers donate the policy buffers)."""
    branch = {
        "blocks": jax.tree_util.tree_map(
            lambda x: x[branch_at:], params["decoder"]["blocks"]
        ),
        "ln_f": params["decoder"]["ln_f"],
        "shared": params["shared"],
    }
    if "lm_head" in params:
        branch["lm_head"] = params["lm_head"]
    return jax.tree_util.tree_map(jnp.copy, jax.lax.stop_gradient(branch))


def generate_seq2seq(
    model: T5LM,
    params: Dict,
    input_ids: Array,
    attention_mask: Array,
    rng: jax.Array,
    settings,
    logits_processor=None,
) -> Dict[str, Array]:
    """Sample decoder continuations (analog of models.generation.generate
    for the encoder-decoder path). Output starts with
    `decoder_start_token_id` (the <pad> HF T5 convention)."""
    from trlx_tpu.models.generation import cast_params_for_decode, sample_token

    cfg = model.cfg
    B = input_ids.shape[0]
    N = settings.max_new_tokens
    params = cast_params_for_decode(params, cfg.dtype)
    # same pp-decode weight-gather hoist as models.generation.generate,
    # restricted to the decoder stack: the encoder runs ONCE (pipelined
    # when pp>1) and its pp-sharded blocks are never read by the decode
    # loop, so gathering them would spend cross-stage (possibly DCN)
    # bandwidth and pp× encoder-param memory for nothing
    from trlx_tpu.parallel.sharding import unshard_for_decode

    mesh = getattr(model, "mesh", None)
    params = dict(params, decoder=unshard_for_decode(params["decoder"], mesh))
    if cfg.decode_weights_quant == "int8":
        # decoder-only weight quantization: the decode loop re-reads the
        # decoder stack every step (the encoder ran once, full precision)
        from trlx_tpu.models.transformer import quantize_decode_weights

        params = dict(params, decoder=quantize_decode_weights(params["decoder"]))
    enc = model.encode(params, input_ids, attention_mask)
    cache = model.init_cache(B, N + 1)
    start = jnp.full((B, 1), cfg.decoder_start_token_id, jnp.int32)

    def pick(rng_t, hidden_last, logits_last, finished):
        if logits_processor is not None:
            logits_last = logits_processor(hidden_last, logits_last)
        tok = sample_token(rng_t, logits_last, settings)
        tok = jnp.where(finished, jnp.int32(settings.pad_token_id), tok)
        return tok, finished | (tok == settings.eos_token_id)

    out, cache = model.decode_step(params, start, enc, attention_mask, cache)
    rng, sub = jax.random.split(rng)
    tok0, fin0 = pick(sub, out["hidden_states"][:, -1], out["logits"][:, -1],
                      jnp.zeros((B,), bool))

    def step(carry, rng_t):
        cache, tok, finished, was_real = carry
        step_out, cache = model.decode_step(
            params, tok[:, None], enc, attention_mask, cache
        )
        nxt, now_fin = pick(
            rng_t, step_out["hidden_states"][:, -1], step_out["logits"][:, -1], finished
        )
        return (cache, nxt, now_fin, ~finished), (tok, was_real)

    if N > 1:
        carry0 = (cache, tok0, fin0, jnp.ones((B,), bool))
        (cache, tok_last, fin, last_real), (toks, reals) = jax.lax.scan(
            step, carry0, jax.random.split(rng, N - 1)
        )
        response_ids = jnp.concatenate([toks.T, tok_last[:, None]], axis=1)
        response_mask = jnp.concatenate([reals.T, last_real[:, None]], axis=1)
    else:
        response_ids = tok0[:, None]
        response_mask = jnp.ones((B, 1), bool)

    decoder_ids = jnp.concatenate([start, response_ids], axis=1)  # with start token
    return {
        "sequences": decoder_ids,
        "response_ids": response_ids,
        "response_mask": response_mask.astype(jnp.int32),
        "encoder_hidden": enc,
    }
