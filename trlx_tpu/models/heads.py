"""Auxiliary heads: the 2-layer MLP used for value / Q heads.

Parity: /root/reference/trlx/utils/modeling.py:21-27 (`make_head` =
Linear(n_embd, 512) -> ReLU -> Linear(512, out) — this fork pins the
hidden width to 512) and /root/reference/trlx/models/modeling_ilql.py:169-227
(`ILQLHeads`: v head + 1-2 q heads + frozen Polyak-synced target q heads).

Heads are plain param pytrees ({"fc_in": {kernel, bias}, "fc_out":
{kernel, bias}}) applied by pure functions, so trainers can freeze / sync
/ shard them with tree ops.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jnp.ndarray

HEAD_HIDDEN = 512  # fork-pinned width (reference utils/modeling.py:21-27)


def init_head(
    rng: jax.Array,
    in_dim: int,
    out_dim: int,
    hidden: int = HEAD_HIDDEN,
    dtype=jnp.float32,
) -> Dict:
    k1, k2 = jax.random.split(rng)
    scale_in = 1.0 / jnp.sqrt(jnp.float32(in_dim))
    scale_h = 1.0 / jnp.sqrt(jnp.float32(hidden))
    return {
        "fc_in": {
            "kernel": (jax.random.uniform(k1, (in_dim, hidden), jnp.float32, -1, 1) * scale_in).astype(dtype),
            "bias": jnp.zeros((hidden,), dtype),
        },
        "fc_out": {
            "kernel": (jax.random.uniform(k2, (hidden, out_dim), jnp.float32, -1, 1) * scale_h).astype(dtype),
            "bias": jnp.zeros((out_dim,), dtype),
        },
    }


def apply_head(params: Dict, x: Array) -> Array:
    """MLP head in fp32 (value/Q losses are fp32; negligible FLOPs)."""
    x = x.astype(jnp.float32)
    h = jax.nn.relu(x @ params["fc_in"]["kernel"].astype(jnp.float32) + params["fc_in"]["bias"])
    return h @ params["fc_out"]["kernel"].astype(jnp.float32) + params["fc_out"]["bias"]


# ---------------------------------------------------------------------------
# ILQL head group
# ---------------------------------------------------------------------------


def init_ilql_heads(
    rng: jax.Array, hidden_size: int, vocab_size: int, two_qs: bool = True
) -> Dict:
    """{"q_heads": [...], "target_q_heads": [...], "v_head": ...}.

    Target heads start as copies of the online heads (reference
    modeling_ilql.py:186-191 `copy_(...)` on init via sync alpha=1).
    """
    n_qs = 2 if two_qs else 1
    keys = jax.random.split(rng, n_qs + 1)
    q_heads = [init_head(keys[i], hidden_size, vocab_size) for i in range(n_qs)]
    return {
        "q_heads": q_heads,
        # deep copy: aliased leaves would break buffer donation in the
        # trainers (f(donate(a), donate(a)))
        "target_q_heads": jax.tree_util.tree_map(jnp.copy, q_heads),
        "v_head": init_head(keys[-1], hidden_size, 1),
    }


def apply_ilql_heads(
    heads: Dict,
    hidden: Array,  # [B, T, E]
    states_ixs: Array,  # [B, n_states]
    actions_ixs: Array,  # [B, n_actions]
) -> Tuple[Sequence[Array], Sequence[Array], Array]:
    """Gather hidden states first, then apply heads (the reference does the
    same — modeling_ilql.py:193-208 — so Q/V matmuls run over n_actions,
    not the full sequence)."""
    from trlx_tpu.ops.common import batched_index_select

    states_hs = batched_index_select(hidden, states_ixs, dim=1)
    actions_hs = batched_index_select(hidden, actions_ixs, dim=1)
    qs = [apply_head(h, actions_hs) for h in heads["q_heads"]]
    target_qs = [
        jax.lax.stop_gradient(apply_head(h, actions_hs))
        for h in heads["target_q_heads"]
    ]
    vs = apply_head(heads["v_head"], states_hs)
    return qs, target_qs, vs


def sync_target_q_heads(heads: Dict, alpha: float) -> Dict:
    """Polyak update target <- alpha * online + (1 - alpha) * target
    (parity: modeling_ilql.py:210-227)."""
    new_targets = jax.tree_util.tree_map(
        lambda q, t: alpha * q + (1.0 - alpha) * t,
        heads["q_heads"],
        heads["target_q_heads"],
    )
    return dict(heads, target_q_heads=new_targets)
