"""Token-level PUCT MCTS decoding guided by ILQL Q/V heads.

Parity: /root/reference/trlx/models/mcts.py:7-218 (`Peach` / `MCTSNode`,
fork-specific) — priors are softmax((log pi + beta*(minQ - V)) / temp) at
each node, node value is V(s), actions are chosen by the PUCT rule and
finally by root visit count.

TPU split: the tree (visit counts, Q/W tables, children) lives on host —
it is tiny, sequential bookkeeping — while every node evaluation is ONE
jitted forward at a static width (sequences padded to prompt_len +
max_new_tokens). The reference re-forwards the full prefix per node too
(it deliberately never extends past_key_values inside the tree), so the
compute shape matches while the host/device boundary is clean.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.models.heads import apply_head
from trlx_tpu.models.wrappers import CausalLMWithILQLHeads, _effective_base


class MCTSNode:
    __slots__ = ("tokens", "parent", "action", "children", "N", "N_sa",
                 "W_sa", "Q_sa", "P_sa", "is_terminal", "value")

    def __init__(self, tokens: List[int], parent=None, action: Optional[int] = None):
        self.tokens = tokens
        self.parent = parent
        self.action = action
        self.children: Dict[int, "MCTSNode"] = {}
        self.N = 0
        self.N_sa = None
        self.W_sa = None
        self.Q_sa = None
        self.P_sa = None
        self.is_terminal = False
        self.value = None

    def select_action(self, c_puct: float) -> int:
        sqrt_n = math.sqrt(self.N + 1e-8)
        u = self.Q_sa + c_puct * self.P_sa * sqrt_n / (1 + self.N_sa)
        return int(np.argmax(u))

    def backup(self, value: float) -> None:
        node = self
        while node is not None:
            node.N += 1
            if node.parent is not None:
                a = node.action
                node.parent.N_sa[a] += 1
                node.parent.W_sa[a] += value
                node.parent.Q_sa[a] = node.parent.W_sa[a] / node.parent.N_sa[a]
            node = node.parent


def _make_eval_fn(model: CausalLMWithILQLHeads, beta: float, temperature: float):
    """Jitted (params, ids[1,width], mask[1,width]) -> (priors[V], value).
    The width is fixed by the caller's padded arrays; jit specializes on it.
    jit's cache is keyed on function identity, so a fresh closure per
    mcts_generate call would recompile every time; cache the jitted fn on
    the model instance (not a module-level dict, which would pin every
    model ever used for the process lifetime)."""
    cache: Dict[tuple, Callable] = model.__dict__.setdefault("_mcts_eval_fns", {})
    cache_key = (float(beta), float(temperature))
    if cache_key in cache:
        return cache[cache_key]

    def eval_fn(params, ids, mask):
        base = _effective_base(model, params)
        out = model.lm(base, ids, mask)
        last = jnp.maximum(mask.sum(axis=1) - 1, 0)
        hidden = jnp.take_along_axis(
            out["hidden_states"], last[:, None, None], axis=1
        )[:, 0]
        logits = jnp.take_along_axis(out["logits"], last[:, None, None], axis=1)[:, 0]
        heads = params["heads"]
        qs = [apply_head(h, hidden) for h in heads["target_q_heads"]]
        min_q = qs[0] if len(qs) == 1 else jnp.minimum(*qs)
        v = apply_head(heads["v_head"], hidden)  # [1, 1]
        adv = min_q - v
        prior_logits = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1) + beta * adv
        priors = jax.nn.softmax(prior_logits / max(temperature, 1e-6), axis=-1)
        return priors[0], v[0, 0]

    jitted = jax.jit(eval_fn)
    cache[cache_key] = jitted
    return jitted


def mcts_generate(
    model: CausalLMWithILQLHeads,
    params: Dict,
    input_ids: np.ndarray,  # [B, P] (left-padded)
    attention_mask: Optional[np.ndarray] = None,
    beta: float = 1.0,
    temperature: float = 1.0,
    max_new_tokens: int = 32,
    num_simulations: int = 50,
    c_puct: float = 1.0,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    logit_mask: Optional[np.ndarray] = None,  # [V] additive, -inf = banned
) -> np.ndarray:
    """Decode each sample with PUCT MCTS; returns [B, P + max_new_tokens]."""
    input_ids = np.asarray(input_ids, np.int32)
    B, P = input_ids.shape
    if attention_mask is None:
        attention_mask = (input_ids != pad_token_id).astype(np.int32)
    width = P + max_new_tokens
    eval_fn = _make_eval_fn(model, beta, temperature)
    add_mask = None
    if logit_mask is not None:
        add_mask = np.where(np.isfinite(np.asarray(logit_mask, np.float32)), 0.0, -np.inf)

    def evaluate(node: MCTSNode) -> float:
        if node.is_terminal:
            return 0.0
        ids = np.full((1, width), pad_token_id, np.int32)
        mask = np.zeros((1, width), np.int32)
        toks = node.tokens[:width]
        ids[0, : len(toks)] = toks
        mask[0, : len(toks)] = 1
        priors, value = eval_fn(params, jnp.asarray(ids), jnp.asarray(mask))
        priors = np.asarray(priors)
        if add_mask is not None:
            priors = priors * np.isfinite(add_mask)
            priors = priors / max(priors.sum(), 1e-9)
        node.P_sa = priors
        vocab = priors.shape[0]
        node.N_sa = np.zeros(vocab, np.int32)
        node.W_sa = np.zeros(vocab, np.float32)
        node.Q_sa = np.zeros(vocab, np.float32)
        if eos_token_id is not None and toks and toks[-1] == eos_token_id:
            node.is_terminal = True
            node.value = 0.0
        else:
            node.value = float(value)
        return node.value

    samples = np.full((B, width), pad_token_id, np.int32)
    samples[:, :P] = input_ids
    for b in range(B):
        prefix = [int(t) for t, m in zip(input_ids[b], attention_mask[b]) if m]
        for step in range(max_new_tokens):
            if eos_token_id is not None and prefix and prefix[-1] == eos_token_id:
                break
            root = MCTSNode(list(prefix))
            evaluate(root)
            for _ in range(num_simulations):
                node = root
                while not node.is_terminal:
                    if node.N == 0:
                        evaluate(node)
                        break
                    action = node.select_action(c_puct)
                    child = node.children.get(action)
                    if child is not None:
                        node = child
                        continue
                    child = MCTSNode(node.tokens + [action], parent=node, action=action)
                    node.children[action] = child
                    evaluate(child)
                    node = child
                    break
                node.backup(node.value if node.value is not None else 0.0)
            best = int(np.argmax(root.N_sa))
            prefix.append(best)
        # write the decoded continuation right after the (left-padded) prompt
        cont = prefix[int(attention_mask[b].sum()):]
        samples[b, P : P + len(cont)] = cont[:max_new_tokens]
    return samples
