"""LoRA: low-rank adapter overlays on the stacked param tree.

Parity: the reference delegates to the `peft` library
(/root/reference/trlx/models/modeling_base.py:124-275 wires
peft_config through from_pretrained; tests/test_peft.py is the contract).
Here adapters are first-party and TPU-shaped: one (A, B) pair per
*stacked* kernel — a rank-r overlay for ALL layers at once with a leading
L axis — merged into the base weights by einsum inside jit, so the base
forward is unchanged and XLA fuses the merge into the surrounding matmul
schedule.

The adapter tree is flat: {path: {"a": [L?, in, r], "b": [L?, r, out]}}.
`merge_lora` adds scaling * A@B (reshaped) onto each targeted kernel;
gradients flow through the merge to A/B only when the base is wrapped in
stop_gradient by the caller.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jnp.ndarray

# (path regex, n leading stack dims, n input dims, n output dims);
# self_attn/cross_attn cover the seq2seq (T5) stacks
_SPLITS: List[Tuple[str, int, int, int]] = [
    (r"blocks/(self_|cross_)?attn/[qkv]/kernel$", 1, 1, 2),  # [L, E, H, D]
    (r"blocks/(self_|cross_)?attn/o/kernel$", 1, 2, 1),      # [L, H, D, E]
    (r"blocks/mlp/fc_(in|gate|out)/kernel$", 1, 1, 1),  # [L, in, out]
    (r"lm_head/kernel$", 0, 1, 1),             # [E, V]
]

DEFAULT_TARGETS = (
    r"blocks/(self_|cross_)?attn/[qkv]/kernel$"
    r"|blocks/(self_|cross_)?attn/o/kernel$"
)


def normalize_peft_config(peft_config: Any) -> Dict[str, Any]:
    """Accept a dict in the HF peft style ({"peft_type": "LORA", "r": 8,
    "lora_alpha": 16, ...}) and normalize to our fields. Delegates to
    models/peft.py, which owns the full adapter surface (LORA |
    PROMPT_TUNING | PREFIX_TUNING)."""
    from trlx_tpu.models.peft import normalize_peft_config as _norm

    return _norm(peft_config)


def _path_str(path) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


def _split_for(path_str: str):
    for pattern, n_stack, n_in, n_out in _SPLITS:
        if re.search(pattern, path_str):
            return n_stack, n_in, n_out
    return None


def _target_match(path_str: str, targets) -> bool:
    if isinstance(targets, str):
        return re.search(targets, path_str) is not None
    # HF-style list of module names ("q", "fc_in", "q_proj"...)
    leaf_module = path_str.split("/")[-2] if "/" in path_str else path_str
    aliases = {"q_proj": "q", "k_proj": "k", "v_proj": "v", "o_proj": "o",
               "c_attn": "q", "out_proj": "o"}
    names = {aliases.get(t, t) for t in targets}
    return leaf_module in names


def init_lora_params(
    rng: jax.Array, base_params: Dict, r: int, targets=DEFAULT_TARGETS
) -> Dict[str, Dict[str, Array]]:
    """{path: {a, b}} for every targeted kernel. A ~ N(0, 0.02), B = 0 so
    the overlay starts as a no-op (standard LoRA init)."""
    lora: Dict[str, Dict[str, Array]] = {}
    flat = jax.tree_util.tree_flatten_with_path(base_params)[0]
    keys = iter(jax.random.split(rng, len(flat)))
    for path, leaf in flat:
        ps = _path_str(path)
        key = next(keys)
        split = _split_for(ps)
        if split is None or not _target_match(ps, targets):
            continue
        n_stack, n_in, n_out = split
        shape = np.shape(leaf)
        stack = shape[:n_stack]
        d_in = int(np.prod(shape[n_stack : n_stack + n_in]))
        d_out = int(np.prod(shape[n_stack + n_in :]))
        lora[ps] = {
            "a": jax.random.normal(key, stack + (d_in, r), jnp.float32) * 0.02,
            "b": jnp.zeros(stack + (r, d_out), jnp.float32),
        }
    if not lora:
        raise ValueError(f"no LoRA targets matched {targets!r}")
    return lora


def merge_lora(base_params: Dict, lora: Dict[str, Dict[str, Array]], scaling: float) -> Dict:
    """base + scaling * A@B on every adapted kernel (pure; jit-friendly)."""

    def merge_leaf(path, leaf):
        ps = _path_str(path)
        ab = lora.get(ps)
        if ab is None:
            return leaf
        delta = jnp.einsum(
            "...ir,...ro->...io", ab["a"], ab["b"],
            preferred_element_type=jnp.float32,
        ) * scaling
        return leaf + delta.reshape(leaf.shape).astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(merge_leaf, base_params)
