"""RunObserver: the glue between the training loop and the flight
recorder / span tracer / telemetry / profiler.

Wiring (all in trainer/base.py, each a one-liner at an existing site):

- beat sites: registered as a sibling listener on the hang doctor's
  heartbeat registry (``HangWatchdog.add_listener``) — the span tracer
  consumes the SAME beats the stall detector does, so phase
  instrumentation lands once;
- guardrail trips: a listener on ``GuardrailMonitor`` — every trip
  signal (loss/kl/reward/grad_norm/cycle_time/truncation/consistency/
  staleness/fleet/memory/stall/peer) lands in the stream the moment it
  is recorded, and perf/memory trips arm the one-shot profiler;
- chaos injections: ``ChaosMonkey.on_fire``;
- everything else (cycle boundaries, samples, OOM-ladder rungs,
  watermark crossings, checkpoint commits/restores, cross-host rows)
  is an explicit ``obs.*`` call from the trainer.

Contract: NO method here ever raises into the training loop. The first
failure logs, flips the observer broken, and every later call is a
cheap no-op — observability must never be the thing that kills a run.
"""

from __future__ import annotations

import functools
import os
import time
import uuid
from collections import deque
from typing import Any, Dict, Optional

from trlx_tpu.obs.config import ObsConfig
from trlx_tpu.obs.profiler import ProfilerArm
from trlx_tpu.obs.recorder import FlightRecorder
from trlx_tpu.obs.spans import SpanTracer
from trlx_tpu.obs.telemetry import TelemetryAggregator, device_provenance
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


def _no_raise(method):
    """Observability never breaks training: first failure logs and
    disarms the observer."""

    @functools.wraps(method)
    def wrapped(self, *args, **kwargs):
        if not self.active:
            return None
        try:
            return method(self, *args, **kwargs)
        except Exception as e:
            self.active = False
            logger.error(
                "obs: %s failed (%s) — flight recorder disarmed for the "
                "rest of the run; training continues", method.__name__, e,
            )
            return None

    return wrapped


class RunObserver:
    """One per trainer; owns the run's flight stream + telemetry."""

    def __init__(
        self,
        cfg: ObsConfig,
        flight_dir: str,
        is_writer: bool = True,
        clock=time.monotonic,
        run_id: Optional[str] = None,
    ):
        self.cfg = cfg
        self.flight_dir = flight_dir
        # non-main hosts accumulate nothing: process 0 owns the stream
        # (cross-host rows arrive through the consensus-cadence gather)
        self.active = bool(cfg.enabled and is_writer)
        self._clock = clock
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self.tracer = SpanTracer()
        self.telemetry = TelemetryAggregator(window=cfg.telemetry_window)
        self.recorder = FlightRecorder(
            flight_dir, self.run_id,
            rotate_bytes=cfg.rotate_bytes, keep_files=cfg.keep_files,
        )
        self.profiler = ProfilerArm(
            cfg.profile, os.path.join(flight_dir, "profiles"),
            enabled=self.active,
        )
        self._events: Dict[str, deque] = {}
        self._step: Optional[int] = None
        self._policy_version: Optional[int] = None
        self._started = False

    # -- attachment ------------------------------------------------------

    def attach(self, watchdog=None, guardrails=None, chaos=None) -> None:
        """Register as a sibling consumer on the existing telemetry
        islands (no-op when disabled, so default-off subsystems and
        ``train.obs.enabled: false`` both cost nothing)."""
        if not self.active:
            return
        if watchdog is not None:
            watchdog.add_listener(self._on_beat)
        if guardrails is not None:
            guardrails.add_listener(self._on_guardrail_trip)
        if chaos is not None:
            chaos.on_fire = self._on_chaos
        # keep beat timestamps and cycle boundaries on one timebase
        if watchdog is not None:
            self._clock = watchdog.clock

    # -- listeners -------------------------------------------------------

    def _on_beat(self, now, phase, event, step=None, count=1) -> None:
        if not self.active:
            return
        try:
            self.tracer.on_beat(now, phase, event, step, count)
        except Exception as e:
            # same contract as _no_raise: log ONCE, then go quiet — a
            # silently frozen stream is undebuggable
            self.active = False
            logger.error(
                "obs: span tracer failed on a beat (%s) — flight "
                "recorder disarmed for the rest of the run; training "
                "continues", e,
            )

    @_no_raise
    def _on_guardrail_trip(self, signal: str, detail: str) -> None:
        self.record("guardrail_trip", signal=signal, detail=detail)
        self.profiler.note_trip(signal)

    @_no_raise
    def _on_chaos(self, fired: Dict[str, Any]) -> None:
        self.record("chaos", **fired)

    # -- correlation + events --------------------------------------------

    @property
    def cycle(self) -> int:
        """The OPEN cycle's 1-based index."""
        return self.telemetry.cycle_count + 1

    def _remember(self, kind: str, row: Dict[str, Any]) -> None:
        tail = self._events.setdefault(kind, deque(maxlen=self.cfg.events_tail))
        tail.append(row)

    def events_tail(self) -> Dict[str, list]:
        return {k: list(v) for k, v in self._events.items()}

    @_no_raise
    def record(self, kind: str, **fields: Any) -> None:
        """One correlated event row (run_id / cycle / step / policy
        version stamped here)."""
        row = {"cycle": self.cycle, "step": self._step,
               "pv": self._policy_version}
        row.update(fields)  # caller's fields win (e.g. run_start's step)
        self.recorder.append(kind, **row)
        self._remember(kind, {"t": round(time.time(), 3), **row})

    # -- run / cycle lifecycle -------------------------------------------

    @_no_raise
    def start(self, **meta: Any) -> None:
        """Arm at the top of learn(): stamps provenance, opens the
        first cycle, and records ``run_start`` (a resumed run appends
        to the same stream under the restored run_id)."""
        self.telemetry.set_static(device=device_provenance(), **meta)
        self._step = meta.get("step")
        now = self._clock()
        if not self._started:
            self.tracer.start_cycle(now)
        else:
            self.tracer.snapshot_cycle(now)  # discard inter-learn() time
        self._started = True
        self.record("run_start", **{k: v for k, v in meta.items() if v is not None})
        self.profiler.begin_cycle(self.cycle)

    @_no_raise
    def set_param_count(self, n: int) -> None:
        self.telemetry.set_param_count(n)

    @_no_raise
    def note_samples(self, n: int) -> None:
        self.telemetry.note_samples(n)

    @_no_raise
    def note_tokens(self, n: float) -> None:
        self.telemetry.note_tokens(n)

    @_no_raise
    def observe_stats(self, stats: Dict[str, Any], step: int) -> None:
        """Tap on the trainer's single ``_tracker_log`` funnel: every
        flushed host scalar the run already produces (the telemetry
        accounting reuses, never re-derives)."""
        self.telemetry.observe_stats(stats)

    @_no_raise
    def end_cycle(
        self, step: Optional[int] = None,
        policy_version: Optional[int] = None, n_steps: int = 0,
        final: bool = False,
    ) -> None:
        """Close one optimization cycle: snapshot the span partition,
        fold it into telemetry, write the ``cycle`` row, advance the
        profiler window. ``final`` (the finish() path) skips re-arming
        the profiler — a capture must not start for a cycle that will
        never run."""
        self._step = step
        self._policy_version = policy_version
        if not self._started:
            self.tracer.start_cycle(self._clock())
            self._started = True
            return
        closing = self.cycle
        wall, breakdown = self.tracer.snapshot_cycle(self._clock())
        row = self.telemetry.close_cycle(
            wall, breakdown, step=step, policy_version=policy_version,
            n_steps=n_steps,
        )
        self.recorder.append("cycle", **row)
        self.profiler.end_cycle(closing)
        if not final:
            self.profiler.begin_cycle(self.cycle)

    @_no_raise
    def record_hosts(self, ages: Dict[str, float], detail: Optional[str]) -> None:
        """Cross-host row at the consensus cadence: the local phase
        counters (equal beat counts at a lockstep gather; wall totals
        name the slow host) plus the straggler verdict, in the same
        correlated stream as everything else."""
        self.record(
            "hosts",
            ages={k: round(float(v), 1) for k, v in sorted(ages.items())},
            straggler=detail,
        )

    # -- artifacts -------------------------------------------------------

    @_no_raise
    def write_telemetry(self, path: str) -> None:
        """Commit a provenance-stamped ``telemetry.json`` snapshot
        (atomic tmp+rename — same pattern as state.json)."""
        from trlx_tpu.utils.checkpointing import atomic_json_write

        atomic_json_write(
            path, self.telemetry.snapshot(self.run_id, self.events_tail())
        )

    def finish(self) -> None:
        """learn()-exit hook: close the open cycle, refresh the
        flight-dir telemetry snapshot, stop any profiler capture.
        Deliberately NOT gated on ``active``: even after a mid-run
        disarm, an in-flight profiler trace must stop and the recorder
        fd must close — only the writes are skipped."""
        try:
            if self.active and self._started:
                self.end_cycle(step=self._step,
                               policy_version=self._policy_version,
                               final=True)
                self.record("run_end")
                self.write_telemetry(
                    os.path.join(self.flight_dir, "telemetry.json")
                )
        except Exception as e:
            logger.error("obs: finish failed (%s); closing anyway", e)
        finally:
            try:
                self.profiler.close()
            except Exception:
                pass
            self.recorder.close()

    # -- resumable state -------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {"run_id": self.run_id, **self.telemetry.state_dict()}

    @_no_raise
    def load_state_dict(self, state: Optional[Dict[str, Any]]) -> None:
        """Adopt a checkpoint's observer state so correlation ids (and
        run totals) stay stable across resume: the relaunched process
        keeps appending to the same stream under the same run_id, and
        cycle numbering continues instead of restarting at 1. A
        malformed ``obs`` blob (hand-edited state.json, format drift)
        disarms the observer instead of crashing the restore — every
        other field of the checkpoint still loads."""
        if not state or not isinstance(state, dict):
            return
        rid = state.get("run_id")
        if rid:
            self.run_id = str(rid)
            self.recorder.run_id = self.run_id
        self.telemetry.load_state_dict(state)


def build_observer(
    train_config,
    checkpoint_dir: Optional[str] = None,
    is_writer: bool = True,
    watchdog=None,
    guardrails=None,
    chaos=None,
    clock=time.monotonic,
) -> RunObserver:
    """TrainConfig -> observer, attached to the run's telemetry
    islands (the ``obs`` field is a plain dict so the flat config
    dataclass stays YAML/back-compatible)."""
    cfg = ObsConfig.from_dict(getattr(train_config, "obs", None))
    root = checkpoint_dir or getattr(train_config, "checkpoint_dir", "ckpts")
    flight_dir = cfg.dir or os.path.join(root, "flight")
    obs = RunObserver(cfg, flight_dir, is_writer=is_writer, clock=clock)
    obs.attach(watchdog=watchdog, guardrails=guardrails, chaos=chaos)
    return obs
