"""Parsed ``train.obs`` section (plain dict in YAML, like the other
robustness subsystems — the flat TrainConfig stays YAML/back-compatible).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ProfileConfig:
    """``train.obs.profile``: on-demand deep profiling.

    start_cycle/stop_cycle  arm a ``jax.profiler`` window capture for
                            cycles [start_cycle, stop_cycle] (1-based;
                            0 disables the window).
    on_trip                 additionally arm a ONE-CYCLE capture when a
                            guardrail perf/memory signal trips
                            (``cycle_time`` / ``memory``) — the profile
                            of the first slow/creeping cycle is exactly
                            the artifact a post-mortem wants.
    dir                     capture directory (default
                            ``<flight_dir>/profiles``).
    force                   capture even off-TPU (tests; default the
                            capture is a no-op on non-TPU backends —
                            the dir is still created so arming is
                            observable).
    """

    start_cycle: int = 0
    stop_cycle: int = 0
    on_trip: bool = False
    dir: Optional[str] = None
    force: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ProfileConfig":
        d = dict(d or {})
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"train.obs.profile: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        return cls(**d)


@dataclass
class ObsConfig:
    """Parsed ``train.obs`` section.

    enabled           master switch. DEFAULT ON (unlike the other
                      subsystems): the whole point is that every run
                      self-documents without anyone remembering to ask.
                      Host-side only, no device syncs, bounded cost.
    dir               flight-recorder directory (default
                      ``<checkpoint_dir>/flight``).
    rotate_bytes      rotate the JSONL stream when the current file
                      exceeds this size.
    keep_files        rotated files retained (oldest pruned beyond it).
    telemetry_window  cycles in the rolling headline (samples/s etc.);
                      the first cycle is always excluded (compile).
    events_tail       per-kind event rows retained in telemetry.json.
    profile           :class:`ProfileConfig` sub-section.
    """

    enabled: bool = True
    dir: Optional[str] = None
    rotate_bytes: int = 4 * 1024 * 1024
    keep_files: int = 8
    telemetry_window: int = 8
    events_tail: int = 16
    profile: ProfileConfig = field(default_factory=ProfileConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ObsConfig":
        d = dict(d or {})
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"train.obs: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        d["profile"] = ProfileConfig.from_dict(d.get("profile"))
        return cls(**d)
