"""Span tracer: per-cycle wall-time partition over the watchdog's beat
sites.

The hang doctor's beat calls already mark every phase boundary the
trainers have (rollout start/end, per-chunk refills, reward, fused
block, per-step train, checkpoint, eval, transport waits). Rather than
instrumenting a second time, the tracer registers as a sibling
listener on those SAME sites (``HangWatchdog.add_listener``) and turns
the beat stream into an exact partition of host wall time:

- every instant belongs to exactly ONE phase — the innermost
  in-progress one (phases nest: PPO's reward call runs inside the
  rollout phase; its time is attributed to ``reward``, not double-
  counted under ``rollout``) — or to ``other`` when no phase is open
  (host bookkeeping between phases);
- therefore the per-cycle phase walls SUM TO THE CYCLE WALL by
  construction (float addition error only), which is the invariant
  tests and the flight-report sanity check hold it to.

Host-side only, no locks on the beat path (beats come from the
training thread; the monitor thread never beats), fake-clock testable:
timestamps arrive from the watchdog's injectable clock.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# the bucket for wall time outside any open phase (host bookkeeping,
# dataloader pulls, tracker writes between phases)
OTHER = "other"


class SpanTracer:
    """Partitions beat-site timestamps into per-phase wall seconds."""

    def __init__(self):
        self._stack: list = []  # innermost phase = last element
        self._last: Optional[float] = None
        self._acc: Dict[str, float] = {}
        self._cycle_t0: Optional[float] = None
        self.beats = 0  # total beat events observed (cost accounting)

    # -- beat consumption ------------------------------------------------

    def on_beat(
        self, now: float, phase: str, event: str = "point",
        step=None, count: int = 1,
    ) -> None:
        """Sibling-listener entry point (HangWatchdog.add_listener
        signature). Attributes the elapsed time since the previous
        event to the CURRENT innermost phase, then applies the stack
        transition. ``point`` beats only advance the clock attribution
        (a many-chunk rollout keeps charging ``rollout``)."""
        self.beats += count
        self._attribute(now)
        if event == "start":
            self._stack.append(phase)
        elif event == "end":
            # pop the innermost occurrence of this phase; exceptions
            # unwind via the watchdog's phase() finally, so ends arrive
            # innermost-first in practice — the reverse search keeps a
            # mismatched end from corrupting unrelated open phases
            for i in range(len(self._stack) - 1, -1, -1):
                if self._stack[i] == phase:
                    del self._stack[i]
                    break

    def _attribute(self, now: float) -> None:
        if self._last is not None and now > self._last:
            bucket = self._stack[-1] if self._stack else OTHER
            self._acc[bucket] = self._acc.get(bucket, 0.0) + (now - self._last)
        self._last = now

    # -- cycle boundaries ------------------------------------------------

    def start_cycle(self, now: float) -> None:
        """Open the first cycle (subsequent cycles open implicitly at
        :meth:`snapshot_cycle`)."""
        self._cycle_t0 = now
        self._last = now
        self._acc = {}

    def snapshot_cycle(self, now: float) -> Tuple[float, Dict[str, float]]:
        """Close the current cycle at ``now``: returns ``(wall_s,
        {phase: seconds})`` — the partition of [cycle start, now] —
        and opens the next cycle. The stack (open phases) carries
        across the boundary, so a phase spanning two cycles is charged
        to each for exactly the time it spent inside it."""
        self._attribute(now)
        t0 = self._cycle_t0 if self._cycle_t0 is not None else now
        wall = max(now - t0, 0.0)
        breakdown = {k: v for k, v in self._acc.items() if v > 0.0}
        self._cycle_t0 = now
        self._acc = {}
        return wall, breakdown

    @property
    def open_phases(self) -> list:
        return list(self._stack)
