"""Flight recorder: unified run telemetry, phase tracing, and
self-documenting perf artifacts (``train.obs.*``).

The repo grew five telemetry islands — watchdog phase beats, guardrail
trip history, memdoctor watermarks/OOM events, fleet membership and
broadcast records, and the supervisor's JSONL ledger — with no shared
timeline; and the bench trajectory went blind whenever nobody ran
``bench.py --record`` on a TPU. This subsystem closes both gaps:

  SpanTracer (obs/spans.py)
      a sibling consumer of the hang doctor's existing beat sites
      (``utils/watchdog.py`` — instrumentation lands ONCE): partitions
      host wall time into the phases the trainers already beat
      (rollout, reward, fused_block, train_step, checkpoint, eval,
      experience, exp_wait), innermost-phase attribution, per cycle.
      By construction the phase walls sum to the cycle wall exactly.
  FlightRecorder (obs/recorder.py)
      ONE size-rotated JSONL event stream under
      ``<checkpoint_dir>/flight/``: per-cycle phase breakdowns plus
      typed events — guardrail trips and ladder actions, chaos
      injections, memdoctor watermark crossings and OOM-ladder rungs,
      fleet degradations, staleness rejections, supervisor restarts,
      checkpoint commits/restores — every row correlated by
      run_id / cycle / policy_version. Appends are single-write
      (crash-torn tails are skipped by the reader); rotation is by
      size with bounded retention.
  TelemetryAggregator (obs/telemetry.py)
      continuously derives the bench-comparable headline numbers from
      the trainer's OWN flushed stats (honest mask-weighted tokens/s,
      samples/s, phase breakdown, engine occupancy/refills/reclaimed
      pages, an analytic-FLOPs MFU estimate reusing the memory
      doctor's param accounting) and commits a ``telemetry.json``
      snapshot alongside every checkpoint — so every run records an
      r05-comparable trajectory point even when nobody runs bench.
  ProfilerArm (obs/profiler.py)
      on-demand ``jax.profiler`` window capture for cycles N..M
      (``train.obs.profile.*``), or one-shot on a guardrail
      perf/memory trip; no-op off-TPU.

Everything here is host-side, jax-free at module scope, never syncs
the device, and NEVER raises into the training loop (a broken
recorder logs once and goes quiet). Default ON with bounded host
cost; ``train.obs.enabled: false`` restores pre-obs behavior exactly.

Render a recorded stream with ``python scripts/flight_report.py
<checkpoint_dir>``; the runbook is docs/observability.md.
"""

from trlx_tpu.obs.config import ObsConfig, ProfileConfig
from trlx_tpu.obs.observer import RunObserver, build_observer
from trlx_tpu.obs.recorder import FlightRecorder, append_external, iter_rows
from trlx_tpu.obs.spans import SpanTracer
from trlx_tpu.obs.telemetry import TelemetryAggregator

__all__ = [
    "ObsConfig",
    "ProfileConfig",
    "RunObserver",
    "build_observer",
    "FlightRecorder",
    "append_external",
    "iter_rows",
    "SpanTracer",
    "TelemetryAggregator",
]
