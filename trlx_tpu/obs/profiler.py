"""On-demand deep profiling: ``jax.profiler`` window capture armed by
cycle range (``train.obs.profile.start_cycle..stop_cycle``) or
one-shot on a guardrail perf/memory trip (``on_trip``).

The capture directory is created whenever a window arms (so arming is
observable and the operator knows where the trace will land); the
actual ``start_trace`` only runs on a TPU backend unless ``force`` is
set — on CPU tier-1 runs arming is a no-op beyond the directory, and
a profiler failure never escapes into the loop.
"""

from __future__ import annotations

import os
from typing import Optional

from trlx_tpu.obs.config import ProfileConfig
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

# guardrail signals that arm the one-shot capture: a slow cycle
# (cycle_time) or creeping HBM (memory) is exactly when the next
# cycle's profile is the post-mortem artifact
TRIP_SIGNALS = ("cycle_time", "memory")


class ProfilerArm:
    """Per-cycle arming state machine. All methods are no-raise."""

    def __init__(self, cfg: ProfileConfig, default_dir: str, enabled: bool = True):
        self.cfg = cfg
        self.dir = cfg.dir or default_dir
        self.enabled = enabled and (
            cfg.start_cycle > 0 or cfg.on_trip
        )
        self.capturing = False
        self._oneshot_armed = False
        self.captures = 0  # windows actually armed (tests observe this)
        self.traced = 0    # windows that really started a jax trace

    def _backend_ok(self) -> bool:
        if self.cfg.force:
            return True
        try:
            import jax

            return jax.default_backend() == "tpu"
        except Exception:
            return False

    def _start(self, cycle: int) -> None:
        capture_dir = os.path.join(self.dir, f"cycle-{cycle:05d}")
        try:
            os.makedirs(capture_dir, exist_ok=True)
        except OSError as e:
            logger.warning("obs profiler: cannot create %s (%s)", capture_dir, e)
            return
        self.capturing = True
        self.captures += 1
        if not self._backend_ok():
            logger.info(
                "obs profiler: armed for cycle %d but backend is not TPU "
                "— capture dir %s created, trace skipped", cycle, capture_dir,
            )
            return
        try:
            import jax

            jax.profiler.start_trace(capture_dir)
            self.traced += 1
            logger.info("obs profiler: tracing cycle %d -> %s", cycle, capture_dir)
        except Exception as e:
            logger.warning("obs profiler: start_trace failed (%s)", e)

    def _stop(self) -> None:
        if not self.capturing:
            return
        self.capturing = False
        if self.traced:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                logger.warning("obs profiler: stop_trace failed (%s)", e)

    # -- cycle hooks -----------------------------------------------------

    def begin_cycle(self, cycle: int) -> None:
        if not self.enabled or self.capturing:
            return
        window = (
            self.cfg.start_cycle > 0
            and self.cfg.start_cycle <= cycle
            and cycle <= max(self.cfg.stop_cycle, self.cfg.start_cycle)
        )
        if window or self._oneshot_armed:
            self._oneshot_armed = False
            self._start(cycle)

    def end_cycle(self, cycle: int) -> None:
        if not self.capturing:
            return
        window_continues = (
            self.cfg.start_cycle > 0
            and cycle + 1 <= max(self.cfg.stop_cycle, self.cfg.start_cycle)
            and cycle + 1 >= self.cfg.start_cycle
        )
        if not window_continues:
            self._stop()

    def note_trip(self, signal: str) -> None:
        """Arm a one-shot capture of the NEXT cycle on a perf/memory
        guardrail trip."""
        if self.enabled and self.cfg.on_trip and signal in TRIP_SIGNALS:
            self._oneshot_armed = True

    def close(self) -> None:
        self._stop()
