"""Flight recorder: one size-rotated JSONL event stream per run.

Layout: ``<flight_dir>/flight-00001.jsonl``, ``flight-00002.jsonl``,
... — the recorder continues the highest-numbered existing file on
(re)open, so a resumed run APPENDS to the same stream instead of
starting a parallel one (correlation by ``run`` id keeps restarted
runs distinguishable within it).

Crash-safety contract: every row is serialized first and written with
ONE ``os.write`` to an ``O_APPEND`` descriptor — a SIGKILL/SIGTERM
mid-write can tear at most the final line, never interleave rows, and
:func:`iter_rows` skips unparseable lines so a torn tail costs one
event, not the stream. Rotation closes the current file (already
final — rows are never rewritten) and opens the next index; files
beyond ``keep_files`` are pruned oldest-first.

Pure stdlib on purpose: ``scripts/flight_report.py`` and
``scripts/supervise.py`` consume/produce this format without
importing jax.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

_FILE_RE = re.compile(r"^flight-(\d{5})\.jsonl$")


def flight_files(directory: str) -> List[str]:
    """Stream files in rotation order (oldest first)."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return []
    found = sorted(
        (int(m.group(1)), e)
        for e in entries
        for m in [_FILE_RE.match(e)]
        if m
    )
    return [os.path.join(directory, e) for _, e in found]


def iter_rows(directory: str) -> Iterator[Dict[str, Any]]:
    """Parse every row of a flight stream in order, skipping torn /
    foreign lines (the reader half of the atomic-append contract)."""
    for path in flight_files(directory):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue  # torn tail from a mid-write kill
                    if isinstance(row, dict):
                        yield row
        except OSError:
            continue


class FlightRecorder:
    """Append typed events to the rotated stream. Thread-safe (the
    watchdog monitor thread records stall trips while the training
    thread records cycles); never raises past :meth:`append` — a
    recorder that cannot write logs nothing and stays quiet (the
    training loop must not die of observability)."""

    def __init__(
        self,
        directory: str,
        run_id: str,
        rotate_bytes: int = 4 * 1024 * 1024,
        keep_files: int = 8,
    ):
        self.directory = directory
        self.run_id = run_id
        self.rotate_bytes = max(int(rotate_bytes), 4096)
        self.keep_files = max(int(keep_files), 1)
        self._fd: Optional[int] = None
        self._index = 0
        self._lock = threading.Lock()
        self.rows_written = 0
        self.rows_dropped = 0  # transient write failures (row skipped)

    # -- file management -------------------------------------------------

    def _path(self, index: int) -> str:
        return os.path.join(self.directory, f"flight-{index:05d}.jsonl")

    def _ensure_open(self) -> int:
        if self._fd is not None:
            return self._fd
        os.makedirs(self.directory, exist_ok=True)
        existing = flight_files(self.directory)
        if existing:
            self._index = int(_FILE_RE.match(os.path.basename(existing[-1])).group(1))
        else:
            self._index = 1
        path = self._path(self._index)
        # seal a torn tail from a mid-write kill: without a trailing
        # newline the next append would CONCATENATE onto the torn line
        # and corrupt a second row — a lone '\n' confines the damage to
        # the line the kill already tore
        try:
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                torn = f.read(1) != b"\n"
        except OSError:
            torn = False
        self._fd = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644,
        )
        if torn:
            os.write(self._fd, b"\n")
        return self._fd

    def _maybe_rotate(self) -> None:
        try:
            size = os.fstat(self._fd).st_size
        except OSError:
            return
        if size < self.rotate_bytes:
            return
        os.close(self._fd)
        self._index += 1
        self._fd = os.open(
            self._path(self._index),
            os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644,
        )
        # prune beyond retention (oldest first; the live file survives)
        files = flight_files(self.directory)
        for path in files[: max(len(files) - self.keep_files, 0)]:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- writes ----------------------------------------------------------

    def append(self, kind: str, **fields: Any) -> None:
        """One event row. ``kind`` plus the caller's correlation fields
        (cycle / step / pv) and payload; ``t`` (epoch seconds) and
        ``run`` are stamped here. A TRANSIENT write failure (ENOSPC, an
        NFS blip) drops this one row and retries from a fresh open on
        the next append — it must not permanently disarm the observer
        the way an escaped exception would."""
        row = {"t": round(time.time(), 3), "run": self.run_id, "kind": kind}
        for k, v in fields.items():
            if v is not None:
                row[k] = v
        with self._lock:
            try:
                data = (json.dumps(row, default=str) + "\n").encode()
                fd = self._ensure_open()
                os.write(fd, data)  # one write = never interleaved
                self.rows_written += 1
                self._maybe_rotate()
            except Exception as e:
                self.rows_dropped += 1
                if self.rows_dropped == 1:
                    logger.error(
                        "flight recorder: append failed (%s) — dropping "
                        "the row and retrying from a fresh open next "
                        "event (further drops counted silently)", e,
                    )
                if self._fd is not None:
                    try:
                        os.close(self._fd)
                    except OSError:
                        pass
                    self._fd = None

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


def append_external(directory: str, kind: str, **fields: Any) -> None:
    """One-shot append from OUTSIDE the run (the supervisor's restart
    ledger mirrors its decisions here so relaunches land in the same
    timeline as the run's own events). Same single-write contract;
    ``run`` is the external writer's name, correlation is by time."""
    os.makedirs(directory, exist_ok=True)
    files = flight_files(directory)
    path = files[-1] if files else os.path.join(directory, "flight-00001.jsonl")
    row = {"t": round(time.time(), 3), "run": fields.pop("run", "external"),
           "kind": kind}
    row.update({k: v for k, v in fields.items() if v is not None})
    data = (json.dumps(row, default=str) + "\n").encode()
    # same torn-tail seal as FlightRecorder._ensure_open: the exact
    # scenario this writer exists for (the supervisor mirroring a
    # relaunch after a mid-write kill) is the one where the stream's
    # last line may be torn — without the seal this row would
    # concatenate onto it and be lost
    try:
        with open(path, "rb") as f:
            f.seek(-1, os.SEEK_END)
            torn = f.read(1) != b"\n"
    except OSError:
        torn = False
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        if torn:
            os.write(fd, b"\n")
        os.write(fd, data)
    finally:
        os.close(fd)
