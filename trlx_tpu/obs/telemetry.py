"""Self-documenting perf artifacts: the bench-comparable headline
numbers, derived continuously from the trainer's OWN flushed stats.

The aggregator consumes exactly two inputs, both already produced by
the training loop (so the telemetry accounting CANNOT drift from the
trainer's accounting — the r06..r10 bench blindness was five rounds of
numbers living only in someone's terminal):

- per-cycle span snapshots (wall + phase partition) and sample/token
  counts from the rollout loop's honest mask-weighted ledger
  (``rollout/real_tokens`` — pad emissions are NOT tokens);
- the flushed tracker stats (engine occupancy / refills / reclaimed
  pages, losses), tapped at the single ``_tracker_log`` funnel.

``telemetry.json`` is committed alongside every checkpoint and
refreshed at the flight-dir root, provenance-stamped (run id, device
kind+count, backend, model geometry, timestamp) so
``scripts/check_bench_sync.py`` accepts it as a legal trajectory
artifact for docs/benchmarks.md rows — every TPU run records an
r05-comparable point even when nobody runs ``bench.py --record``.

The MFU estimate is analytic (2P FLOPs/token forward, 6P train
fwd+bwd, ref/experience forwards counted once each), reusing the
memory doctor's param accounting (:func:`tree_param_count`) for P —
an ESTIMATE for trend lines, not a profiler measurement; the field is
named ``mfu_estimate`` accordingly.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

# bf16 dense-matmul peak per chip, by device kind (same table bench.py
# carries; duplicated rather than imported — bench.py is a script, not
# a package module)
PEAK_TFLOPS = {
    "TPU v4": 275.0, "TPU v5 lite": 197.0, "TPU v5": 459.0,
    "TPU v6 lite": 918.0,
}


def tree_param_count(tree) -> int:
    """Float-leaf element count of a param tree — the memory doctor's
    param accounting (``memdoctor._float_leaves``) reduced to a count
    instead of bytes, so the MFU numerator and the HBM plan size the
    same tree the same way."""
    import numpy as np

    from trlx_tpu.utils.memdoctor import _float_leaves

    total = 0
    for leaf in _float_leaves(tree):
        shape = getattr(leaf, "shape", ())
        total += int(np.prod(shape, dtype=np.int64)) if shape else 1
    return total


def chip_peak_tflops(device_kind: str) -> float:
    for key, peak in sorted(PEAK_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if device_kind.startswith(key):
            return peak
    return 197.0  # conservative default for unknown chips


def device_provenance() -> Dict[str, Any]:
    """Best-effort device stamp (CPU containers stamp honestly as
    cpu — the r09/r10 lesson: a non-TPU artifact must SAY so)."""
    try:
        import jax

        devs = jax.devices()
        return {
            "backend": jax.default_backend(),
            "device_kind": devs[0].device_kind,
            "device_count": len(devs),
            "comparable": jax.default_backend() == "tpu",
        }
    except Exception:
        return {"backend": "unknown", "device_kind": "unknown",
                "device_count": 0, "comparable": False}


# tracker-stat keys mirrored into the per-cycle rows / headline (means
# over the cycle's chunks, flush-cadence attribution)
_ENGINE_KEYS = (
    "rollout/engine_occupancy",
    "rollout/engine_refills",
    "rollout/engine_decode_steps",
    "rollout/engine_reclaimed_pages",
    "rollout/token_occupancy",
    "rollout/truncation_rate",
)


class TelemetryAggregator:
    """Rolling per-cycle ledger + run totals + headline derivation."""

    def __init__(self, window: int = 8, max_cycles: int = 64):
        self.window = max(int(window), 1)
        self.max_cycles = max(int(max_cycles), self.window + 1)
        self.cycles: List[Dict[str, Any]] = []  # bounded tail
        self.cycle_count = 0  # total cycles ever (survives the tail)
        # run totals (persisted across resume so the trajectory point
        # covers the whole run, not just the last incarnation)
        self.total_samples = 0
        self.total_real_tokens = 0.0
        self.total_wall_s = 0.0
        self.total_train_steps = 0
        # staged by the rollout loop, consumed by the next cycle close
        self._pending_samples = 0
        self._pending_tokens = 0.0
        self._last_stats: Dict[str, float] = {}
        # model/static facts, set once by the trainer
        self.static: Dict[str, Any] = {}
        self._param_count: Optional[int] = None

    # -- inputs ----------------------------------------------------------

    def set_static(self, **facts: Any) -> None:
        self.static.update({k: v for k, v in facts.items() if v is not None})

    def set_param_count(self, n: int) -> None:
        self._param_count = int(n)

    def note_samples(self, n: int) -> None:
        self._pending_samples += int(n)

    def note_tokens(self, n: float) -> None:
        self._pending_tokens += float(n)

    def observe_stats(self, stats: Dict[str, Any]) -> None:
        for k in _ENGINE_KEYS:
            v = stats.get(k)
            if isinstance(v, (int, float)):
                self._last_stats[k.split("/", 1)[1]] = float(v)

    def close_cycle(
        self, wall_s: float, breakdown: Dict[str, float],
        step: Optional[int] = None, policy_version: Optional[int] = None,
        n_steps: int = 0,
    ) -> Dict[str, Any]:
        """Fold one closed cycle in; returns the cycle row (what the
        flight recorder writes)."""
        self.cycle_count += 1
        samples, self._pending_samples = self._pending_samples, 0
        tokens, self._pending_tokens = self._pending_tokens, 0.0
        self.total_samples += samples
        self.total_real_tokens += tokens
        self.total_wall_s += wall_s
        self.total_train_steps += int(n_steps)
        row: Dict[str, Any] = {
            "cycle": self.cycle_count,
            "step": step,
            "pv": policy_version,
            "wall_s": round(wall_s, 4),
            "phases": {k: round(v, 4) for k, v in sorted(breakdown.items())},
            "samples": samples,
            "real_tokens": round(tokens, 1),
            "train_steps": int(n_steps),
        }
        if samples and wall_s > 0:
            row["samples_per_sec"] = round(samples / wall_s, 3)
        if self._last_stats:
            row["engine"] = {
                k: round(v, 4) for k, v in sorted(self._last_stats.items())
            }
            # provenance: WHICH decode implementation (static sampler /
            # engine xla gather / engine pallas kernel, x lane groups)
            # produced the tokens behind these numbers
            if self.static.get("decode_impl"):
                row["engine"]["decode_impl"] = self.static["decode_impl"]
        self.cycles.append(row)
        del self.cycles[: max(len(self.cycles) - self.max_cycles, 0)]
        return row

    # -- derivation ------------------------------------------------------

    def _window_rows(self) -> List[Dict[str, Any]]:
        # exclude cycle 1 (compile-dominated) from the steady-state
        # headline whenever later cycles exist
        rows = [
            r for r in self.cycles
            if r["cycle"] > 1 and r.get("samples", 0) > 0
        ]
        if not rows:
            rows = [r for r in self.cycles if r.get("samples", 0) > 0]
        if not rows:
            # offline trainers (DPO/SFT/ILQL) never collect rollout
            # samples — the phase attribution must still ride the
            # headline, just without the samples/s keys
            rows = [r for r in self.cycles if r["cycle"] > 1] or list(self.cycles)
        return rows[-self.window:]

    def headline(self) -> Dict[str, Any]:
        rows = self._window_rows()
        out: Dict[str, Any] = {
            "cycles": self.cycle_count,
            "total_samples": self.total_samples,
            "total_real_tokens": round(self.total_real_tokens, 1),
            "total_wall_s": round(self.total_wall_s, 3),
            "total_train_steps": self.total_train_steps,
        }
        if self.total_wall_s > 0 and self.total_samples:
            out["run_samples_per_sec"] = round(
                self.total_samples / self.total_wall_s, 3
            )
        wall = sum(r["wall_s"] for r in rows)
        samples = sum(r.get("samples", 0) for r in rows)
        tokens = sum(r.get("real_tokens", 0.0) for r in rows)
        if wall > 0 and samples:
            out["samples_per_sec"] = round(samples / wall, 3)
        if wall > 0 and tokens:
            out["real_tokens_per_sec"] = round(tokens / wall, 1)
        # aggregate phase breakdown over the window (seconds + share)
        phases: Dict[str, float] = {}
        for r in rows:
            for k, v in r.get("phases", {}).items():
                phases[k] = phases.get(k, 0.0) + v
        if phases and wall > 0:
            out["phase_s"] = {k: round(v, 3) for k, v in sorted(phases.items())}
            out["phase_share"] = {
                k: round(v / wall, 4) for k, v in sorted(phases.items())
            }
            out["slowest_phase"] = max(phases.items(), key=lambda kv: kv[1])[0]
        if self._last_stats:
            out["engine"] = {
                k: round(v, 4) for k, v in sorted(self._last_stats.items())
            }
        # kernel attribution for the headline: a recorded telemetry.json
        # must say which decode implementation its tok/s number came
        # from (static sampler vs engine-paged-xla vs engine-paged-
        # pallas, x lane groups) — the same honesty rule as the bench
        # pillars' per-pillar attribution
        if self.static.get("decode_impl"):
            out["decode_impl"] = self.static["decode_impl"]
        mfu = self.mfu_estimate(rows)
        if mfu is not None:
            out["mfu_estimate"] = mfu
        return out

    def mfu_estimate(self, rows: List[Dict[str, Any]]) -> Optional[float]:
        """Analytic model-FLOPs utilization over the window: generated
        tokens pay one policy forward (2P), experience pays policy+ref
        teacher-forced forwards (4P per sample-token), train steps pay
        fwd+bwd (6P per trained token). P from the memory doctor's
        param accounting; peak from the device kind. None when any
        input is unknown (CPU runs report no MFU rather than a fake)."""
        if not self._param_count or not rows:
            return None
        prov = self.static.get("device") or {}
        if not prov.get("comparable"):
            return None
        seq = self.static.get("seq_length") or 0
        batch = self.static.get("batch_size") or 0
        if not (seq and batch):
            return None
        wall = sum(r["wall_s"] for r in rows)
        if wall <= 0:
            return None
        p = float(self._param_count)
        gen_tokens = sum(r.get("real_tokens", 0.0) for r in rows)
        exp_tokens = sum(r.get("samples", 0) for r in rows) * seq
        train_tokens = sum(r.get("train_steps", 0) for r in rows) * batch * seq
        flops = 2.0 * p * gen_tokens + 4.0 * p * exp_tokens + 6.0 * p * train_tokens
        peak = (
            chip_peak_tflops(prov.get("device_kind", "")) * 1e12
            * max(int(prov.get("device_count", 1)), 1)
        )
        return round(flops / wall / peak, 4)

    # -- snapshot / persistence ------------------------------------------

    def snapshot(
        self, run_id: str, events_tail: Optional[Dict[str, list]] = None,
    ) -> Dict[str, Any]:
        """The ``telemetry.json`` payload: provenance + headline +
        per-cycle tail + recent events."""
        device = self.static.get("device") or device_provenance()
        snap: Dict[str, Any] = {
            "format": 1,
            "provenance": {
                "run_id": run_id,
                "written_at": round(time.time(), 3),
                **device,
                **{k: v for k, v in self.static.items() if k != "device"},
                "param_count": self._param_count,
            },
            "headline": self.headline(),
            "cycles": self.cycles[-self.window:],
        }
        if events_tail:
            snap["events"] = events_tail
        return snap

    def state_dict(self) -> Dict[str, Any]:
        return {
            "cycle_count": self.cycle_count,
            "total_samples": self.total_samples,
            "total_real_tokens": self.total_real_tokens,
            "total_wall_s": self.total_wall_s,
            "total_train_steps": self.total_train_steps,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.cycle_count = int(state.get("cycle_count", 0))
        self.total_samples = int(state.get("total_samples", 0))
        self.total_real_tokens = float(state.get("total_real_tokens", 0.0))
        self.total_wall_s = float(state.get("total_wall_s", 0.0))
        self.total_train_steps = int(state.get("total_train_steps", 0))
