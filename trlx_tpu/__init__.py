"""trlx_tpu — a TPU-native (JAX/XLA/pjit/pallas) RLHF framework with the
capability surface of trlx: PPO, ILQL, SFT and RFT fine-tuning of causal
and seq2seq language models, from one chip to multi-host pods via a
single sharding-polymorphic trainer (mesh axes dp/fsdp/tp/sp).
"""

__version__ = "0.1.0"

from trlx_tpu import utils  # noqa: F401
from trlx_tpu.api import train  # noqa: F401
from trlx_tpu.data.configs import TRLConfig  # noqa: F401
from trlx_tpu.utils import logging  # noqa: F401
