"""Sharded experience queue: ordered, deduplicating, bounded.

The queue is the delivery half of the experience transport
(``trlx_tpu/exp/__init__.py``): producers ``offer`` finished chunks,
the consumer ``poll``s them back **in chunk-sequence order** and
advances a **committed cursor** once a chunk has actually been pushed
to the rollout store. The semantics are chosen so at-least-once
delivery composes with exactly-once consumption:

- every chunk carries a ``(epoch, chunk_seq)`` id, monotonically
  increasing within an epoch (the epoch bumps when a guardrail
  requeue/rollback rebuilds the data stream — in-flight chunks from the
  old generation can then never be confused with replayed ones);
- a redelivered id — one at-or-below the committed cursor, or one
  already buffered — is dropped as a duplicate (consumer-side dedup);
- out-of-order arrivals are buffered until the gap fills; ``poll`` only
  ever hands out ``cursor + 1``, so the consumed sequence is invariant
  to delivery interleaving (the property tests/test_exp_queue.py
  fuzzes);
- ``offer`` reports ``"full"`` once ``max_depth`` unconsumed chunks are
  buffered — the producer-side back-pressure signal (the learner lags);
  the transport turns it into a bounded, watchdog-beating wait.

The committed cursor is what the trainer persists in ``state.json``
(inside the atomic checkpoint commit + integrity manifest), so a
resume/rollback replays exactly the unconsumed chunks: the PR 4
group-invariant prompt stream regenerates any lost-in-flight chunk
deterministically from its stream position.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

STALENESS_MODES = ("reject", "clip")

# offer() outcomes (strings so transport stats/tests read plainly)
OFFER_ACCEPTED = "accepted"
OFFER_DUPLICATE = "duplicate"
OFFER_FULL = "full"
OFFER_STALE_EPOCH = "stale_epoch"


@dataclass(frozen=True)
class StalenessConfig:
    """Parsed ``ppo.exp.staleness`` section.

    mode           ``reject``: drop a chunk older than ``max_staleness``
                   policy versions (it is re-dispatched and regenerated
                   with the current policy); ``clip``: admit it with
                   IMPACT-style clipped importance weights threaded into
                   the PPO surrogate as a per-token correction factor
                   (arXiv:1912.00167).
    max_staleness  versions-at-consumption minus version-at-generation a
                   chunk may carry before the gate acts. The default 1
                   admits the ``overlap_rollouts`` prefetch (one update
                   stale by construction) untouched.
    clip_c         symmetric clip range for the importance correction in
                   ``clip`` mode: weights land in [1-clip_c, 1+clip_c].
    """

    mode: str = "reject"
    max_staleness: int = 1
    clip_c: float = 0.3

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "StalenessConfig":
        d = dict(d or {})
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"exp.staleness: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        cfg = cls(**d)
        if cfg.mode not in STALENESS_MODES:
            raise ValueError(
                f"exp.staleness.mode must be one of {STALENESS_MODES}, "
                f"got {cfg.mode!r}"
            )
        if cfg.max_staleness < 0:
            raise ValueError("exp.staleness.max_staleness must be >= 0")
        return cfg


@dataclass(frozen=True)
class ExpConfig:
    """Parsed ``ppo.exp`` section (plain dict in YAML).

    enabled          master switch (default off: the rollout loop keeps
                     the direct path; on, and fault-free, the transport
                     path is golden-checked bit-equal to it).
    max_depth        unconsumed chunks the queue buffers before
                     ``offer`` reports back-pressure and producers
                     block/shed.
    lease_ttl_s      seconds a production lease may go without a
                     heartbeat before it is considered dead and its
                     chunk re-dispatched to a live producer.
    offer_timeout_s  bound on one back-pressure wait before the
                     producer gives up the attempt (the wait itself
                     heartbeats the ``exp_wait`` watchdog phase); 0
                     waits indefinitely — the watchdog deadline is then
                     the backstop.
    wait_poll_s      poll cadence (and beat cadence) of the bounded
                     waits: back-pressure and lease-expiry.
    staleness        :class:`StalenessConfig` (``mode``/
                     ``max_staleness``/``clip_c``).
    """

    enabled: bool = False
    max_depth: int = 4
    lease_ttl_s: float = 30.0
    offer_timeout_s: float = 600.0
    wait_poll_s: float = 0.05
    staleness: StalenessConfig = field(default_factory=StalenessConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ExpConfig":
        d = dict(d or {})
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"ppo.exp: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        if "staleness" in d:
            d["staleness"] = StalenessConfig.from_dict(d["staleness"])
        cfg = cls(**d)
        if cfg.max_depth < 1:
            raise ValueError("exp.max_depth must be >= 1")
        if cfg.lease_ttl_s <= 0:
            raise ValueError("exp.lease_ttl_s must be > 0")
        return cfg


@dataclass
class ExperienceChunk:
    """One unit of delivered experience.

    chunk_id        ``(epoch, chunk_seq)``: epoch = data-stream
                    generation (bumped on guardrail requeue/rollback),
                    seq = monotonically increasing chunk index within
                    the epoch — for PPO, the prompt-stream chunk
                    position, so a lost chunk regenerates from the
                    group-invariant stream.
    policy_version  optimizer cycles applied when the chunk's samples
                    were GENERATED; the admission gate compares it
                    against the version at consumption (staleness
                    metadata).
    payload         the finished experience (PPO: the assembled
                    PPORolloutBatch) — opaque to the queue.
    meta            host-side stats riding along (chunk stats dict,
                    row counts).
    """

    chunk_id: Tuple[int, int]
    policy_version: int
    payload: Any = None
    meta: Dict[str, Any] = field(default_factory=dict)

    @property
    def epoch(self) -> int:
        return self.chunk_id[0]

    @property
    def seq(self) -> int:
        return self.chunk_id[1]


class ExperienceQueue:
    """Bounded, ordered, deduplicating chunk buffer (host-side only).

    The consumer cursor counts COMMITTED chunks of the current epoch:
    ``poll`` hands out seq ``cursor + 1`` when buffered, and
    :meth:`commit` advances the cursor once the chunk's payload reached
    the store. ``offer`` never blocks — the bounded wait (with watchdog
    beats) is the transport's job, so this class stays fake-clock-free
    and exhaustively testable."""

    def __init__(self, max_depth: int):
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        self.max_depth = int(max_depth)
        self.epoch = 0
        self._cursor = 0  # highest committed seq of the current epoch
        self._buffered: Dict[int, ExperienceChunk] = {}
        self.stats: Dict[str, int] = {
            "accepted": 0,
            "duplicates": 0,
            "full_rejections": 0,
            "stale_epoch_drops": 0,
            "committed": 0,
        }

    # -- producer side ---------------------------------------------------

    def offer(self, chunk: ExperienceChunk) -> str:
        """Deliver a chunk. Returns one of ``accepted`` / ``duplicate``
        (consumer-side dedup: at-or-below the cursor, or already
        buffered) / ``full`` (back-pressure: ``max_depth`` unconsumed
        chunks pending) / ``stale_epoch`` (the data stream was rebuilt
        under this chunk — its prompts will be replayed under the new
        epoch, so the old delivery must not train)."""
        if chunk.epoch != self.epoch:
            self.stats["stale_epoch_drops"] += 1
            logger.warning(
                "exp queue: dropping chunk %s from epoch %d (current "
                "epoch %d — the data stream was rebuilt under it)",
                chunk.chunk_id, chunk.epoch, self.epoch,
            )
            return OFFER_STALE_EPOCH
        if chunk.seq <= self._cursor or chunk.seq in self._buffered:
            self.stats["duplicates"] += 1
            logger.info(
                "exp queue: dropping duplicate delivery of chunk %s "
                "(cursor %d)", chunk.chunk_id, self._cursor,
            )
            return OFFER_DUPLICATE
        if len(self._buffered) >= self.max_depth:
            self.stats["full_rejections"] += 1
            return OFFER_FULL
        self._buffered[chunk.seq] = chunk
        self.stats["accepted"] += 1
        return OFFER_ACCEPTED

    # -- consumer side ---------------------------------------------------

    def poll(self) -> Optional[ExperienceChunk]:
        """The next in-order chunk (seq ``cursor + 1``), or None when it
        has not been delivered yet. Does NOT advance the cursor — call
        :meth:`commit` after the payload reached the store, so a crash
        between poll and push replays the chunk instead of losing it."""
        return self._buffered.get(self._cursor + 1)

    def commit(self, chunk: ExperienceChunk) -> None:
        """Mark ``chunk`` consumed: advance the committed cursor and
        drop the buffer entry. Must be the chunk :meth:`poll` returned
        (in-order consumption is the queue's contract)."""
        if chunk.seq != self._cursor + 1:
            raise ValueError(
                f"out-of-order commit: chunk seq {chunk.seq} but cursor "
                f"is {self._cursor} (expected {self._cursor + 1})"
            )
        self._buffered.pop(chunk.seq, None)
        self._cursor = chunk.seq
        self.stats["committed"] += 1

    def discard(self, chunk: ExperienceChunk) -> None:
        """Drop a buffered chunk WITHOUT advancing the cursor (staleness
        rejection: the seq will be re-dispatched and redelivered)."""
        self._buffered.pop(chunk.seq, None)

    # -- state -----------------------------------------------------------

    @property
    def depth(self) -> int:
        return len(self._buffered)

    @property
    def cursor(self) -> int:
        return self._cursor

    def next_undelivered(self) -> int:
        """Smallest seq > cursor not currently buffered — the next gap
        an in-order consumer is waiting on."""
        seq = self._cursor + 1
        while seq in self._buffered:
            seq += 1
        return seq

    def advance_epoch(self) -> int:
        """Invalidate every in-flight chunk: bump the epoch, clear the
        buffer, reset the cursor (the rebuilt data stream replays from
        its own position; seqs restart with it)."""
        self.epoch += 1
        self._buffered.clear()
        self._cursor = 0
        return self.epoch

    def load_cursor(self, epoch: int, cursor: int) -> None:
        """Resume: restore the committed consumer position (the buffer
        is empty by construction — in-flight chunks never persist; the
        prompt stream regenerates them)."""
        self.epoch = int(epoch)
        self._cursor = int(cursor)
        self._buffered.clear()

    def state_summary(self) -> Dict[str, Any]:
        return {
            "epoch": self.epoch,
            "cursor": self._cursor,
            "depth": self.depth,
            **self.stats,
        }
