"""Resilient experience transport: the substrate for disaggregated
async actor–learner training (ROADMAP item 1, IMPACT/OPPO in PAPERS.md).

Rollout producers and the learner stop sharing one lockstep loop here:
experience travels through a durable, sharded queue with at-least-once
delivery and lease-based production, so the failure semantics of the
experience path — a worker dying mid-chunk, duplicate delivery on
retry, stale batches corrupting the PPO surrogate — are owned by one
chaos-proven layer instead of leaking into every trainer.

  queue.py      bounded FIFO of experience chunks keyed by a
                monotonically increasing ``(epoch, chunk_seq)`` id, with
                consumer-side dedup (redelivered ids dropped), in-order
                consumption, back-pressure past ``exp.max_depth``, and a
                persisted consumer cursor (committed inside the atomic
                checkpoint via the trainer's ``state.json``). Also the
                staleness admission gate (``exp.staleness.mode:
                reject|clip``) and the parsed ``ppo.exp`` config.
  leases.py     per-chunk production leases with watchdog-style
                heartbeats; an expired lease (worker death, stall) is
                reclaimed and its chunk re-dispatched to a live
                producer.
  transport.py  the orchestrator the trainers drive: produce-side
                ``begin_chunk``/``deliver`` (lease + back-pressure),
                consume-side ``poll``/``admit``/``committed`` (dedup +
                staleness), epoch aborts for guardrail requeue/rollback,
                and ``state_dict``/``load_state_dict`` for resume.
  net.py        the PROCESS-BOUNDARY substrate: the pluggable topic/
                message transport (atomic-rename shared-fs, or a tcp
                hub) that carries fleet chunk dispatch/delivery and
                the serving tier's request/response traffic across
                machines. ``transport.py`` is the delivery state
                machine; ``net.py`` is the wire it can ride.

Everything here is pure host-side bookkeeping — no jax at module scope
— with injectable clocks, so tier-1 tests cover every delivery
interleaving on a fake clock (tests/test_exp_queue.py).
"""

from trlx_tpu.exp.leases import Lease, LeaseTable
from trlx_tpu.exp.queue import (
    ExpConfig,
    ExperienceChunk,
    ExperienceQueue,
    StalenessConfig,
)
from trlx_tpu.exp.transport import ExperienceTransport

__all__ = [
    "ExpConfig",
    "ExperienceChunk",
    "ExperienceQueue",
    "ExperienceTransport",
    "Lease",
    "LeaseTable",
    "StalenessConfig",
]
