"""Pluggable message transport: the process-boundary substrate shared
by the experience/fleet plumbing (trlx_tpu/fleet/) and the serving tier
(trlx_tpu/serve/).

Before this module the atomic-rename shared-filesystem protocol
(fleet/serde.py ``commit_message_dir``/``read_message_dir``) was wired
directly into the fleet coordinator and worker. It is now ONE backend
behind a small interface, so the learner, the rollout fleet and the
serving frontend can cross a real machine boundary by swapping config,
not code:

  shared_fs   the golden path: topic = a subdirectory, message = an
              atomically-renamed ``{meta.json, arrays.npz}`` dir.
              Byte-identical layout to the pre-interface fleet — the
              refactor is behavior-preserving by construction (the
              backend calls the very same serde functions).
  tcp         a socket/RPC backend: one :class:`TcpHub` process holds
              the topic store in memory; clients PUT/GET/LIST/DELETE
              over length-prefixed JSON+binary frames. Delivery is
              at-least-once with consumer-visible dedup — a PUT of an
              existing (topic, name) reports ``duplicate`` exactly like
              the shared-fs rename race — so a dropped/retried message
              (chaos ``serve_transport_drop``) converges to
              exactly-once.

The message model is deliberately tiny: a *topic* (mailbox) holding
named messages, each a JSON-safe ``meta`` dict plus an optional dict of
numpy arrays. Names are unique per topic; a second put of the same name
is a no-op returning False. That single primitive covers fleet chunk
dispatch/delivery and serve request/response traffic; richer semantics
(ordering, leases, staleness) stay where they are — in exp/transport.py
and the consumers — on top of it.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

Message = Tuple[Dict[str, Any], Dict[str, np.ndarray]]


class Transport:
    """Topic/message transport interface (see module docstring)."""

    def put(
        self,
        topic: str,
        name: str,
        meta: Dict[str, Any],
        arrays: Optional[Dict[str, np.ndarray]] = None,
        meta_name: str = "meta.json",
    ) -> bool:
        """Publish a message. Returns False when (topic, name) already
        exists — the racing-duplicate outcome callers treat as
        success-by-dedup."""
        raise NotImplementedError

    def get(
        self, topic: str, name: str, meta_name: str = "meta.json"
    ) -> Optional[Message]:
        """The committed message, or None when absent/not yet landed."""
        raise NotImplementedError

    def get_meta(
        self, topic: str, name: str, meta_name: str = "meta.json"
    ) -> Optional[Dict[str, Any]]:
        """Meta-only read (cheap routing without the arrays payload)."""
        raise NotImplementedError

    def list(self, topic: str) -> List[str]:
        """Committed message names in the topic, sorted."""
        raise NotImplementedError

    def delete(self, topic: str, name: str) -> None:
        """Drop a message (idempotent; absent is fine)."""
        raise NotImplementedError

    def delete_prefix(self, topic: str, prefix: str) -> None:
        for name in self.list(topic):
            if name.startswith(prefix):
                self.delete(topic, name)

    def close(self) -> None:
        pass


class SharedFSTransport(Transport):
    """The atomic-rename shared-filesystem backend — the pre-interface
    fleet protocol verbatim (delegates to fleet/serde.py, so the wire
    layout stays golden bit-equal: ``<root>/<topic>/<name>/{<meta_name>,
    arrays.npz}``)."""

    def __init__(self, root: str):
        self.root = root

    def _dir(self, topic: str, name: str = "") -> str:
        return os.path.join(self.root, topic, name) if name else os.path.join(
            self.root, topic
        )

    def put(self, topic, name, meta, arrays=None, meta_name="meta.json"):
        from trlx_tpu.fleet import serde

        return serde.commit_message_dir(
            self._dir(topic, name), meta, dict(arrays or {}),
            meta_name=meta_name,
        )

    def get(self, topic, name, meta_name="meta.json"):
        from trlx_tpu.fleet import serde

        return serde.read_message_dir(
            self._dir(topic, name), meta_name=meta_name
        )

    def get_meta(self, topic, name, meta_name="meta.json"):
        from trlx_tpu.fleet import serde

        return serde.read_message_meta(
            self._dir(topic, name), meta_name=meta_name
        )

    def list(self, topic):
        try:
            entries = sorted(os.listdir(self._dir(topic)))
        except OSError:
            return []
        # ".tmp_" entries are half-committed message dirs mid-rename
        return [
            e for e in entries if not e.startswith(".") and ".tmp" not in e
        ]

    def delete(self, topic, name):
        shutil.rmtree(self._dir(topic, name), ignore_errors=True)


# -- TCP backend --------------------------------------------------------
#
# Frame format (both directions): 4-byte big-endian header length, the
# JSON header, then `blob_len` raw bytes (the npz payload). One
# request/response pair per connection — simple, stateless, and immune
# to half-closed-socket bookkeeping; the payloads (rollout chunks,
# serve prompts) dwarf the connect cost.


def _send_frame(sock: socket.socket, header: Dict[str, Any], blob: bytes):
    h = json.dumps(header).encode()
    sock.sendall(struct.pack(">I", len(h)) + h + blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("transport: peer closed mid-frame")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Tuple[Dict[str, Any], bytes]:
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    header = json.loads(_recv_exact(sock, hlen).decode())
    blob = _recv_exact(sock, int(header.get("blob_len", 0)))
    return header, blob


def _pack_arrays(arrays: Optional[Dict[str, np.ndarray]]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in (arrays or {}).items()})
    return buf.getvalue()


def _unpack_arrays(blob: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(blob)) as z:
        return {k: z[k] for k in z.files}


class _HubHandler(socketserver.BaseRequestHandler):
    def handle(self):
        hub: "TcpHub" = self.server.hub  # type: ignore[attr-defined]
        try:
            header, blob = _recv_frame(self.request)
        except (ConnectionError, ValueError, json.JSONDecodeError):
            return
        cmd = header.get("cmd")
        topic = header.get("topic", "")
        name = header.get("name", "")
        resp: Dict[str, Any] = {"ok": True}
        out_blob = b""
        with hub._lock:
            store = hub._topics.setdefault(topic, {})
            if cmd == "put":
                if name in store:
                    resp["status"] = "duplicate"
                else:
                    store[name] = (dict(header.get("meta") or {}), blob)
                    resp["status"] = "accepted"
            elif cmd == "get":
                msg = store.get(name)
                if msg is None:
                    resp["found"] = False
                else:
                    resp["found"] = True
                    resp["meta"] = msg[0]
                    if header.get("meta_only"):
                        out_blob = b""
                    else:
                        out_blob = msg[1]
            elif cmd == "list":
                resp["names"] = sorted(store)
            elif cmd == "delete":
                store.pop(name, None)
            else:
                resp = {"ok": False, "error": f"unknown cmd {cmd!r}"}
        resp["blob_len"] = len(out_blob)
        try:
            _send_frame(self.request, resp, out_blob)
        except OSError:
            pass


class _HubServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TcpHub:
    """In-memory topic store behind a threaded TCP server. Run one next
    to the consumer (the learner / the serving frontend); producers and
    clients connect with :class:`TcpTransport`. Contents are volatile —
    exactly as durable as the consumer process itself, which is the
    right durability class for redeliverable traffic (chunks regenerate
    from replay snapshots, serve requests are client-retried)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = _HubServer((host, port), _HubHandler)
        self._server.hub = self  # type: ignore[attr-defined]
        self._topics: Dict[str, Dict[str, Tuple[Dict[str, Any], bytes]]] = {}
        self._lock = threading.Lock()
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="transport-hub",
            daemon=True,
        )
        self._thread.start()
        logger.info("transport hub listening on %s:%d", self.host, self.port)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class TcpTransport(Transport):
    """Socket client for a :class:`TcpHub`. ``retries`` transparently
    re-sends on connection errors; because PUT is deduplicating by
    (topic, name), the retry loop is idempotent — a lost response whose
    request actually landed converges to ``duplicate``, which callers
    already treat as success."""

    def __init__(
        self,
        host: str,
        port: int,
        retries: int = 3,
        timeout_s: float = 10.0,
        drop_hook=None,
    ):
        self.host, self.port = host, int(port)
        self.retries = int(retries)
        self.timeout_s = float(timeout_s)
        # chaos seam (serve_transport_drop): called before each send;
        # returning True "loses" the frame — the retry loop + hub dedup
        # must make delivery exactly-once anyway
        self.drop_hook = drop_hook
        self.stats = {"sent": 0, "dropped": 0, "retried": 0}

    def _rpc(
        self, header: Dict[str, Any], blob: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                self.stats["retried"] += 1
            if self.drop_hook is not None and self.drop_hook():
                # the frame is "lost on the wire": no send this attempt
                self.stats["dropped"] += 1
                last = ConnectionError("transport: frame dropped (chaos)")
                continue
            try:
                with socket.create_connection(
                    (self.host, self.port), timeout=self.timeout_s
                ) as sock:
                    header = dict(header, blob_len=len(blob))
                    _send_frame(sock, header, blob)
                    self.stats["sent"] += 1
                    return _recv_frame(sock)
            except (OSError, ConnectionError, ValueError) as e:
                last = e
        raise ConnectionError(
            f"transport: rpc {header.get('cmd')!r} to "
            f"{self.host}:{self.port} failed after {self.retries + 1} "
            f"attempts: {last}"
        )

    def put(self, topic, name, meta, arrays=None, meta_name="meta.json"):
        resp, _ = self._rpc(
            {"cmd": "put", "topic": topic, "name": name, "meta": meta},
            _pack_arrays(arrays),
        )
        return resp.get("status") == "accepted"

    def get(self, topic, name, meta_name="meta.json"):
        resp, blob = self._rpc({"cmd": "get", "topic": topic, "name": name})
        if not resp.get("found"):
            return None
        return resp.get("meta") or {}, _unpack_arrays(blob)

    def get_meta(self, topic, name, meta_name="meta.json"):
        resp, _ = self._rpc(
            {"cmd": "get", "topic": topic, "name": name, "meta_only": True}
        )
        return (resp.get("meta") or {}) if resp.get("found") else None

    def list(self, topic):
        resp, _ = self._rpc({"cmd": "list", "topic": topic})
        return list(resp.get("names") or [])

    def delete(self, topic, name):
        self._rpc({"cmd": "delete", "topic": topic, "name": name})


def make_hub_transport(
    spec: Optional[Dict[str, Any]],
) -> Tuple[TcpHub, TcpTransport, Dict[str, Any]]:
    """The SERVER side of the tcp backend (the serving frontend, the
    fleet learner): host the hub the spec names and return ``(hub,
    local client, advertised client spec)`` — remote peers connect
    with the advertised spec via :func:`make_transport`. ``bind``
    (default 127.0.0.1; use 0.0.0.0 to accept remote peers) is the
    listen address, ``host`` the address advertised to peers, ``port``
    0 = ephemeral (the advertised spec carries the real port)."""
    spec = dict(spec or {})
    if spec.pop("backend", None) != "tcp":
        raise ValueError("make_hub_transport: spec.backend must be 'tcp'")
    known = {"host", "port", "retries", "timeout_s", "bind"}
    unknown = set(spec) - known
    if unknown:
        raise ValueError(f"transport (tcp hub): unknown keys {sorted(unknown)}")
    hub = TcpHub(spec.get("bind", "127.0.0.1"), int(spec.get("port", 0)))
    client = TcpTransport(
        "127.0.0.1", hub.port,
        retries=int(spec.get("retries", 3)),
        timeout_s=float(spec.get("timeout_s", 10.0)),
    )
    advertised = {
        "backend": "tcp", "host": spec.get("host", hub.host),
        "port": hub.port,
    }
    return hub, client, advertised


def make_server_transport(
    spec: Optional[Dict[str, Any]], default_root: str
) -> Tuple[Optional[TcpHub], Transport, Dict[str, Any]]:
    """The CONSUMER side's one-stop bootstrap (serving frontend, fleet
    learner): ``(hub_or_None, transport, advertised client spec)``.
    tcp specs host the hub via :func:`make_hub_transport`; everything
    else resolves through :func:`make_transport` (shared-fs peers use
    the advertised root)."""
    spec = dict(spec or {})
    if spec.get("backend") == "tcp":
        return make_hub_transport(spec)
    transport = make_transport(spec, default_root)
    return None, transport, {
        "backend": "shared_fs", "root": spec.get("root") or default_root,
    }


def make_transport(
    spec: Optional[Dict[str, Any]], default_root: str
) -> Transport:
    """Config -> backend (the CLIENT side for tcp). ``spec`` keys:
    ``backend`` ("shared_fs", default, or "tcp"), ``root``
    (shared_fs), ``host``/``port`` (tcp client; ``bind`` is tolerated
    so server and client can share one spec dict),
    ``retries``/``timeout_s`` (tcp). Unknown keys fail loudly — a
    typo'd backend must not silently fall back to the default."""
    spec = dict(spec or {})
    backend = spec.pop("backend", "shared_fs")
    known = {
        "shared_fs": {"root"},
        "tcp": {"host", "port", "retries", "timeout_s", "bind"},
    }
    if backend not in known:
        raise ValueError(
            f"transport.backend must be one of {sorted(known)}, "
            f"got {backend!r}"
        )
    unknown = set(spec) - known[backend]
    if unknown:
        raise ValueError(
            f"transport ({backend}): unknown keys {sorted(unknown)}"
        )
    if backend == "tcp":
        if "port" not in spec:
            raise ValueError("transport.backend tcp needs host/port")
        return TcpTransport(
            spec.get("host", "127.0.0.1"), spec["port"],
            retries=int(spec.get("retries", 3)),
            timeout_s=float(spec.get("timeout_s", 10.0)),
        )
    return SharedFSTransport(spec.get("root") or default_root)
