"""Pluggable message transport: the process-boundary substrate shared
by the experience/fleet plumbing (trlx_tpu/fleet/) and the serving tier
(trlx_tpu/serve/).

Before this module the atomic-rename shared-filesystem protocol
(fleet/serde.py ``commit_message_dir``/``read_message_dir``) was wired
directly into the fleet coordinator and worker. It is now ONE backend
behind a small interface, so the learner, the rollout fleet and the
serving frontend can cross a real machine boundary by swapping config,
not code:

  shared_fs   the golden path: topic = a subdirectory, message = an
              atomically-renamed ``{meta.json, arrays.npz}`` dir.
              Byte-identical layout to the pre-interface fleet — the
              refactor is behavior-preserving by construction (the
              backend calls the very same serde functions).
  tcp         a socket/RPC backend: one :class:`TcpHub` process holds
              the topic store in memory; clients PUT/GET/LIST/DELETE
              over length-prefixed JSON+binary frames. Delivery is
              at-least-once with consumer-visible dedup — a PUT of an
              existing (topic, name) reports ``duplicate`` exactly like
              the shared-fs rename race — so a dropped/retried message
              (chaos ``serve_transport_drop``) converges to
              exactly-once.

The message model is deliberately tiny: a *topic* (mailbox) holding
named messages, each a JSON-safe ``meta`` dict plus an optional dict of
numpy arrays. Names are unique per topic; a second put of the same name
is a no-op returning False. That single primitive covers fleet chunk
dispatch/delivery and serve request/response traffic; richer semantics
(ordering, leases, staleness) stay where they are — in exp/transport.py
and the consumers — on top of it.

Alongside immutable messages the interface carries RECORDS: small
mutable JSON documents with last-write-wins semantics (``put_record``
/ ``get_record`` / ``list_records`` / ``delete_record``). Records are
what the fleet CONTROL PLANE is made of — membership epochs, worker
heartbeats, quarantine verdicts, the shutdown flag, broadcast
manifests and the CURRENT pointer — so once they ride the transport,
a worker fleet needs NO shared filesystem at all. On the shared-fs
backend a record (topic, name) is exactly ``<root>/<topic>/<name>.json``
written atomically, which makes the refactor byte-identical to the
pre-records fleet layout (``membership.json``, ``workers/<id>.json``,
…).

Fault injection: :class:`FaultyTransport` wraps any backend with a
deterministic, seed-driven per-link fault schedule (drop / delay /
duplicate / reorder / partition) using the SAME entry grammar and
per-fault RNG-stream discipline as ``utils/chaos.py`` (append-only
fault tuple, one ``random.Random(seed * 1_000_003 + i)`` stream per
fault), so a hostile network is a reproducible test, not a flake
generator. Configure it with a ``faults`` sub-dict in any transport
spec, or wrap programmatically in tests.
"""

from __future__ import annotations

import io
import json
import os
import random
import shutil
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.utils import logging
from trlx_tpu.utils.resilient import (
    DeadlineExceeded,
    call_with_deadline,
    compute_backoff,
)

logger = logging.get_logger(__name__)

Message = Tuple[Dict[str, Any], Dict[str, np.ndarray]]


class Transport:
    """Topic/message transport interface (see module docstring)."""

    def put(
        self,
        topic: str,
        name: str,
        meta: Dict[str, Any],
        arrays: Optional[Dict[str, np.ndarray]] = None,
        meta_name: str = "meta.json",
    ) -> bool:
        """Publish a message. Returns False when (topic, name) already
        exists — the racing-duplicate outcome callers treat as
        success-by-dedup."""
        raise NotImplementedError

    def get(
        self, topic: str, name: str, meta_name: str = "meta.json"
    ) -> Optional[Message]:
        """The committed message, or None when absent/not yet landed."""
        raise NotImplementedError

    def get_meta(
        self, topic: str, name: str, meta_name: str = "meta.json"
    ) -> Optional[Dict[str, Any]]:
        """Meta-only read (cheap routing without the arrays payload)."""
        raise NotImplementedError

    def list(self, topic: str) -> List[str]:
        """Committed message names in the topic, sorted."""
        raise NotImplementedError

    def delete(self, topic: str, name: str) -> None:
        """Drop a message (idempotent; absent is fine)."""
        raise NotImplementedError

    def delete_prefix(self, topic: str, prefix: str) -> None:
        for name in self.list(topic):
            if name.startswith(prefix):
                self.delete(topic, name)

    # -- records: mutable last-write-wins JSON documents ------------------
    #
    # Messages are immutable (second put dedups); records are the
    # opposite — rewritten in place on every heartbeat / pointer flip.
    # Both live in the same topic namespace without colliding: on
    # shared-fs a record is a ``<name>.json`` FILE where a message is a
    # directory, and ``list``/``list_records`` each see only their own
    # kind.

    def put_record(self, topic: str, name: str, meta: Dict[str, Any]) -> None:
        """Write (or atomically overwrite) a record."""
        raise NotImplementedError

    def get_record(self, topic: str, name: str) -> Optional[Dict[str, Any]]:
        """The record, or None when absent (a torn/mid-write record
        also reads as absent — the writer side is atomic)."""
        raise NotImplementedError

    def list_records(self, topic: str) -> List[str]:
        """Record names in the topic, sorted."""
        raise NotImplementedError

    def delete_record(self, topic: str, name: str) -> None:
        """Drop a record (idempotent; absent is fine)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class SharedFSTransport(Transport):
    """The atomic-rename shared-filesystem backend — the pre-interface
    fleet protocol verbatim (delegates to fleet/serde.py, so the wire
    layout stays golden bit-equal: ``<root>/<topic>/<name>/{<meta_name>,
    arrays.npz}``)."""

    def __init__(self, root: str):
        self.root = root

    def _dir(self, topic: str, name: str = "") -> str:
        return os.path.join(self.root, topic, name) if name else os.path.join(
            self.root, topic
        )

    def put(self, topic, name, meta, arrays=None, meta_name="meta.json"):
        from trlx_tpu.fleet import serde

        return serde.commit_message_dir(
            self._dir(topic, name), meta, dict(arrays or {}),
            meta_name=meta_name,
        )

    def get(self, topic, name, meta_name="meta.json"):
        from trlx_tpu.fleet import serde

        return serde.read_message_dir(
            self._dir(topic, name), meta_name=meta_name
        )

    def get_meta(self, topic, name, meta_name="meta.json"):
        from trlx_tpu.fleet import serde

        return serde.read_message_meta(
            self._dir(topic, name), meta_name=meta_name
        )

    def list(self, topic):
        try:
            entries = sorted(os.listdir(self._dir(topic)))
        except OSError:
            return []
        # ".tmp_" entries are half-committed message dirs mid-rename;
        # plain files are RECORDS (``<name>.json``), not messages
        return [
            e for e in entries
            if not e.startswith(".") and ".tmp" not in e
            and os.path.isdir(self._dir(topic, e))
        ]

    def delete(self, topic, name):
        shutil.rmtree(self._dir(topic, name), ignore_errors=True)

    # -- records (``<root>/<topic>/<name>.json``, atomic rewrite) ---------

    def _record_path(self, topic: str, name: str) -> str:
        return os.path.join(self._dir(topic), f"{name}.json")

    def put_record(self, topic, name, meta):
        from trlx_tpu.utils.checkpointing import atomic_json_write

        os.makedirs(self._dir(topic), exist_ok=True)
        atomic_json_write(self._record_path(topic, name), dict(meta))

    def get_record(self, topic, name):
        try:
            with open(self._record_path(topic, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def list_records(self, topic):
        try:
            entries = sorted(os.listdir(self._dir(topic)))
        except OSError:
            return []
        return [
            e[: -len(".json")] for e in entries
            if e.endswith(".json") and not e.startswith(".")
            and ".tmp" not in e
            and os.path.isfile(self._dir(topic, e))
        ]

    def delete_record(self, topic, name):
        try:
            os.remove(self._record_path(topic, name))
        except OSError:
            pass


# -- TCP backend --------------------------------------------------------
#
# Frame format (both directions): 4-byte big-endian header length, the
# JSON header, then `blob_len` raw bytes (the npz payload). One
# request/response pair per connection — simple, stateless, and immune
# to half-closed-socket bookkeeping; the payloads (rollout chunks,
# serve prompts) dwarf the connect cost.


def _send_frame(sock: socket.socket, header: Dict[str, Any], blob: bytes):
    h = json.dumps(header).encode()
    sock.sendall(struct.pack(">I", len(h)) + h + blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("transport: peer closed mid-frame")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Tuple[Dict[str, Any], bytes]:
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    header = json.loads(_recv_exact(sock, hlen).decode())
    blob = _recv_exact(sock, int(header.get("blob_len", 0)))
    return header, blob


def _pack_arrays(arrays: Optional[Dict[str, np.ndarray]]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in (arrays or {}).items()})
    return buf.getvalue()


def _unpack_arrays(blob: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(blob)) as z:
        return {k: z[k] for k in z.files}


class _HubHandler(socketserver.BaseRequestHandler):
    def handle(self):
        hub: "TcpHub" = self.server.hub  # type: ignore[attr-defined]
        # a half-open peer (died mid-frame, dropped link) must time out
        # instead of pinning this handler thread forever
        self.request.settimeout(hub.handler_timeout_s)
        try:
            header, blob = _recv_frame(self.request)
        except (OSError, ConnectionError, ValueError, json.JSONDecodeError):
            return
        cmd = header.get("cmd")
        topic = header.get("topic", "")
        name = header.get("name", "")
        resp: Dict[str, Any] = {"ok": True}
        out_blob = b""
        with hub._lock:
            store = hub._topics.setdefault(topic, {})
            records = hub._records.setdefault(topic, {})
            if cmd == "put":
                if name in store:
                    resp["status"] = "duplicate"
                else:
                    store[name] = (dict(header.get("meta") or {}), blob)
                    resp["status"] = "accepted"
            elif cmd == "get":
                msg = store.get(name)
                if msg is None:
                    resp["found"] = False
                else:
                    resp["found"] = True
                    resp["meta"] = msg[0]
                    if header.get("meta_only"):
                        out_blob = b""
                    else:
                        out_blob = msg[1]
            elif cmd == "list":
                resp["names"] = sorted(store)
            elif cmd == "delete":
                store.pop(name, None)
            elif cmd == "put_record":
                records[name] = dict(header.get("meta") or {})
            elif cmd == "get_record":
                rec = records.get(name)
                resp["found"] = rec is not None
                if rec is not None:
                    resp["meta"] = rec
            elif cmd == "list_records":
                resp["names"] = sorted(records)
            elif cmd == "delete_record":
                records.pop(name, None)
            else:
                resp = {"ok": False, "error": f"unknown cmd {cmd!r}"}
        resp["blob_len"] = len(out_blob)
        try:
            _send_frame(self.request, resp, out_blob)
        except OSError:
            pass


class _HubServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TcpHub:
    """In-memory topic store behind a threaded TCP server. Run one next
    to the consumer (the learner / the serving frontend); producers and
    clients connect with :class:`TcpTransport`. Contents are volatile —
    exactly as durable as the consumer process itself, which is the
    right durability class for redeliverable traffic (chunks regenerate
    from replay snapshots, serve requests are client-retried)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        handler_timeout_s: float = 30.0,
    ):
        self._server = _HubServer((host, port), _HubHandler)
        self._server.hub = self  # type: ignore[attr-defined]
        self._topics: Dict[str, Dict[str, Tuple[Dict[str, Any], bytes]]] = {}
        self._records: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self.handler_timeout_s = float(handler_timeout_s)
        self._lock = threading.Lock()
        self.host, self.port = self._server.server_address[:2]
        self.restarts = 0
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="transport-hub",
            daemon=True,
        )
        self._thread.start()
        logger.info("transport hub listening on %s:%d", self.host, self.port)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def restart(self) -> None:
        """Crash-and-relaunch in one call (the chaos ``hub_crash``
        body): drop the server AND every volatile topic/record — which
        is exactly what a supervised hub relaunch looks like to its
        clients. Recovery needs no hub-side persistence: clients ride
        their retry/backoff through the outage, workers re-register on
        the next heartbeat, lost dispatches get a fresh attempt number
        from the learner, and re-posted in-flight messages converge
        through the put dedup."""
        self.close()
        with self._lock:
            self._topics.clear()
            self._records.clear()
        self._server = _HubServer((self.host, self.port), _HubHandler)
        self._server.hub = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="transport-hub",
            daemon=True,
        )
        self._thread.start()
        self.restarts += 1
        logger.warning(
            "transport hub restarted (empty) on %s:%d", self.host, self.port
        )


class TcpTransport(Transport):
    """Socket client for a :class:`TcpHub`. ``retries`` transparently
    re-sends on connection errors with backoff+jitter between attempts
    (``resilient.compute_backoff`` — a restarting hub sees a reconnect
    ramp, not a thundering herd); because PUT is deduplicating by
    (topic, name), the retry loop is idempotent — a lost response whose
    request actually landed converges to ``duplicate``, which callers
    already treat as success.

    Every attempt — connect, send, recv — runs under
    ``resilient.call_with_deadline(rpc_deadline_s)``. ``timeout_s``
    bounds each individual socket op, but a half-open peer that drips
    one byte per op could still pin a beat thread indefinitely; the
    attempt-level deadline (default ``2 * timeout_s``) turns that into
    a retriable failure that surfaces in watchdog/hang-doctor land
    instead of a wedge."""

    def __init__(
        self,
        host: str,
        port: int,
        retries: int = 3,
        timeout_s: float = 10.0,
        drop_hook=None,
        rpc_deadline_s: Optional[float] = None,
        backoff_base_s: float = 0.05,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.host, self.port = host, int(port)
        self.retries = int(retries)
        self.timeout_s = float(timeout_s)
        self.rpc_deadline_s = (
            float(rpc_deadline_s) if rpc_deadline_s is not None
            else 2.0 * self.timeout_s
        )
        self.backoff_base_s = float(backoff_base_s)
        self._sleep = sleep
        # chaos seam (serve_transport_drop): called before each send;
        # returning True "loses" the frame — the retry loop + hub dedup
        # must make delivery exactly-once anyway
        self.drop_hook = drop_hook
        self.stats = {"sent": 0, "dropped": 0, "retried": 0}

    def _attempt(
        self, header: Dict[str, Any], blob: bytes
    ) -> Tuple[Dict[str, Any], bytes]:
        with socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        ) as sock:
            _send_frame(sock, dict(header, blob_len=len(blob)), blob)
            self.stats["sent"] += 1
            return _recv_frame(sock)

    def _rpc(
        self, header: Dict[str, Any], blob: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                self.stats["retried"] += 1
                self._sleep(
                    compute_backoff(
                        attempt - 1, self.backoff_base_s, max_delay=1.0
                    )
                )
            if self.drop_hook is not None and self.drop_hook():
                # the frame is "lost on the wire": no send this attempt
                self.stats["dropped"] += 1
                last = ConnectionError("transport: frame dropped (chaos)")
                continue
            try:
                return call_with_deadline(
                    self._attempt, self.rpc_deadline_s, header, blob
                )
            except (
                OSError, ConnectionError, ValueError, DeadlineExceeded
            ) as e:
                last = e
        raise ConnectionError(
            f"transport: rpc {header.get('cmd')!r} to "
            f"{self.host}:{self.port} failed after {self.retries + 1} "
            f"attempts: {last}"
        )

    def put(self, topic, name, meta, arrays=None, meta_name="meta.json"):
        resp, _ = self._rpc(
            {"cmd": "put", "topic": topic, "name": name, "meta": meta},
            _pack_arrays(arrays),
        )
        return resp.get("status") == "accepted"

    def get(self, topic, name, meta_name="meta.json"):
        resp, blob = self._rpc({"cmd": "get", "topic": topic, "name": name})
        if not resp.get("found"):
            return None
        return resp.get("meta") or {}, _unpack_arrays(blob)

    def get_meta(self, topic, name, meta_name="meta.json"):
        resp, _ = self._rpc(
            {"cmd": "get", "topic": topic, "name": name, "meta_only": True}
        )
        return (resp.get("meta") or {}) if resp.get("found") else None

    def list(self, topic):
        resp, _ = self._rpc({"cmd": "list", "topic": topic})
        return list(resp.get("names") or [])

    def delete(self, topic, name):
        self._rpc({"cmd": "delete", "topic": topic, "name": name})

    # -- records: last-write-wins, so retries are trivially idempotent ----

    def put_record(self, topic, name, meta):
        self._rpc(
            {"cmd": "put_record", "topic": topic, "name": name,
             "meta": dict(meta)}
        )

    def get_record(self, topic, name):
        resp, _ = self._rpc(
            {"cmd": "get_record", "topic": topic, "name": name}
        )
        return (resp.get("meta") or {}) if resp.get("found") else None

    def list_records(self, topic):
        resp, _ = self._rpc({"cmd": "list_records", "topic": topic})
        return list(resp.get("names") or [])

    def delete_record(self, topic, name):
        self._rpc({"cmd": "delete_record", "topic": topic, "name": name})


# -- deterministic per-link fault injection -----------------------------

# Append-only, like chaos.FAULT_SITES and for the same reason: each
# fault draws from its own ``random.Random(seed * 1_000_003 + i)``
# stream keyed by POSITION, so appending a new fault kind leaves every
# existing schedule bit-identical. graft-lint's append-discipline check
# doesn't police this tuple (it isn't a chaos site list), but the
# contract is identical and tests pin the prefix.
NET_FAULT_SITES = (
    "drop",        # this op raises ConnectionError (frame lost on the wire)
    "delay",       # this op completes after sleeping ``delay_s``
    "duplicate",   # a put lands TWICE (retry after a lost ack) — dedup eats it
    "reorder",     # a list returns names in reversed order
    "partition",   # the LINK goes down for ``partition_s``: every op fails
)


class FaultyTransport(Transport):
    """Deterministic per-link fault injector wrapping any backend.

    Faults use the exact entry grammar of ``utils/chaos.py`` —
    ``{fault, at | every | p, span}`` matched against a per-fault
    op counter — and the same per-fault RNG-stream discipline (see
    :data:`NET_FAULT_SITES`), so a hostile network is a reproducible
    schedule, not a flake generator. Configure via a ``faults``
    sub-dict in any transport spec::

        transport:
          backend: tcp
          host: 10.0.0.1
          port: 9123
          faults:
            seed: 7
            partition_s: 2.0
            faults: [{fault: partition, at: 3}, {fault: drop, p: 0.01}]

    or wrap programmatically. An armed :class:`~trlx_tpu.utils.chaos.
    ChaosMonkey` can additionally drive the injector through the
    ``net_drop`` / ``net_partition`` sites: each attempted op on a
    LIVE link consults both sites once (a chaos-driven partition lasts
    ``chaos.stall_delay`` seconds). Because ops-per-second depends on
    wall-clock (beat threads, poll loops), chaos counts at this seam
    are timing-dependent — schedules should use ``p:`` or small
    ``at:`` values, and assertions should target the recovery
    behavior (eviction, rejoin, bit-equality), which holds no matter
    which op the fault lands on.

    Gate order per op: existing partition → new partition → drop →
    delay; ``duplicate`` applies after a successful message put,
    ``reorder`` to list results. ``clock``/``sleep`` are injectable
    for fake-clock tests."""

    def __init__(
        self,
        inner: Transport,
        config: Optional[Dict[str, Any]] = None,
        chaos=None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        from trlx_tpu.utils.chaos import _Entry

        config = dict(config or {})
        known = {"seed", "faults", "delay_s", "partition_s"}
        unknown = set(config) - known
        if unknown:
            raise ValueError(
                f"transport faults: unknown keys {sorted(unknown)}"
            )
        self.inner = inner
        self.chaos = chaos
        self.seed = int(config.get("seed", 0))
        self.delay_s = float(config.get("delay_s", 0.05))
        self.partition_s = float(config.get("partition_s", 1.0))
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._entries: Dict[str, list] = {s: [] for s in NET_FAULT_SITES}
        self._counts: Dict[str, int] = {s: 0 for s in NET_FAULT_SITES}
        self._rngs = {
            site: random.Random(self.seed * 1_000_003 + i)
            for i, site in enumerate(NET_FAULT_SITES)
        }
        for raw in config.get("faults") or []:
            raw = dict(raw)
            fault = raw.pop("fault", None)
            if fault not in NET_FAULT_SITES:
                raise ValueError(
                    f"transport faults: unknown fault {fault!r} "
                    f"(choose from {list(NET_FAULT_SITES)})"
                )
            bad = set(raw) - {"at", "span", "every", "p"}
            if bad:
                raise ValueError(
                    f"transport faults[{fault}]: unknown keys {sorted(bad)}"
                )
            entry = _Entry(fault=fault, **raw)
            if entry.at is None and entry.every is None and entry.p is None:
                raise ValueError(
                    f"transport faults[{fault}]: one of at/every/p required"
                )
            self._entries[fault].append(entry)
        self._partition_until = 0.0
        self.stats = {
            "ops": 0, "dropped": 0, "delayed": 0, "duplicated": 0,
            "reordered": 0, "partitions": 0, "partitioned_ops": 0,
        }

    def _consult(self, site: str) -> bool:
        with self._lock:
            self._counts[site] += 1
            count, rng = self._counts[site], self._rngs[site]
            # evaluate EVERY entry (no short-circuit) so each takes its
            # p-draw — same stream discipline as ChaosMonkey.consult
            return any([e.matches(count, rng) for e in self._entries[site]])

    def _gate(self, op: str) -> None:
        self.stats["ops"] += 1
        now = self._clock()
        with self._lock:
            down = now < self._partition_until
        if down:
            self.stats["partitioned_ops"] += 1
            raise ConnectionError(
                f"faulty transport: link partitioned ({op})"
            )
        partition = self._consult("partition")
        partition_s = self.partition_s
        if self.chaos is not None and self.chaos.consult("net_partition"):
            partition = True
            partition_s = self.chaos.stall_delay
        if partition:
            with self._lock:
                self._partition_until = now + partition_s
            self.stats["partitions"] += 1
            self.stats["partitioned_ops"] += 1
            raise ConnectionError(
                f"faulty transport: link partitioned for "
                f"{partition_s:.2f}s ({op})"
            )
        drop = self._consult("drop")
        if self.chaos is not None and self.chaos.consult("net_drop"):
            drop = True
        if drop:
            self.stats["dropped"] += 1
            raise ConnectionError(f"faulty transport: frame dropped ({op})")
        if self._consult("delay"):
            self.stats["delayed"] += 1
            self._sleep(self.delay_s)

    def put(self, topic, name, meta, arrays=None, meta_name="meta.json"):
        self._gate("put")
        accepted = self.inner.put(
            topic, name, meta, arrays, meta_name=meta_name
        )
        if self._consult("duplicate"):
            # retry-after-lost-ack: the same frame lands twice; the
            # inner dedup must report duplicate, proving convergence
            self.stats["duplicated"] += 1
            self.inner.put(topic, name, meta, arrays, meta_name=meta_name)
        return accepted

    def get(self, topic, name, meta_name="meta.json"):
        self._gate("get")
        return self.inner.get(topic, name, meta_name=meta_name)

    def get_meta(self, topic, name, meta_name="meta.json"):
        self._gate("get_meta")
        return self.inner.get_meta(topic, name, meta_name=meta_name)

    def list(self, topic):
        self._gate("list")
        names = self.inner.list(topic)
        if self._consult("reorder"):
            self.stats["reordered"] += 1
            names = list(reversed(names))
        return names

    def delete(self, topic, name):
        self._gate("delete")
        self.inner.delete(topic, name)

    def put_record(self, topic, name, meta):
        self._gate("put_record")
        self.inner.put_record(topic, name, meta)

    def get_record(self, topic, name):
        self._gate("get_record")
        return self.inner.get_record(topic, name)

    def list_records(self, topic):
        self._gate("list_records")
        names = self.inner.list_records(topic)
        if self._consult("reorder"):
            self.stats["reordered"] += 1
            names = list(reversed(names))
        return names

    def delete_record(self, topic, name):
        self._gate("delete_record")
        self.inner.delete_record(topic, name)

    def close(self):
        self.inner.close()


def base_transport(transport: Transport) -> Transport:
    """Unwrap fault-injector layers to the real backend (used where
    behavior must key on the BACKEND, e.g. picking the broadcast
    implementation, not on whether a test wrapped it in faults)."""
    while isinstance(transport, FaultyTransport):
        transport = transport.inner
    return transport


def make_hub_transport(
    spec: Optional[Dict[str, Any]],
) -> Tuple[TcpHub, TcpTransport, Dict[str, Any]]:
    """The SERVER side of the tcp backend (the serving frontend, the
    fleet learner): host the hub the spec names and return ``(hub,
    local client, advertised client spec)`` — remote peers connect
    with the advertised spec via :func:`make_transport`. ``bind``
    (default 127.0.0.1; use 0.0.0.0 to accept remote peers) is the
    listen address, ``host`` the address advertised to peers, ``port``
    0 = ephemeral (the advertised spec carries the real port)."""
    spec = dict(spec or {})
    if spec.pop("backend", None) != "tcp":
        raise ValueError("make_hub_transport: spec.backend must be 'tcp'")
    # ``faults`` in the spec describes the NETWORK links; the hub host's
    # loopback client isn't one, so it stays unwrapped here (remote
    # peers pick the faults up through make_transport)
    spec.pop("faults", None)
    known = {"host", "port", "retries", "timeout_s", "bind",
             "rpc_deadline_s", "host_hub"}
    unknown = set(spec) - known
    if unknown:
        raise ValueError(f"transport (tcp hub): unknown keys {sorted(unknown)}")
    hub = TcpHub(spec.get("bind", "127.0.0.1"), int(spec.get("port", 0)))
    client = TcpTransport(
        "127.0.0.1", hub.port,
        retries=int(spec.get("retries", 3)),
        timeout_s=float(spec.get("timeout_s", 10.0)),
        rpc_deadline_s=spec.get("rpc_deadline_s"),
    )
    advertised = {
        "backend": "tcp", "host": spec.get("host", hub.host),
        "port": hub.port,
    }
    return hub, client, advertised


def make_server_transport(
    spec: Optional[Dict[str, Any]], default_root: str
) -> Tuple[Optional[TcpHub], Transport, Dict[str, Any]]:
    """The CONSUMER side's one-stop bootstrap (serving frontend, fleet
    learner): ``(hub_or_None, transport, advertised client spec)``.
    tcp specs host the hub via :func:`make_hub_transport` — unless
    ``host_hub: false``, which says an EXTERNAL hub process owns the
    address (``python -m trlx_tpu.exp.net``, supervised via
    ``scripts/supervise.py --hub-cmd``) and the consumer should just
    be a client of it. Everything else resolves through
    :func:`make_transport` (shared-fs peers use the advertised
    root)."""
    spec = dict(spec or {})
    if spec.get("backend") == "tcp":
        if not spec.get("host_hub", True):
            if not spec.get("port"):
                raise ValueError(
                    "transport: host_hub=false needs an explicit port "
                    "(the external hub's address)"
                )
            return None, make_transport(spec, default_root), {
                "backend": "tcp",
                "host": spec.get("host", "127.0.0.1"),
                "port": int(spec["port"]),
            }
        return make_hub_transport(spec)
    transport = make_transport(spec, default_root)
    return None, transport, {
        "backend": "shared_fs", "root": spec.get("root") or default_root,
    }


def make_transport(
    spec: Optional[Dict[str, Any]], default_root: str
) -> Transport:
    """Config -> backend (the CLIENT side for tcp). ``spec`` keys:
    ``backend`` ("shared_fs", default, or "tcp"), ``root``
    (shared_fs), ``host``/``port`` (tcp client; ``bind`` and
    ``host_hub`` are tolerated so server and client can share one spec
    dict), ``retries``/``timeout_s``/``rpc_deadline_s`` (tcp), and
    ``faults`` (any backend — wraps the result in
    :class:`FaultyTransport`). Unknown keys fail loudly — a typo'd
    backend must not silently fall back to the default."""
    spec = dict(spec or {})
    faults = spec.pop("faults", None)
    backend = spec.pop("backend", "shared_fs")
    known = {
        "shared_fs": {"root"},
        "tcp": {"host", "port", "retries", "timeout_s", "bind",
                "rpc_deadline_s", "host_hub"},
    }
    if backend not in known:
        raise ValueError(
            f"transport.backend must be one of {sorted(known)}, "
            f"got {backend!r}"
        )
    unknown = set(spec) - known[backend]
    if unknown:
        raise ValueError(
            f"transport ({backend}): unknown keys {sorted(unknown)}"
        )
    if backend == "tcp":
        if "port" not in spec:
            raise ValueError("transport.backend tcp needs host/port")
        transport: Transport = TcpTransport(
            spec.get("host", "127.0.0.1"), spec["port"],
            retries=int(spec.get("retries", 3)),
            timeout_s=float(spec.get("timeout_s", 10.0)),
            rpc_deadline_s=spec.get("rpc_deadline_s"),
        )
    else:
        transport = SharedFSTransport(spec.get("root") or default_root)
    if faults:
        transport = FaultyTransport(transport, faults)
    return transport


def main(argv: Optional[List[str]] = None) -> int:
    """Standalone hub process: ``python -m trlx_tpu.exp.net --port N``.

    This is the ``host_hub: false`` counterpart — the hub runs as its
    own supervised role (``scripts/supervise.py --hub-cmd``) so a hub
    crash is an exit code routed through the supervisor's restart
    ladder, while learner and workers ride their reconnect/re-register
    recovery. Exits 0 on SIGTERM/Ctrl-C (a deliberate stop)."""
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        prog="python -m trlx_tpu.exp.net",
        description="run a standalone transport hub",
    )
    parser.add_argument("--bind", default="127.0.0.1",
                        help="listen address (0.0.0.0 for remote peers)")
    parser.add_argument("--port", type=int, required=True,
                        help="listen port (fixed: clients need it)")
    parser.add_argument("--handler-timeout-s", type=float, default=30.0)
    args = parser.parse_args(argv)

    hub = TcpHub(args.bind, args.port,
                 handler_timeout_s=args.handler_timeout_s)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    print(f"transport hub listening on {hub.host}:{hub.port}", flush=True)
    try:
        while not stop.wait(0.2):
            pass
    except KeyboardInterrupt:
        pass
    hub.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
