"""Experience transport orchestrator: leases + queue + admission gate.

The object trainers actually drive (trainer/ppo.py is the first
producer/consumer pair; ROADMAP item 1's remote rollout fleet plugs in
behind the same API). One instance owns the delivery state machine:

  producer side   :meth:`begin_chunk` (lease + replay snapshot) ->
                  produce -> :meth:`heartbeat` at milestones ->
                  :meth:`deliver` (bounded back-pressure wait, lease
                  release). A producer that dies mid-lease simply stops
                  heartbeating; :meth:`reclaim_expired` hands the chunk
                  to a live producer with the replay snapshot intact.
  consumer side   :meth:`poll` (in-order, deduped) -> :meth:`admit`
                  (staleness gate: version-at-generation vs
                  version-at-consumption) -> push to the store ->
                  :meth:`committed` (cursor advance — the position the
                  checkpoint persists).

The bounded waits (back-pressure, lease expiry) take a ``wait``
callable so the trainer can thread watchdog heartbeats through them —
a queue wedge then shows up as the ``exp_wait`` phase going silent,
never as an undiagnosable hang.

This class is in-process delivery STATE (ordering, dedup, staleness,
cursors); the bytes that cross a process/machine boundary ride the
pluggable topic transport in :mod:`trlx_tpu.exp.net` (shared-fs or
tcp) — the fleet's chunk messaging and the serving tier's
request/response traffic both use it.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from trlx_tpu.exp.leases import Lease, LeaseTable
from trlx_tpu.exp.queue import (
    OFFER_ACCEPTED,
    OFFER_DUPLICATE,
    OFFER_FULL,
    OFFER_STALE_EPOCH,
    ExpConfig,
    ExperienceChunk,
    ExperienceQueue,
)
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

ADMIT = "admit"
ADMIT_CLIP = "clip"
REJECT = "reject"


class ExperienceTransport:
    """Lease-based at-least-once production feeding an ordered,
    deduplicating queue, with a staleness admission gate in front of
    the consumer."""

    def __init__(
        self,
        cfg: ExpConfig,
        owner: str = "producer-0",
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.cfg = cfg
        self.owner = owner
        self._clock = clock
        self._sleep = sleep
        self.queue = ExperienceQueue(cfg.max_depth)
        self.leases = LeaseTable(cfg.lease_ttl_s, clock=clock)
        # highest seq ever leased in the current epoch: production
        # allocates the next one (re-dispatch reclaims, never re-leases)
        self._produced_seq = 0
        # chaos queue_wedge: the next N offers report full regardless of
        # real depth (a consumer that stopped draining, from the
        # producer's point of view)
        self._wedged_offers = 0
        self.stats: Dict[str, int] = {
            "backpressure_waits": 0,
            "staleness_rejects": 0,
            "staleness_clips": 0,
            "redispatches": 0,
        }

    # -- producer side ---------------------------------------------------

    def begin_chunk(self, snapshot: Optional[Dict[str, Any]] = None) -> Lease:
        """Lease the next chunk seq for production. ``snapshot`` is the
        replay state a re-dispatch restores (RNG / running-moment
        snapshot + the stream position) — it stays on the lease, so a
        producer death loses nothing but the wasted work."""
        self._produced_seq += 1
        return self.leases.acquire(
            (self.queue.epoch, self._produced_seq), self.owner,
            meta=snapshot,
        )

    def heartbeat(self, lease: Lease) -> None:
        self.leases.heartbeat(lease.chunk_id)

    def reassign(self, lease: Lease, producer: str) -> None:
        """Relabel WHO is generating the leased chunk (the rollout
        fleet: the learner keeps holding the lease on the worker's
        behalf, but expiry logs and postmortems should name the worker
        actually producing, not the learner process)."""
        lease.owner = producer
        self.stats["reassignments"] = self.stats.get("reassignments", 0) + 1

    def producer_died(self, lease: Lease) -> None:
        """The producer holding ``lease`` died mid-chunk (chaos
        ``worker_death_mid_lease``): its heartbeats stop; the lease
        expires on TTL and :meth:`reclaim_expired` re-dispatches."""
        self.leases.mark_dead(lease.chunk_id)
        logger.warning(
            "exp transport: producer %r died holding the lease on chunk "
            "%s — the lease will expire in <= %.3gs and the chunk will "
            "be re-dispatched", lease.owner, lease.chunk_id,
            self.cfg.lease_ttl_s,
        )

    def wedge(self, offers: int = 2) -> None:
        """Chaos ``queue_wedge`` body: make the next ``offers``
        deliveries see a full queue, exercising the back-pressure wait
        path (bounded, watchdog-beating) without a second thread."""
        self._wedged_offers += int(offers)

    def deliver(
        self,
        lease: Lease,
        policy_version: int,
        payload: Any,
        meta: Optional[Dict[str, Any]] = None,
        wait: Optional[Callable[[float], None]] = None,
    ) -> str:
        """Offer the finished chunk, waiting out back-pressure (bounded
        by ``offer_timeout_s``; each poll calls ``wait(poll_s)`` so the
        caller can beat its watchdog phase), then release the lease.
        Returns the final offer status (``accepted`` or ``duplicate`` —
        dedup means a redelivery is SUCCESS from the producer's view)."""
        chunk = ExperienceChunk(
            chunk_id=lease.chunk_id, policy_version=int(policy_version),
            payload=payload, meta=dict(meta or {}),
        )
        deadline = (
            self._clock() + self.cfg.offer_timeout_s
            if self.cfg.offer_timeout_s > 0 else None
        )
        while True:
            if self._wedged_offers > 0:
                self._wedged_offers -= 1
                status = OFFER_FULL
            else:
                status = self.queue.offer(chunk)
            if status != OFFER_FULL:
                break
            self.stats["backpressure_waits"] += 1
            if deadline is not None and self._clock() >= deadline:
                raise RuntimeError(
                    f"exp transport: back-pressure wait on chunk "
                    f"{chunk.chunk_id} exceeded offer_timeout_s="
                    f"{self.cfg.offer_timeout_s} (queue depth "
                    f"{self.queue.depth}/{self.queue.max_depth} — the "
                    "learner stopped draining)"
                )
            (wait or self._sleep)(self.cfg.wait_poll_s)
        self.leases.release(lease.chunk_id)
        return status

    # -- consumer side ---------------------------------------------------

    def poll(self) -> Optional[ExperienceChunk]:
        """The next in-order chunk, or None (not delivered yet)."""
        return self.queue.poll()

    def reclaim_expired(self) -> List[Lease]:
        """Reclaim every expired lease for re-dispatch (fresh clock,
        attempt+1, replay snapshot intact). The caller regenerates each
        returned lease's chunk."""
        out = []
        for lease in self.leases.expired():
            out.append(self.leases.reclaim(lease.chunk_id, self.owner))
            self.stats["redispatches"] += 1
        return out

    def admit(
        self, chunk: ExperienceChunk, current_version: int
    ) -> Tuple[str, int]:
        """Staleness admission gate. Returns ``(verdict, staleness)``:

        - ``admit``  — within ``max_staleness`` (the overlap_rollouts
          prefetch is 1 by construction); train on it as-is.
        - ``clip``   — over-stale but ``mode: clip``: train with
          IMPACT-style clipped importance weights (the trainer threads
          the per-token correction into the surrogate).
        - ``reject`` — over-stale, ``mode: reject``: the chunk is
          dropped from the buffer (cursor unmoved) and must be
          re-dispatched/regenerated with the current policy.
        """
        staleness = int(current_version) - int(chunk.policy_version)
        scfg = self.cfg.staleness
        if staleness <= scfg.max_staleness:
            return ADMIT, staleness
        if scfg.mode == "clip":
            self.stats["staleness_clips"] += 1
            return ADMIT_CLIP, staleness
        self.stats["staleness_rejects"] += 1
        self.queue.discard(chunk)
        return REJECT, staleness

    def committed(self, chunk: ExperienceChunk) -> None:
        """The chunk's payload reached the store: advance the consumer
        cursor (the position the checkpoint persists)."""
        self.queue.commit(chunk)

    def redispatch_rejected(self, chunk: ExperienceChunk) -> Lease:
        """Re-lease a staleness-rejected chunk's seq for regeneration
        (the original lease was released at delivery). The replay
        snapshot comes from the chunk's meta, so the regeneration is
        deterministic."""
        self.stats["redispatches"] += 1
        return self.leases.acquire(
            chunk.chunk_id, self.owner,
            meta=chunk.meta.get("snapshot"),
        )

    # -- epoch + persistence ---------------------------------------------

    def abort_epoch(self) -> int:
        """Guardrail requeue / rollback rebuilt the data stream: void
        every in-flight chunk and lease; seqs restart under the new
        epoch (replayed prompts produce fresh chunks)."""
        self.leases.drop_all()
        self._produced_seq = 0
        return self.queue.advance_epoch()

    def state_dict(self) -> Dict[str, Any]:
        """What the checkpoint persists (inside the atomic state.json
        commit): the committed consumer cursor and its epoch. Produced-
        but-unconsumed chunks deliberately do NOT persist — the prompt
        stream regenerates them on resume, which is what makes the
        cursor alone a complete recovery point."""
        return {
            "epoch": int(self.queue.epoch),
            "cursor": int(self.queue.cursor),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.queue.load_cursor(
            state.get("epoch", 0), state.get("cursor", 0)
        )
        self._produced_seq = self.queue.cursor

    def stats_summary(self) -> Dict[str, Any]:
        return {
            **{f"queue_{k}": v for k, v in self.queue.stats.items()},
            **{f"lease_{k}": v for k, v in self.leases.stats.items()},
            **self.stats,
            "depth": self.queue.depth,
            "cursor": self.queue.cursor,
            "epoch": self.queue.epoch,
        }
