"""Per-chunk production leases with watchdog-style heartbeats.

At-least-once delivery needs an answer to "the producer died mid-chunk":
a producer takes a LEASE on a chunk seq before generating it and
heartbeats the lease at production milestones (after generation, after
scoring). A lease whose heartbeat goes silent past ``ttl_s`` — worker
death, a wedged sampler — is EXPIRED: the table reclaims it and the
chunk is re-dispatched to a live producer, which regenerates it
deterministically from the group-invariant prompt stream (the lease
carries the producer-state snapshot needed for a bit-identical replay
in-process; a remote producer would re-pull from the stream position
instead).

Host-side only, injectable clock (tier-1 tests drive expiry on a fake
clock), no threads — expiry is evaluated by whoever calls
:meth:`expired`, which in the in-process integration is the consumer
loop's bounded wait.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

ChunkId = Tuple[int, int]


@dataclass
class Lease:
    """One outstanding production claim.

    ``meta`` carries whatever the producer needs to REPLAY the chunk on
    re-dispatch (in-process PPO: the RNG/running-moments snapshot and
    the pulled prompt batch; cross-process: the prompt-stream
    position). ``attempt`` counts dispatches of this chunk — 1 on first
    acquire, +1 per reclaim."""

    chunk_id: ChunkId
    owner: str
    acquired_at: float
    last_beat: float
    attempt: int = 1
    meta: Dict[str, Any] = field(default_factory=dict)
    dead: bool = False  # producer announced death (chaos) — stop beating

    def age(self, now: float) -> float:
        return now - self.last_beat


class LeaseTable:
    """Outstanding leases keyed by chunk id, with TTL-based expiry."""

    def __init__(
        self,
        ttl_s: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        if ttl_s <= 0:
            raise ValueError("lease ttl_s must be > 0")
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._leases: Dict[ChunkId, Lease] = {}
        self.stats: Dict[str, int] = {
            "acquired": 0,
            "released": 0,
            "expired": 0,
            "reclaimed": 0,
            "heartbeats": 0,
        }

    def acquire(
        self,
        chunk_id: ChunkId,
        owner: str,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Lease:
        """Claim production of ``chunk_id``. Re-acquiring an id whose
        lease is still live is an error (two producers must never build
        the same chunk concurrently — re-dispatch goes through
        :meth:`reclaim`)."""
        existing = self._leases.get(chunk_id)
        if existing is not None:
            raise ValueError(
                f"chunk {chunk_id} is already leased to "
                f"{existing.owner!r} (attempt {existing.attempt}); "
                "reclaim the expired lease instead of re-acquiring"
            )
        now = self._clock()
        lease = Lease(
            chunk_id=chunk_id, owner=owner, acquired_at=now, last_beat=now,
            meta=dict(meta or {}),
        )
        self._leases[chunk_id] = lease
        self.stats["acquired"] += 1
        return lease

    def heartbeat(self, chunk_id: ChunkId) -> None:
        """Producer liveness: refresh the lease's silent-age clock. A
        dead (chaos-killed) producer's beats are ignored — that is the
        death, as far as the table can observe it."""
        lease = self._leases.get(chunk_id)
        if lease is None or lease.dead:
            return
        lease.last_beat = self._clock()
        self.stats["heartbeats"] += 1

    def release(self, chunk_id: ChunkId) -> None:
        """Production finished (the chunk was delivered): drop the lease."""
        if self._leases.pop(chunk_id, None) is not None:
            self.stats["released"] += 1

    def mark_dead(self, chunk_id: ChunkId) -> None:
        """The producer died mid-lease (chaos ``worker_death_mid_lease``
        simulates it): heartbeats stop; the lease expires on TTL like a
        real worker death would."""
        lease = self._leases.get(chunk_id)
        if lease is not None:
            lease.dead = True

    def expired(self) -> List[Lease]:
        """Leases whose heartbeat is older than ``ttl_s`` — candidates
        for reclaim + re-dispatch. Does not mutate the table."""
        now = self._clock()
        return [
            lease for lease in self._leases.values()
            if lease.age(now) > self.ttl_s
        ]

    def reclaim(self, chunk_id: ChunkId, new_owner: str) -> Lease:
        """Take over an EXPIRED lease for re-dispatch: same chunk id and
        replay meta, attempt incremented, fresh heartbeat clock."""
        old = self._leases.get(chunk_id)
        if old is None:
            raise KeyError(f"no lease to reclaim for chunk {chunk_id}")
        now = self._clock()
        if old.age(now) <= self.ttl_s and not old.dead:
            raise ValueError(
                f"lease for chunk {chunk_id} is still live "
                f"(age {old.age(now):.3f}s <= ttl {self.ttl_s}s)"
            )
        self.stats["expired"] += 1
        self.stats["reclaimed"] += 1
        fresh = Lease(
            chunk_id=chunk_id, owner=new_owner, acquired_at=now,
            last_beat=now, attempt=old.attempt + 1, meta=old.meta,
        )
        self._leases[chunk_id] = fresh
        logger.warning(
            "exp lease: chunk %s lease expired on %r (attempt %d) — "
            "re-dispatched to %r (attempt %d)", chunk_id, old.owner,
            old.attempt, new_owner, fresh.attempt,
        )
        return fresh

    def get(self, chunk_id: ChunkId) -> Optional[Lease]:
        return self._leases.get(chunk_id)

    def drop_all(self) -> None:
        """Epoch abort (guardrail requeue/rollback): every in-flight
        production is void — its prompts replay under the new epoch."""
        self._leases.clear()

    @property
    def outstanding(self) -> int:
        return len(self._leases)
