"""SLO admission scheduler: EDF ordering, deadline eviction, starvation
accounting.

Priority semantics at the lane-refill decision point (the trainer's
rollout-chunk boundary): serving requests OUTRANK training refills —
the frontend's tick runs its serve batches before the next training
chunk dispatches — but the allowance is bounded
(``serve.max_batches_per_tick``), so a flood of requests slows training
and is REPORTED (the starvation counters below + a loud log + a flight
event), it never wedges the loop. Within serving, admission is earliest
deadline first; a request whose deadline has already passed is evicted
with a ``timeout`` result instead of burning lanes on an answer nobody
is waiting for.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from trlx_tpu.serve.request import ServeRequest
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


@dataclass
class Pending:
    req: ServeRequest
    arrival_t: float
    deadline_t: float


class SLOScheduler:
    def __init__(self, default_deadline_s: float, max_batch: int):
        self.default_deadline_s = float(default_deadline_s)
        self.max_batch = int(max_batch)
        self._queue: List[Pending] = []
        self.stats = {
            "submitted": 0,
            "deadline_evictions": 0,
            "training_deferred_ticks": 0,
            "serving_starved_ticks": 0,
        }
        # consecutive-tick streaks behind the two starvation reports
        self._training_streak = 0
        self._serving_streak = 0

    # -- intake ------------------------------------------------------------

    def submit(self, req: ServeRequest, now: float) -> None:
        deadline = req.deadline_s
        if deadline is None:
            deadline = self.default_deadline_s
        # a non-positive deadline means ALREADY EXPIRED (the chaos
        # serve_request_timeout contract; also what a client asking for
        # "0 seconds" deserves) — the same tick's expire() sweep evicts
        # it before admission
        self._queue.append(
            Pending(req=req, arrival_t=now, deadline_t=now + float(deadline))
        )
        self.stats["submitted"] += 1

    @property
    def pending(self) -> int:
        return len(self._queue)

    def pending_session_keys(self) -> set:
        """Cache keys of sessions with a turn waiting in the queue —
        the ledger's deadline sweep must not evict their history out
        from under the queued turn."""
        from trlx_tpu.serve.kv import session_key

        return {
            session_key(p.req.session_id)
            for p in self._queue if p.req.session_id
        }

    # -- deadline eviction -------------------------------------------------

    def expire(self, now: float) -> List[Pending]:
        """Pop every queued request whose deadline already passed (the
        frontend posts them a ``timeout`` result; a session request's
        pinned pages are reclaimed by the ledger's deadline sweep)."""
        dead = [p for p in self._queue if now >= p.deadline_t]
        if dead:
            self._queue = [p for p in self._queue if now < p.deadline_t]
            self.stats["deadline_evictions"] += len(dead)
        return dead

    # -- admission ---------------------------------------------------------

    def pick(self, now: float, limit: Optional[int] = None) -> List[Pending]:
        """Admit the next batch, earliest deadline first."""
        limit = self.max_batch if limit is None else min(limit, self.max_batch)
        self._queue.sort(key=lambda p: (p.deadline_t, p.arrival_t, p.req.rid))
        batch, self._queue = self._queue[:limit], self._queue[limit:]
        return batch

    def requeue(self, batch: List[Pending]) -> None:
        """Hand a picked batch back (lane starvation: the engine had no
        capacity this tick). Requests keep their original deadlines, so
        a long starvation degrades to deadline eviction — visible and
        bounded — rather than unbounded queue growth."""
        self._queue.extend(batch)

    # -- starvation accounting ---------------------------------------------

    def note_tick(
        self, ran_full_allowance: bool, starved: bool, report_after: int
    ) -> List[str]:
        """Record one tick's outcome; returns the starvation reports
        (if any) that just crossed their streak threshold."""
        out = []
        if ran_full_allowance and self.pending:
            self._training_streak += 1
            self.stats["training_deferred_ticks"] += 1
            if self._training_streak == report_after:
                out.append("training_starved")
        else:
            self._training_streak = 0
        if starved and self.pending:
            self._serving_streak += 1
            self.stats["serving_starved_ticks"] += 1
            if self._serving_streak == report_after:
                out.append("serving_starved")
        elif not starved:
            self._serving_streak = 0
        return out
