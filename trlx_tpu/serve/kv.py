"""Host-side refcounted page ledger behind the prefix/session KV cache.

The device half of the serving KV story is ops/paged_kv.py refcounts +
the gen_engine warm pool; THIS module is the authority over page
lifetimes between engine calls:

  * the free-stack mirror (adopted from each call's ``kv_state``, plus
    host-side frees from adoptions and evictions),
  * per-page cache holds (refcount 1 while a prefix/session entry owns
    the page; the per-call row shares are composed transiently in
    :meth:`compose_refcnt` and released by the engine in-call),
  * the entry table itself — shared system-prompt prefixes and pinned
    multi-turn sessions — with active-user refcounts, LRU ordering,
    and refcount-zero + LRU eviction under pool pressure.

Copy-on-write is structural rather than a page copy: an entry shares
only its PAGE-ALIGNED pages; the divergent suffix (the unaligned
remainder plus everything request-specific) always prefills into the
request's own freshly-popped pages, so shared pages are read-only by
construction and two requests can never write the same page.

Everything here is plain python/numpy over page IDS — no jax — which
is what lets tests fuzz acquire/release/adopt/evict interleavings
cheaply and assert the invariants (never double-free; refcount-zero
implies on the free stack; pages conserved).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


@dataclass
class CacheEntry:
    """One cached prefix or pinned session."""

    key: str
    kind: str  # "prefix" | "session"
    pages: np.ndarray  # aligned page ids (0 = compacted-pad placeholder)
    kv_len: int  # aligned token coverage = len(pages) * page_size
    layout_ids: np.ndarray  # slot-layout tokens [kv_len]
    layout_mask: np.ndarray  # 1 = real, 0 = pad (positions ride cumsum)
    # the unaligned tail past kv_len (ids + mask — the prompt's internal
    # pads can straddle the aligned boundary): re-prefilled by the next
    # turn into its own pages (the copy-on-write half)
    pending_ids: List[int] = field(default_factory=list)
    pending_mask: List[int] = field(default_factory=list)
    refs: int = 0  # active in-flight users (evictable only at 0)
    last_used: float = 0.0
    deadline_t: Optional[float] = None  # sessions: idle eviction time


class PageLedger:
    """Free-stack mirror + cache holds over the serve pool's page ids."""

    def __init__(self, n_pages: int, page_size: int):
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # mirrors ops/paged_kv.init_alloc: free[:ntop] are free ids,
        # popped from the top
        self.free = np.concatenate(
            [np.arange(1, n_pages, dtype=np.int32), np.zeros(1, np.int32)]
        )
        self.ntop = n_pages - 1
        # cache hold COUNTS: a page can be held by more than one entry
        # (a session whose pinned table maps a shared prefix's pages is
        # the canonical case); it returns to the free stack only when
        # the last holder drops
        self.hold = np.zeros(n_pages, np.int32)
        self.entries: Dict[str, CacheEntry] = {}
        self.stats = {
            "adopted_entries": 0,
            "evicted_entries": 0,
            "deadline_evicted_entries": 0,
            "reclaimed_pages": 0,
            "shared_page_hits": 0,
        }

    # -- free-stack plumbing ---------------------------------------------

    def adopt_stack(self, free: np.ndarray, ntop: int) -> None:
        """Adopt the engine call's end-of-call stack as the new mirror."""
        self.free = np.asarray(free, np.int32).copy()
        self.ntop = int(ntop)

    def push(self, pages) -> int:
        """Host-side free (adoption surplus, evictions). Returns the
        number of real pages pushed."""
        n = 0
        for p in np.asarray(pages, np.int32).reshape(-1):
            if p <= 0:
                continue
            if self.hold[p]:
                raise AssertionError(
                    f"ledger: freeing page {int(p)} still held by a cache "
                    "entry (double-free)"
                )
            self.free[self.ntop] = p
            self.ntop += 1
            n += 1
        return n

    def push_unheld(self, pages) -> int:
        """Free only the pages NO entry holds — the refusal paths of a
        pinned-row adoption use this: a refused row's table can map a
        surviving entry's shared pages, whose lifecycle stays the
        entry's."""
        pages = np.asarray(pages, np.int32).reshape(-1)
        pages = pages[pages > 0]
        return self.push(pages[self.hold[pages] == 0])

    def free_pages(self) -> int:
        return self.ntop

    # -- cache holds -------------------------------------------------------

    def compose_refcnt(self, row_shares: List[np.ndarray]) -> np.ndarray:
        """The device refcount array for one engine call: the cache's
        own hold plus one count per queue row mapping the page —
        in-call releases then decrement at most down to the hold, so a
        shared page can never reach the free stack mid-call."""
        refcnt = self.hold.astype(np.int32).copy()
        for pages in row_shares:
            for p in np.asarray(pages, np.int32).reshape(-1):
                if p > 0:
                    refcnt[p] += 1
        return refcnt

    def _hold_pages(self, pages: np.ndarray) -> None:
        for p in pages:
            if p > 0:
                self.hold[p] += 1

    def _drop_hold(self, pages: np.ndarray) -> List[int]:
        """Decrement holds; returns the pages that just hit zero (the
        ones the dropping entry must free or transfer)."""
        released = []
        for p in pages:
            if p <= 0:
                continue
            if self.hold[p] <= 0:
                raise AssertionError(
                    f"ledger: dropping a hold on page {int(p)} that has "
                    "none (double-release)"
                )
            self.hold[p] -= 1
            if self.hold[p] == 0:
                released.append(int(p))
        return released

    # -- entries -----------------------------------------------------------

    def get(self, key: str) -> Optional[CacheEntry]:
        return self.entries.get(key)

    def acquire(self, key: str, now: float) -> Optional[CacheEntry]:
        entry = self.entries.get(key)
        if entry is None:
            return None
        entry.refs += 1
        entry.last_used = now
        self.stats["shared_page_hits"] += int((entry.pages > 0).sum())
        return entry

    def release(self, key: str) -> None:
        entry = self.entries.get(key)
        if entry is not None and entry.refs > 0:
            entry.refs -= 1

    def adopt(
        self,
        key: str,
        kind: str,
        pages: np.ndarray,
        layout_ids: np.ndarray,
        layout_mask: np.ndarray,
        pending_ids: List[int],
        now: float,
        deadline_t: Optional[float] = None,
        pending_mask: Optional[List[int]] = None,
    ) -> CacheEntry:
        """Adopt aligned pages (just pinned by the engine) into a new
        entry, replacing any previous entry under the key. A session
        turn's new table CONTAINS the old entry's shared pages, so the
        old hold is dropped first and the union re-held — pages moving
        between the versions transfer without touching the free stack."""
        pages = np.asarray(pages, np.int32).copy()
        old = self.entries.pop(key, None)
        if old is not None:
            released = self._drop_hold(old.pages)
            stale = sorted(
                set(released) - set(int(p) for p in pages if p > 0)
            )
            # pages the new version no longer covers AND no other entry
            # holds (a shrunk session cannot happen today, but the
            # ledger must not leak if it ever does)
            self.push(np.asarray(stale, np.int32))
        self._hold_pages(pages)
        entry = CacheEntry(
            key=key, kind=kind, pages=pages,
            kv_len=len(pages) * self.page_size,
            layout_ids=np.asarray(layout_ids, np.int32).copy(),
            layout_mask=np.asarray(layout_mask, np.int32).copy(),
            pending_ids=[int(t) for t in pending_ids],
            pending_mask=[int(m) for m in (
                pending_mask if pending_mask is not None
                else [1] * len(pending_ids)
            )],
            refs=0, last_used=now, deadline_t=deadline_t,
        )
        self.entries[key] = entry
        self.stats["adopted_entries"] += 1
        return entry

    def drop(self, key: str, reason: str = "evicted") -> int:
        """Evict an entry, reclaiming its pages. Returns pages freed."""
        entry = self.entries.pop(key, None)
        if entry is None:
            return 0
        if entry.refs > 0:
            raise AssertionError(
                f"ledger: dropping entry {key} with {entry.refs} active "
                "users"
            )
        released = self._drop_hold(entry.pages)
        n = self.push(np.asarray(released, np.int32))
        self.stats["evicted_entries"] += 1
        self.stats["reclaimed_pages"] += n
        logger.info(
            "serve kv: %s entry %s %s — %d pages reclaimed",
            entry.kind, key, reason, n,
        )
        return n

    def expire_deadlines(self, now: float, skip=()) -> List[str]:
        """Deadline eviction: drop idle entries whose deadline passed
        (sessions mainly — their pinned pages are exactly what pool
        pressure needs back). In-use entries (refs > 0) survive until
        released, then fall to the next sweep; ``skip`` names entries
        the caller knows are about to be used (a queued session turn)."""
        out = []
        skip = set(skip)
        for key in list(self.entries):
            if key in skip:
                continue
            e = self.entries[key]
            if e.deadline_t is not None and now >= e.deadline_t and e.refs == 0:
                self.drop(key, reason="deadline-expired")
                self.stats["deadline_evicted_entries"] += 1
                out.append(key)
        return out

    def evict_for(self, pages_needed: int, max_entries: int) -> int:
        """LRU eviction of refcount-zero entries until ``pages_needed``
        fit on the stack (and the entry count is back under
        ``max_entries``). Returns pages reclaimed; a shortfall is the
        caller's problem (degrade to plain prefill — never deadlock)."""
        freed = 0
        while self.entries:
            over_cap = len(self.entries) > max_entries
            if self.ntop >= pages_needed and not over_cap:
                break
            idle = [e for e in self.entries.values() if e.refs == 0]
            if not idle:
                break
            victim = min(idle, key=lambda e: e.last_used)
            freed += self.drop(victim.key, reason="lru-evicted")
        return freed

    # -- invariants --------------------------------------------------------

    def accounting(self) -> Dict[str, int]:
        held = int((self.hold > 0).sum())  # unique pages under any hold
        return {
            "free": int(self.ntop),
            "held": held,
            "total": self.n_pages - 1,  # page 0 reserved
        }

    def check_invariants(self) -> None:
        """Between engine calls: free ∪ held partitions the pool (no
        page both free and held; refcount-zero == on the stack), and
        the stack holds no duplicates."""
        stack = self.free[: self.ntop]
        if len(set(stack.tolist())) != len(stack):
            raise AssertionError("ledger: duplicate page on the free stack")
        for p in stack:
            if p <= 0 or self.hold[p]:
                raise AssertionError(
                    f"ledger: page {int(p)} is on the free stack while "
                    "held by an entry"
                )
        acct = self.accounting()
        if acct["free"] + acct["held"] != acct["total"]:
            raise AssertionError(
                f"ledger: page leak — free {acct['free']} + held "
                f"{acct['held']} != pool {acct['total']}"
            )


def prefix_key(prefix_ids: List[int]) -> str:
    import hashlib

    h = hashlib.sha256(
        np.asarray(prefix_ids, np.int32).tobytes()
    ).hexdigest()[:16]
    return f"px:{h}"


def session_key(session_id: str) -> str:
    return f"sess:{session_id}"


def aligned_len(n: int, page_size: int) -> int:
    return (n // page_size) * page_size
