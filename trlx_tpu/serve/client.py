"""Serve client: submit requests / await results over any exp/net.py
transport backend.

The client is transport-symmetric with the frontend: pass the
``transport_spec`` the frontend advertises (shared_fs root for
same-filesystem callers, tcp host/port to cross a machine). Submission
is idempotent by request id — a retried submit of the same rid dedups
at the transport — and the result poll is a plain bounded wait, so a
client can always be restarted without double-serving a request.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

from trlx_tpu.exp.net import make_transport
from trlx_tpu.serve.request import (
    REQUESTS_TOPIC,
    RESULTS_TOPIC,
    ServeRequest,
    ServeResult,
)


class ServeClient:
    def __init__(self, transport_spec: Dict[str, Any]):
        self.transport = make_transport(dict(transport_spec), ".")

    def submit(
        self,
        prompt_ids: List[int],
        max_tokens: Optional[int] = None,
        deadline_s: Optional[float] = None,
        prefix_ids: Optional[List[int]] = None,
        session_id: Optional[str] = None,
        rid: Optional[str] = None,
    ) -> str:
        rid = rid or uuid.uuid4().hex[:12]
        req = ServeRequest(
            rid=rid, prompt_ids=list(prompt_ids), max_tokens=max_tokens,
            deadline_s=deadline_s, prefix_ids=list(prefix_ids or []),
            session_id=session_id,
        )
        self.transport.put(REQUESTS_TOPIC, rid, req.to_meta())
        return rid

    def result(
        self, rid: str, timeout_s: float = 60.0, poll_s: float = 0.05
    ) -> Optional[ServeResult]:
        """Poll for the result; None on timeout (the request may still
        complete later — poll again or treat as an SLO miss). A picked-
        up result is deleted from the transport: the frontend's bounded
        retention is the backstop, not the steady state."""
        deadline = time.monotonic() + timeout_s
        while True:
            meta = self.transport.get_meta(RESULTS_TOPIC, rid)
            if meta is not None:
                self.transport.delete(RESULTS_TOPIC, rid)
                return ServeResult.from_meta(meta)
            if time.monotonic() >= deadline:
                return None
            time.sleep(poll_s)

    def request_and_wait(self, prompt_ids: List[int], **kw) -> ServeResult:
        timeout_s = kw.pop("timeout_s", 120.0)
        rid = self.submit(prompt_ids, **kw)
        res = self.result(rid, timeout_s=timeout_s)
        if res is None:
            raise TimeoutError(
                f"serve client: no result for {rid} within {timeout_s}s"
            )
        return res
