"""Serve request/result wire records.

Everything crossing the client/frontend boundary is JSON-safe meta on
the pluggable transport (exp/net.py) — token id lists, not arrays
(requests are tiny next to fleet chunks). ``rng_row`` derives the
per-request RNG id the engine keys sampling on: a pure function of the
request id, so the SAME request produces the SAME tokens regardless of
transport backend, batch composition, or which tick serves it (the
RPC-vs-shared-fs golden in tests/test_serve.py pins this).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

REQUESTS_TOPIC = "requests"
RESULTS_TOPIC = "results"

# request terminal states
OK = "ok"
TIMEOUT = "timeout"  # deadline expired before (or while) being served
ERROR = "error"  # malformed / over-budget request
CANCELLED = "cancelled"  # frontend shut down with the request queued


def rng_row(rid: str, max_new: int) -> int:
    """Deterministic per-request RNG row id, bounded so
    ``row * max_new + j`` stays inside int32 in the engine's id space.

    Honesty note on the hash: the row space is ``2**30 // max_new``
    (~33M at max_new=32), so at large request volumes DISTINCT request
    ids can land on the same sampling stream (birthday bound: ~50% of
    one collision existing after ~7k requests). A collision only
    reduces sampling diversity between two requests with identical
    prompts — correctness, isolation and determinism are unaffected.
    Widening needs a second fold-in slot in the engine's RNG id space;
    noted as follow-up in docs/serving.md."""
    return int(zlib.crc32(rid.encode("utf-8")) % (2**30 // max(max_new, 1)))


@dataclass
class ServeRequest:
    """One external generation request.

    deadline_s is RELATIVE to arrival at the frontend (client clocks
    are not trusted); ``prefix_ids`` marks the shareable system-prompt
    prefix (cached page-aligned across requests); ``session_id`` pins
    the request's KV across turns — a follow-up turn sends ONLY the new
    user tokens in ``prompt_ids``.
    """

    rid: str
    prompt_ids: List[int]
    max_tokens: Optional[int] = None
    deadline_s: Optional[float] = None
    prefix_ids: List[int] = field(default_factory=list)
    session_id: Optional[str] = None

    def to_meta(self) -> Dict[str, Any]:
        return {
            "rid": self.rid,
            "prompt_ids": [int(t) for t in self.prompt_ids],
            "max_tokens": self.max_tokens,
            "deadline_s": self.deadline_s,
            "prefix_ids": [int(t) for t in self.prefix_ids],
            "session_id": self.session_id,
        }

    @classmethod
    def from_meta(cls, meta: Dict[str, Any]) -> "ServeRequest":
        return cls(
            rid=str(meta["rid"]),
            prompt_ids=[int(t) for t in meta.get("prompt_ids") or []],
            max_tokens=meta.get("max_tokens"),
            deadline_s=meta.get("deadline_s"),
            prefix_ids=[int(t) for t in meta.get("prefix_ids") or []],
            session_id=meta.get("session_id"),
        )


@dataclass
class ServeResult:
    """What the frontend posts back under the request's id."""

    rid: str
    status: str
    tokens: List[int] = field(default_factory=list)
    detail: str = ""
    latency_s: float = 0.0  # arrival -> result ready
    queue_wait_s: float = 0.0  # arrival -> engine dispatch
    decode_tok_s: float = 0.0  # batch real tokens / batch wall
    shared_pages: int = 0  # prefix/session pages REUSED (not prefilled)
    session_id: Optional[str] = None

    def to_meta(self) -> Dict[str, Any]:
        return {
            "rid": self.rid,
            "status": self.status,
            "tokens": [int(t) for t in self.tokens],
            "detail": self.detail,
            "latency_s": float(self.latency_s),
            "queue_wait_s": float(self.queue_wait_s),
            "decode_tok_s": float(self.decode_tok_s),
            "shared_pages": int(self.shared_pages),
            "session_id": self.session_id,
        }

    @classmethod
    def from_meta(cls, meta: Dict[str, Any]) -> "ServeResult":
        return cls(
            rid=str(meta["rid"]),
            status=str(meta["status"]),
            tokens=[int(t) for t in meta.get("tokens") or []],
            detail=str(meta.get("detail", "")),
            latency_s=float(meta.get("latency_s", 0.0)),
            queue_wait_s=float(meta.get("queue_wait_s", 0.0)),
            decode_tok_s=float(meta.get("decode_tok_s", 0.0)),
            shared_pages=int(meta.get("shared_pages", 0)),
            session_id=meta.get("session_id"),
        )
