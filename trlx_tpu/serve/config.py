"""Parsed ``train.serve`` section (plain dict in YAML; host-only)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional


@dataclass(frozen=True)
class ServeConfig:
    """``train.serve.*`` knobs (default off).

    enabled            master switch: the trainer builds a serving
                       frontend at learn() start and ticks it at the
                       lane-refill decision points (rollout chunk
                       boundaries + once per optimization cycle).
                       Serving runs SEPARATE engine calls on the live
                       policy params with its own RNG/pool, so the
                       training loss stream stays bit-equal to a
                       no-serving run by construction.
    max_batch          queue rows per serve engine call (one compiled
                       executable; short ticks pad with dummy rows).
    slots              decode lanes per call; 0 = max_batch.
    page_size          KV page size of the PERSISTENT serve pool.
    pool_pages         pages in the serve pool; 0 = worst case for
                       max_batch rows (no headroom for cached
                       prefixes/sessions — size it up to actually
                       cache).
    max_prompt_len     serve row width (prompt + session history
                       budget). Requests longer than this are rejected
                       with an ``error`` result, never wedged.
    max_new_tokens     hard cap on a request's ``max_tokens`` (the
                       engine's N; also the per-request ``row_budget``
                       ceiling).
    default_max_tokens when a request omits ``max_tokens``.
    default_deadline_s when a request omits ``deadline_s`` (relative
                       to arrival at the frontend).
    kv_quant           "int8" | "none" | null (null follows the
                       model's kv_cache_quant, like the rollout
                       engine).
    max_batches_per_tick  serve batches one tick may run before
                       handing the lanes back to training — the bound
                       that makes "serving outranks training refills"
                       a priority, not a wedge.
    starvation_report_after  consecutive full-allowance ticks (with
                       requests still pending) before the frontend
                       loudly reports a starved training loop; and
                       consecutive starved ticks (no lane capacity —
                       chaos ``serve_lane_starvation``) before it
                       reports starved serving.
    prefix_cache       share page-aligned system-prompt prefixes
                       across requests (refcounted; prefilled once by
                       the pioneering request).
    sessions           pin multi-turn sessions' pages across turns.
    session_deadline_s idle seconds before a session's pinned pages
                       are evicted (deadline eviction reclaims them).
    max_cache_entries  prefix + session entries kept before LRU
                       eviction of refcount-zero entries.
    groups             independent serve LANE GROUPS: the frontend
                       keeps one warm pool + page ledger per group,
                       assigns requests to groups (sessions/prefixes
                       sticky by key hash so their pinned pages stay
                       in one pool), and runs every group in ONE
                       stacked engine dispatch whose group axis shards
                       over the mesh's data axes when the geometry
                       divides — the serve frontend itself becomes
                       multi-chip. Request token streams are
                       per-request-id RNG and therefore invariant to
                       the group count.
    transport          request/response backend (exp/net.py spec):
                       ``{}`` = shared_fs under
                       ``<train.checkpoint_dir>/serve``; ``{backend:
                       tcp, port: N}`` makes the frontend host a
                       socket hub (port 0 = ephemeral) so clients
                       cross a machine boundary.
    seed               serving RNG seed (independent of the training
                       stream — serving must never touch the
                       trainer's key chain).
    """

    enabled: bool = False
    max_batch: int = 4
    slots: int = 0
    page_size: int = 64
    pool_pages: int = 0
    max_prompt_len: int = 128
    max_new_tokens: int = 32
    default_max_tokens: int = 32
    default_deadline_s: float = 120.0
    kv_quant: Optional[str] = None
    max_batches_per_tick: int = 1
    starvation_report_after: int = 8
    prefix_cache: bool = True
    sessions: bool = True
    session_deadline_s: float = 600.0
    max_cache_entries: int = 32
    groups: int = 1
    transport: Optional[Dict[str, Any]] = None
    seed: int = 0

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ServeConfig":
        d = dict(d or {})
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"train.serve: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        cfg = cls(**d)
        if cfg.max_batch < 1:
            raise ValueError("train.serve.max_batch must be >= 1")
        if cfg.page_size < 1:
            raise ValueError("train.serve.page_size must be >= 1")
        if cfg.max_new_tokens < 1:
            raise ValueError("train.serve.max_new_tokens must be >= 1")
        if cfg.max_prompt_len < 2:
            raise ValueError("train.serve.max_prompt_len must be >= 2")
        if cfg.default_max_tokens > cfg.max_new_tokens:
            raise ValueError(
                "train.serve.default_max_tokens exceeds max_new_tokens"
            )
        if cfg.kv_quant not in (None, "none", "int8"):
            raise ValueError(
                f"train.serve.kv_quant must be none/int8, got {cfg.kv_quant!r}"
            )
        if cfg.max_batches_per_tick < 1:
            raise ValueError("train.serve.max_batches_per_tick must be >= 1")
        if cfg.groups < 1:
            raise ValueError("train.serve.groups must be >= 1")
        return cfg
