"""Live-traffic serving tier (``train.serve.*``).

External generation requests enter the SAME continuous-batching decode
engine that produces training rollouts (models/gen_engine.py), on the
same live policy weights — "train and serve the same model" with the
staleness machinery already solved by the versioned weight broadcast
(the serving frontend always samples the learner's current params, so
its staleness is zero by construction).

Pieces:

  config.py     ``ServeConfig`` parsed from the ``train.serve`` dict.
  request.py    request/result wire records + RNG row derivation.
  kv.py         the host-side refcounted page ledger behind the
                prefix/session KV cache (the engine's device half is
                ops/paged_kv.py refcounts + gen_engine warm pools).
  scheduler.py  SLO admission: EDF ordering, deadline eviction,
                training/serving starvation accounting.
  frontend.py   the orchestrator a trainer ticks at its lane-refill
                decision points.
  client.py     submit/await over any exp/net.py transport backend.

Runbook: docs/serving.md.
"""

from trlx_tpu.serve.config import ServeConfig
from trlx_tpu.serve.request import ServeRequest, ServeResult

__all__ = ["ServeConfig", "ServeRequest", "ServeResult"]
