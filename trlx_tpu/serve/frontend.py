"""The serving frontend: transport intake -> SLO scheduler -> engine.

One instance lives on the learner and is TICKED by the trainer at its
lane-refill decision points (rollout chunk boundaries + once per
optimization cycle). A tick drains newly-arrived requests from the
transport, evicts the deadline-expired, and runs up to
``serve.max_batches_per_tick`` engine batches on the LIVE policy params
— serving requests outrank the next training refill, training backfills
the lanes the moment the allowance is spent, and a starved side (either
one) is reported, never wedged.

Isolation contract: serving owns its rng (``serve.seed``), its page
pool, and its engine executables. It reads ``trainer.params`` and
touches NOTHING else — which is why the training loss stream is
bit-equal to a no-serving run by construction (pinned by
tests/test_serve.py and the chaos serving leg).

The timing ledger is honest about v1 granularity: a request's whole
decode runs inside one engine dispatch, so TTFT == request latency
here; ``queue_wait_s`` and the batch-level per-token decode rate are
reported separately. Segmented decode (the session machinery already
carries KV across calls) is the follow-up that separates them.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from trlx_tpu.serve import kv as skv
from trlx_tpu.serve.config import ServeConfig
from trlx_tpu.serve.request import (
    CANCELLED,
    ERROR,
    OK,
    REQUESTS_TOPIC,
    RESULTS_TOPIC,
    TIMEOUT,
    ServeRequest,
    ServeResult,
    rng_row,
)
from trlx_tpu.serve.scheduler import Pending, SLOScheduler
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


class RowError(ValueError):
    """A request that can never be served (too long, session overflow)."""


class DeferRow(Exception):
    """The request must wait for a later tick (a same-batch request
    already holds its session — one in-flight turn per session)."""


@dataclass
class _RowCtx:
    pend: Pending
    ids: np.ndarray
    mask: np.ndarray
    budget: int
    pin: bool
    ready: int
    rngrow: int
    table_row: np.ndarray
    entry_key: Optional[str] = None  # acquired entry to release
    adopt_session: Optional[str] = None  # session key to adopt at finish
    adopt_prefix: Optional[List[int]] = None  # pioneer's prefix ids
    shared_pages: int = 0
    group: int = 0  # lane group whose pool/ledger serves this row
    note: str = ""  # surfaced in the result's detail


@dataclass
class _Record:
    latency_s: float
    queue_wait_s: float
    decode_tok_s: float
    deadline_met: bool


class ServeFrontend:
    """See module docstring. ``runner`` is the trainer-built jitted
    engine entry: ``runner(q_ids, q_mask, rng, row_budget, warm, q_pin,
    q_ready, q_rng_row) -> engine output`` (models/gen_engine.py
    serving mode)."""

    def __init__(
        self,
        cfg: ServeConfig,
        runner: Callable[..., Dict[str, Any]],
        geom: Dict[str, Any],
        checkpoint_dir: str,
        chaos=None,
        obs=None,
        clock: Callable[[], float] = time.time,
    ):
        import jax

        from trlx_tpu.exp import net
        from trlx_tpu.ops import paged_kv

        self.cfg = cfg
        self.runner = runner
        self.chaos = chaos
        self.obs = obs
        self._clock = clock
        # engine geometry (must match the spec the runner was traced
        # with): P row width, N budget ceiling, PS page size, NP pool
        self.P = int(geom["P"])
        self.N = int(geom["N"])
        self.PS = int(geom["page_size"])
        self.MP = paged_kv.pages_per_slot(self.P, self.N, self.PS)
        self.NP = int(geom["pool_pages"])
        self.PP = -(-self.P // self.PS)
        self.pad_id = int(geom["pad_token_id"])
        # sharded lane groups: G independent (pool, ledger) pairs; one
        # stacked dispatch serves them all (the runner is vmapped over
        # the group axis when G > 1 — trainer/base._serve_start).
        # Requests route to groups sticky by session/prefix key, so an
        # entry's pinned pages always live in the pool that holds them.
        self.G = int(geom.get("groups", 1) or 1)
        if self.G != cfg.groups:
            raise ValueError(
                f"serve geometry groups={self.G} != config groups="
                f"{cfg.groups} — the runner was traced for a different "
                "lane-group count"
            )
        # the persistent serve pool(s) (device) + host ledger(s): G == 1
        # keeps the historic unstacked layout (and exact call contract);
        # G > 1 stacks a leading group axis on every pool leaf
        pool0 = paged_kv.init_pool(
            geom["n_layer"], self.NP, self.PS, geom["n_kv_head"],
            geom["head_dim"], geom["kv_quant"], geom["dtype"],
        )
        if self.G == 1:
            self.pool = pool0
        else:
            import jax.numpy as jnp

            self.pool = jax.tree_util.tree_map(
                lambda x: jnp.zeros((self.G,) + x.shape, x.dtype), pool0
            )
        self.ledgers = [
            skv.PageLedger(self.NP, self.PS) for _ in range(self.G)
        ]
        self.ledger = self.ledgers[0]  # the single-group fast path
        self.sched = SLOScheduler(cfg.default_deadline_s, cfg.max_batch)
        # serving RNG: ONE fixed base key — the engine folds the
        # per-request rng_row in, so a request's stream depends only on
        # (serve.seed, request id), never on batch composition or which
        # tick served it
        self._rng = jax.random.PRNGKey(cfg.seed)
        # transport: hosts the hub on the tcp backend; clients connect
        # with `transport_spec`. Spec parsing/validation lives in
        # exp/net.py (a typo'd backend fails loudly, never a silent
        # shared-fs fallback).
        import os

        self.hub, self.transport, self.transport_spec = (
            net.make_server_transport(
                cfg.transport, os.path.join(checkpoint_dir, "serve")
            )
        )
        # bounded intake/result bookkeeping: a long-lived frontend must
        # not grow without bound with the request count. _seen only has
        # to cover the list->get->delete race window; posted results
        # are retained on the transport for a bounded tail (clients
        # also delete their result on pickup — see ServeClient.result)
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self._posted: deque = deque()
        self._result_queue: List[ServeResult] = []
        self._records: List[_Record] = []
        self._gen_stats: Dict[str, float] = {}
        self.stats: Dict[str, Any] = {
            "ticks": 0,
            "batches": 0,
            "completed": 0,
            "errors": 0,
            "cancelled": 0,
            "deadline_missed": 0,
            "transport_drops": 0,
            "starvation_reports": 0,
        }
        logger.info(
            "serve frontend up: P=%d N=%d page_size=%d pool_pages=%d "
            "transport=%s", self.P, self.N, self.PS, self.NP,
            self.transport_spec,
        )

    # -- intake ------------------------------------------------------------

    def _poll_requests(self, now: float) -> None:
        try:
            names = self.transport.list(REQUESTS_TOPIC)
        except (OSError, ConnectionError) as e:
            logger.warning("serve: request poll failed (%s)", e)
            return
        for name in names:
            if name in self._seen:
                continue
            try:
                meta = self.transport.get_meta(REQUESTS_TOPIC, name)
                if meta is None:
                    continue
                self.transport.delete(REQUESTS_TOPIC, name)
            except (OSError, ConnectionError) as e:
                # transient outage mid-intake: leave the request on the
                # transport UNMARKED so the next tick retries it — a
                # request must never be dropped by a blip
                logger.warning(
                    "serve: request intake of %r failed (%s) — retrying "
                    "next tick", name, e,
                )
                continue
            self._seen[name] = None
            while len(self._seen) > 8192:
                self._seen.popitem(last=False)
            try:
                req = ServeRequest.from_meta(meta)
            except (KeyError, TypeError, ValueError) as e:
                self._post(ServeResult(rid=name, status=ERROR,
                                       detail=f"malformed request: {e}"))
                continue
            if self.chaos is not None and self.chaos.consult(
                "serve_request_timeout"
            ):
                # chaos: the request spent its whole deadline in some
                # upstream queue — it arrives already expired and must
                # be evicted (pages reclaimed via the session sweep),
                # never admitted
                req.deadline_s = 0.0
            self.sched.submit(req, now)

    # -- tick --------------------------------------------------------------

    def tick(self, step: int = 0) -> int:
        """One lane-refill decision point. Returns batches run."""
        now = self._clock()
        self.stats["ticks"] += 1
        self._poll_requests(now)
        # deadline eviction: queued requests past their deadline, and
        # idle sessions past theirs (reclaiming their pinned pages)
        for pend in self.sched.expire(now):
            self._post(ServeResult(
                rid=pend.req.rid, status=TIMEOUT,
                detail="deadline expired before service",
                latency_s=now - pend.arrival_t,
                session_id=pend.req.session_id,
            ))
        # a session with a turn already QUEUED must not lose its pinned
        # history to the idle-deadline sweep out from under that turn
        for ledger in self.ledgers:
            ledger.expire_deadlines(
                now, skip=self.sched.pending_session_keys()
            )
        starved = self.chaos is not None and self.chaos.consult(
            "serve_lane_starvation"
        )
        ran = 0
        if not starved:
            while ran < self.cfg.max_batches_per_tick:
                batch = self.sched.pick(self._clock())
                if not batch:
                    break
                self._run_batch(batch)
                ran += 1
        for report in self.sched.note_tick(
            ran >= self.cfg.max_batches_per_tick, starved,
            self.cfg.starvation_report_after,
        ):
            self.stats["starvation_reports"] += 1
            logger.warning(
                "serve: %s — %d requests pending after %d consecutive "
                "ticks (%s)", report, self.sched.pending,
                self.cfg.starvation_report_after,
                "serving used its full lane allowance; training refills "
                "are being deferred (bounded by max_batches_per_tick — "
                "training proceeds, slower)"
                if report == "training_starved" else
                "no lane capacity reached serving; aging requests will "
                "be deadline-evicted",
            )
            if self.obs is not None:
                self.obs.record("serve_starvation", kind=report,
                                pending=self.sched.pending, step=step)
        self._flush_results()
        return ran

    # -- row construction --------------------------------------------------

    def _compose(self, head_ids, head_mask, tail_ids, tail_mask):
        """[head | pad gap | tail] at width P (the serve row layout:
        internal pads between the shared/aligned head and the divergent
        tail keep shared tokens at canonical slot positions while
        cumsum-derived rotary positions stay those of the unpadded
        conversation)."""
        gap = self.P - len(head_ids) - len(tail_ids)
        if gap < 0:
            raise RowError(
                f"request needs {len(head_ids) + len(tail_ids)} prompt "
                f"slots, row width is {self.P} (train.serve.max_prompt_len)"
            )
        ids = np.concatenate([
            np.asarray(head_ids, np.int32),
            np.full(gap, self.pad_id, np.int32),
            np.asarray(tail_ids, np.int32),
        ])
        mask = np.concatenate([
            np.asarray(head_mask, np.int32),
            np.zeros(gap, np.int32),
            np.asarray(tail_mask, np.int32),
        ])
        return ids, mask

    def _group_of(self, req: ServeRequest) -> int:
        """Lane group for a request: sessions and prefixes hash their
        CACHE KEY (sticky — their pinned pages live in exactly one
        group's pool), everything else hashes the request id (stateless
        spread). Stable across processes (crc32, not PYTHONHASHSEED)."""
        if self.G == 1:
            return 0
        import zlib

        if req.session_id and self.cfg.sessions:
            key = skv.session_key(req.session_id)
        elif req.prefix_ids and self.cfg.prefix_cache:
            key = skv.prefix_key(list(req.prefix_ids))
        else:
            key = req.rid
        return zlib.crc32(key.encode()) % self.G

    def _build_row(
        self, pend: Pending, now: float, used_keys: set,
        ledger: skv.PageLedger,
    ) -> _RowCtx:
        req = pend.req
        budget = min(
            int(req.max_tokens or self.cfg.default_max_tokens), self.N
        )
        budget = max(budget, 1)
        table_row = np.zeros(self.MP, np.int32)
        rrow = rng_row(req.rid, self.N)
        if not req.prompt_ids and not req.session_id:
            raise RowError("empty prompt")

        # -- multi-turn session continuation
        if req.session_id and self.cfg.sessions:
            key = skv.session_key(req.session_id)
            if key in used_keys:
                # one in-flight turn per session: a same-batch second
                # turn would fork the pinned conversation
                raise DeferRow()
            used_keys.add(key)
            entry = ledger.acquire(key, now)
            if entry is not None:
                tail_ids = list(entry.pending_ids) + list(req.prompt_ids)
                tail_mask = list(entry.pending_mask) + [1] * len(
                    req.prompt_ids
                )
                try:
                    ids, mask = self._compose(
                        entry.layout_ids, entry.layout_mask, tail_ids,
                        tail_mask,
                    )
                except RowError:
                    ledger.release(key)
                    raise RowError(
                        "session overflow: the pinned conversation plus "
                        "the new turn no longer fits the serve row — end "
                        "the session or raise max_prompt_len"
                    )
                npg = len(entry.pages)
                table_row[:npg] = entry.pages
                return _RowCtx(
                    pend=pend, ids=ids, mask=mask, budget=budget, pin=True,
                    ready=entry.kv_len, rngrow=rrow, table_row=table_row,
                    entry_key=key, adopt_session=key,
                    shared_pages=int((entry.pages > 0).sum()),
                )
            # new session: a plain (optionally prefix-shared) row,
            # pinned. The note keeps history loss HONEST: a client that
            # expected a continuation (entry deadline-evicted between
            # turns) can see it was served without context
            ctx = self._prefix_or_plain(
                pend, budget, rrow, now, pin=True, used_keys=used_keys,
                ledger=ledger,
            )
            ctx.adopt_session = key
            ctx.note = "fresh session (no pinned history)"
            return ctx

        return self._prefix_or_plain(
            pend, budget, rrow, now, pin=False, used_keys=used_keys,
            ledger=ledger,
        )

    def _prefix_or_plain(
        self, pend: Pending, budget: int, rrow: int, now: float, pin: bool,
        used_keys: set, ledger: skv.PageLedger,
    ) -> _RowCtx:
        req = pend.req
        table_row = np.zeros(self.MP, np.int32)
        prefix = list(req.prefix_ids or [])
        A = skv.aligned_len(len(prefix), self.PS)
        if self.cfg.prefix_cache and A >= self.PS:
            key = skv.prefix_key(prefix)
            entry = ledger.acquire(key, now)
            if entry is not None:
                try:
                    ids, mask = self._compose(
                        entry.layout_ids, entry.layout_mask,
                        prefix[A:] + list(req.prompt_ids),
                        [1] * (len(prefix) - A + len(req.prompt_ids)),
                    )
                except RowError:
                    # over-long request: the acquired ref must not
                    # outlive the row (a leaked ref would pin the
                    # entry's pages against eviction forever)
                    ledger.release(key)
                    raise
                npg = len(entry.pages)
                table_row[:npg] = entry.pages
                return _RowCtx(
                    pend=pend, ids=ids, mask=mask, budget=budget, pin=pin,
                    ready=entry.kv_len, rngrow=rrow, table_row=table_row,
                    entry_key=key,
                    shared_pages=int((entry.pages > 0).sum()),
                )
            if key not in used_keys:
                # pioneer: prefix at canonical slots 0..Lp-1, pinned so
                # the aligned pages can be adopted into the cache at
                # finish. Only ONE pioneer per prefix per batch —
                # same-batch peers run unshared below and share from
                # the next batch on.
                used_keys.add(key)
                ids, mask = self._compose(
                    prefix, [1] * len(prefix), list(req.prompt_ids),
                    [1] * len(req.prompt_ids),
                )
                return _RowCtx(
                    pend=pend, ids=ids, mask=mask, budget=budget,
                    pin=True, ready=0, rngrow=rrow, table_row=table_row,
                    adopt_prefix=prefix,
                )
        # plain: classic left-padded row
        ids, mask = self._compose(
            [], [], prefix + list(req.prompt_ids),
            [1] * (len(prefix) + len(req.prompt_ids)),
        )
        return _RowCtx(
            pend=pend, ids=ids, mask=mask, budget=budget, pin=pin,
            ready=0, rngrow=rrow, table_row=table_row,
        )

    # -- the engine call ---------------------------------------------------

    def _run_batch(self, batch: List[Pending]) -> None:
        now = self._clock()
        rows_by_group: List[List[_RowCtx]] = [[] for _ in range(self.G)]
        used_keys: set = set()
        deferred: List[Pending] = []
        for pend in batch:
            g = self._group_of(pend.req)
            try:
                ctx = self._build_row(pend, now, used_keys, self.ledgers[g])
                ctx.group = g
                rows_by_group[g].append(ctx)
            except DeferRow:
                deferred.append(pend)
            except RowError as e:
                self.stats["errors"] += 1
                self._post(ServeResult(
                    rid=pend.req.rid, status=ERROR, detail=str(e),
                    latency_s=self._clock() - pend.arrival_t,
                    session_id=pend.req.session_id,
                ))
        if deferred:
            self.sched.requeue(deferred)
        rows = [c for grp in rows_by_group for c in grp]
        if not rows:
            return
        try:
            self._dispatch_rows(rows_by_group)
        except Exception:
            # a failed batch (device error, transport hiccup mid-result)
            # must not strand its requests: release every still-held
            # cache ref and hand the requests back to the queue — they
            # retry next tick, bounded by their own deadlines (a
            # persistent failure degrades to deadline eviction, never a
            # wedge or a leaked pin)
            for c in rows:
                if c.entry_key is not None:
                    self.ledgers[c.group].release(c.entry_key)
                    c.entry_key = None
            self.sched.requeue([c.pend for c in rows])
            self.stats["batch_failures"] = (
                self.stats.get("batch_failures", 0) + 1
            )
            raise

    def _assemble_group(self, rows: List[_RowCtx]):
        """One group's [max_batch]-wide engine arrays; unfilled rows are
        dummy lanes (one real token, budget 1 — finished at refill)."""
        Q = self.cfg.max_batch
        ids = np.full((Q, self.P), self.pad_id, np.int32)
        mask = np.zeros((Q, self.P), np.int32)
        ids[:, -1] = 0
        mask[:, -1] = 1
        budget = np.ones(Q, np.int32)
        pin = np.zeros(Q, bool)
        ready = np.zeros(Q, np.int32)
        rngrow = np.zeros(Q, np.int32)
        table = np.zeros((Q, self.MP), np.int32)
        for i, c in enumerate(rows):
            ids[i], mask[i] = c.ids, c.mask
            budget[i] = c.budget
            pin[i] = c.pin
            ready[i] = c.ready
            rngrow[i] = c.rngrow
            table[i] = c.table_row
        return ids, mask, budget, pin, ready, rngrow, table

    def _dispatch_rows(self, rows_by_group: List[List[_RowCtx]]) -> None:
        import jax.numpy as jnp

        # pool pressure: make room for each group's worst-case pages —
        # prompt AND response (a lane can grow to MP pages through
        # decode) — by LRU-evicting refcount-zero entries; a shortfall
        # degrades to fewer admitted lanes inside the engine
        # (oom-truncation, reported as an error result), never a
        # deadlock
        for g, grp in enumerate(rows_by_group):
            if grp:
                self.ledgers[g].evict_for(
                    len(grp) * self.MP, self.cfg.max_cache_entries
                )
        assembled = [self._assemble_group(grp) for grp in rows_by_group]
        refcnts = [
            self.ledgers[g].compose_refcnt(
                [c.table_row for c in grp if c.ready > 0]
            )
            for g, grp in enumerate(rows_by_group)
        ]
        t0 = self._clock()
        if self.G == 1:
            ids, mask, budget, pin, ready, rngrow, table = assembled[0]
            warm = {
                "pool": self.pool,
                "free": jnp.asarray(self.ledger.free),
                "ntop": jnp.int32(self.ledger.ntop),
                "refcnt": jnp.asarray(refcnts[0]),
                "row_table": jnp.asarray(table),
            }
            out = self.runner(
                jnp.asarray(ids), jnp.asarray(mask), self._rng,
                jnp.asarray(budget), warm, jnp.asarray(pin),
                jnp.asarray(ready), jnp.asarray(rngrow),
            )
            kvs = out["kv_state"]
            self.pool = kvs["pool"]
            self.ledger.adopt_stack(
                np.asarray(kvs["free"]), int(kvs["ntop"])
            )
            per_group = [(
                rows_by_group[0], ids, mask,
                np.asarray(out["response_ids"]),
                np.asarray(out["response_mask"]),
                np.asarray(kvs["saved_tables"]),
                np.asarray(kvs["saved_len"]),
                self.ledger,
            )]
            gstats = {
                k: float(np.asarray(v)) for k, v in out["gen_stats"].items()
            }
        else:
            # sharded lanes: ONE stacked dispatch serves every group
            # (the runner is vmapped over axis 0; empty groups ride as
            # all-dummy batches so their warm pools round-trip intact)
            def stk(i):
                return jnp.asarray(np.stack([a[i] for a in assembled]))

            warm = {
                "pool": self.pool,  # stacked leaves [G, ...]
                "free": jnp.asarray(
                    np.stack([led.free for led in self.ledgers])
                ),
                "ntop": jnp.asarray(
                    np.asarray([led.ntop for led in self.ledgers], np.int32)
                ),
                "refcnt": jnp.asarray(np.stack(refcnts)),
                "row_table": stk(6),
            }
            out = self.runner(
                stk(0), stk(1), self._rng, stk(2), warm, stk(3), stk(4),
                stk(5),
            )
            kvs = out["kv_state"]
            self.pool = kvs["pool"]
            free_np = np.asarray(kvs["free"])
            ntop_np = np.asarray(kvs["ntop"])
            resp_np = np.asarray(out["response_ids"])
            rmask_np = np.asarray(out["response_mask"])
            saved_t_np = np.asarray(kvs["saved_tables"])
            saved_l_np = np.asarray(kvs["saved_len"])
            per_group = []
            for g, grp in enumerate(rows_by_group):
                self.ledgers[g].adopt_stack(free_np[g], int(ntop_np[g]))
                per_group.append((
                    grp, assembled[g][0], assembled[g][1], resp_np[g],
                    rmask_np[g], saved_t_np[g], saved_l_np[g],
                    self.ledgers[g],
                ))
            gstats = {
                k: float(np.asarray(v).sum())
                for k, v in out["gen_stats"].items()
            }
        wall = max(self._clock() - t0, 1e-9)
        self.stats["batches"] += 1
        # honest accounting: batches are padded to max_batch with dummy
        # lanes (1 emitted token each) — count only REAL requests'
        # tokens, and drop the dummy-polluted ratios
        real_toks = sum(
            int(pg[4][: len(pg[0])].sum()) for pg in per_group
        )
        gstats["real_tokens"] = float(real_toks)
        gstats.pop("truncated", None)
        gstats.pop("occupancy", None)
        for k, v in gstats.items():
            self._gen_stats[k] = self._gen_stats.get(k, 0.0) + v
        # gauges, not counters: free_pages is the end-of-call stack
        # depth; pinned_pages re-counts a session's whole page set
        # every turn, so the accumulated sum is meaningless — keep the
        # last call's value (current pinned residency lives in
        # kv_held_pages in the summary)
        self._gen_stats["free_pages"] = gstats.get("free_pages", 0.0)
        self._gen_stats["pinned_pages"] = gstats.get("pinned_pages", 0.0)
        decode_tok_s = real_toks / wall
        done = self._clock()
        for grp, ids, mask, resp, rmask, saved_t, saved_l, ledger in per_group:
            for i, c in enumerate(grp):
                if c.entry_key is not None:
                    ledger.release(c.entry_key)
                    c.entry_key = None  # failure handler must not re-release
                n = int(rmask[i].sum())
                if c.pin:
                    self._adopt_row(c, ids[i], mask[i], resp[i], n,
                                    saved_t[i], saved_l[i], done, ledger)
                met = done <= c.pend.deadline_t
                if not met:
                    self.stats["deadline_missed"] += 1
                self.stats["completed"] += 1
                if n == 0:
                    # the engine could not admit the lane at all (pool
                    # exhausted past what eviction could reclaim): an
                    # honest error beats a silent empty completion
                    self.stats["errors"] += 1
                if n == 0:
                    parts = ["unserved: serve pool exhausted"]
                else:
                    parts = [p for p in (
                        c.note, "" if met else "completed past deadline"
                    ) if p]
                res = ServeResult(
                    rid=c.pend.req.rid,
                    status=OK if n > 0 else ERROR,
                    tokens=[int(t) for t in resp[i][rmask[i] > 0]],
                    detail="; ".join(parts),
                    latency_s=done - c.pend.arrival_t,
                    queue_wait_s=t0 - c.pend.arrival_t,
                    decode_tok_s=decode_tok_s,
                    shared_pages=c.shared_pages,
                    session_id=c.pend.req.session_id,
                )
                self._records.append(_Record(
                    latency_s=res.latency_s, queue_wait_s=res.queue_wait_s,
                    decode_tok_s=decode_tok_s, deadline_met=met,
                ))
                self._post(res)
        del self._records[:-512]

    def _adopt_row(self, c, row_ids, row_mask, resp, n, table_row,
                   saved_len, now, ledger: skv.PageLedger) -> None:
        """Fold a pinned row's pages into the cache (session turn or
        prefix pioneer); surplus pages past the aligned boundary go
        straight back to the free stack (the copy-on-write half: the
        next turn/request re-prefills the unaligned remainder into its
        own pages)."""
        saved_len = int(saved_len)
        A = skv.aligned_len(saved_len, self.PS)
        npg = A // self.PS
        surplus = table_row[npg:][table_row[npg:] > 0]
        if c.adopt_session is not None and self.cfg.sessions and n > 0:
            full_ids = np.concatenate(
                [row_ids, resp[: max(saved_len - self.P, 0)]]
            )[:saved_len]
            full_mask = np.concatenate(
                [row_mask, np.ones(max(saved_len - self.P, 0), np.int32)]
            )[:saved_len]
            # page-granular SLOT compaction: an all-pad page (the
            # engine already released it — its table entry is 0)
            # contributes no KV and no rotary positions, so its PS-slot
            # block can be dropped from the pinned layout outright.
            # Without this every turn would bake its pad gap into the
            # session forever and a handful of turns would overflow the
            # row width; with it the session's slot budget tracks REAL
            # conversation content (plus page rounding).
            keep_pages, keep_blocks_ids, keep_blocks_mask = [], [], []
            corrupt = False
            for j in range(npg):
                blk = slice(j * self.PS, (j + 1) * self.PS)
                if table_row[j] > 0:
                    keep_pages.append(int(table_row[j]))
                    keep_blocks_ids.append(full_ids[blk])
                    keep_blocks_mask.append(full_mask[blk])
                elif int(full_mask[blk].sum()) > 0:
                    # a null page under REAL tokens: nothing valid to
                    # pin — refuse the adoption rather than cache a
                    # corrupt conversation
                    corrupt = True
                    break
            if corrupt:
                logger.error(
                    "serve: session %s adoption refused (real tokens on "
                    "a released page) — pages freed, session not pinned",
                    c.adopt_session,
                )
                ledger.push_unheld(table_row)
                return
            ledger.adopt(
                c.adopt_session, "session",
                np.asarray(keep_pages, np.int32),
                np.concatenate(keep_blocks_ids)
                if keep_blocks_ids else np.zeros(0, np.int32),
                np.concatenate(keep_blocks_mask)
                if keep_blocks_mask else np.zeros(0, np.int32),
                pending_ids=[int(t) for t in full_ids[A:]]
                + [int(resp[n - 1])],
                pending_mask=[int(m) for m in full_mask[A:]] + [1],
                now=now,
                deadline_t=now + self.cfg.session_deadline_s,
            )
            ledger.push(surplus)
            return
        if (
            c.adopt_prefix is not None
            and saved_len >= len(c.adopt_prefix)
            and npg > 0
        ):
            Ap = skv.aligned_len(len(c.adopt_prefix), self.PS)
            npp = Ap // self.PS
            ledger.adopt(
                skv.prefix_key(c.adopt_prefix), "prefix",
                table_row[:npp],
                np.asarray(c.adopt_prefix[:Ap], np.int32),
                np.ones(Ap, np.int32),
                pending_ids=[], now=now,
            )
            ledger.push(table_row[npp:][table_row[npp:] > 0])
            return
        # nothing adoptable: free everything the pin kept
        ledger.push_unheld(table_row)

    # -- results -----------------------------------------------------------

    def _post(self, res: ServeResult) -> None:
        self._result_queue.append(res)

    def _flush_results(self) -> None:
        remaining: List[ServeResult] = []
        for res in self._result_queue:
            if self.chaos is not None and self.chaos.consult(
                "serve_transport_drop"
            ):
                # chaos: the result frame is lost on the wire — keep it
                # queued; the re-post under the same name dedups at the
                # hub/filesystem, so delivery converges to exactly-once
                self.stats["transport_drops"] += 1
                remaining.append(res)
                continue
            try:
                self.transport.put(RESULTS_TOPIC, res.rid, res.to_meta())
            except (OSError, ConnectionError) as e:
                logger.warning("serve: result post failed (%s) — retrying "
                               "next tick", e)
                remaining.append(res)
                continue
            # bounded retention: results a client never picks up (its
            # own delete on read is the fast path) age out of the
            # transport after a generous tail
            self._posted.append(res.rid)
            while len(self._posted) > 2048:
                old = self._posted.popleft()
                try:
                    self.transport.delete(RESULTS_TOPIC, old)
                except (OSError, ConnectionError):
                    pass
        self._result_queue = remaining

    # -- teardown / reporting ----------------------------------------------

    def close(self) -> None:
        """Flush, cancel whatever is still queued (a client must never
        hang on a frontend that went away), and stop the hub."""
        now = self._clock()
        # final transport poll: a request that landed after the last
        # tick must get a cancelled result, not a client-side hang
        self._poll_requests(now)
        # drain EVERYTHING still pending with a cancelled result
        while True:
            batch = self.sched.pick(now)
            if not batch:
                break
            for pend in batch:
                self.stats["cancelled"] += 1
                self._post(ServeResult(
                    rid=pend.req.rid, status=CANCELLED,
                    detail="serving frontend shut down",
                    latency_s=now - pend.arrival_t,
                    session_id=pend.req.session_id,
                ))
        self._flush_results()
        if self.hub is not None:
            self.hub.close()
        logger.info("serve frontend closed: %s", self.stats_summary())

    def stats_summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {**self.stats, **self.sched.stats}
        # ledger counters/accounting sum over lane groups (G == 1 is
        # the degenerate single-ledger sum)
        kv: Dict[str, float] = {}
        for led in self.ledgers:
            for k, v in led.stats.items():
                kv[k] = kv.get(k, 0) + v
        out.update({f"kv_{k}": v for k, v in kv.items()})
        out.update(
            {f"engine_{k}": v for k, v in self._gen_stats.items()}
        )
        out["pending"] = self.sched.pending
        out["cache_entries"] = sum(len(led.entries) for led in self.ledgers)
        out["kv_held_pages"] = sum(
            led.accounting()["held"] for led in self.ledgers
        )
        if self.G > 1:
            out["lane_groups"] = self.G
        out.update(self.slo_report())
        return out

    def slo_report(self) -> Dict[str, float]:
        """Latency/decode percentiles over the recent request window —
        the numbers the bench serve section records."""
        if not self._records:
            return {}
        lat = np.asarray([r.latency_s for r in self._records])
        qw = np.asarray([r.queue_wait_s for r in self._records])
        dec = np.asarray([r.decode_tok_s for r in self._records])
        met = np.asarray([r.deadline_met for r in self._records])
        return {
            # v1: whole-request decode in one dispatch => ttft == latency
            "ttft_p50_s": float(np.percentile(lat, 50)),
            "ttft_p95_s": float(np.percentile(lat, 95)),
            "latency_p50_s": float(np.percentile(lat, 50)),
            "latency_p95_s": float(np.percentile(lat, 95)),
            "queue_wait_p50_s": float(np.percentile(qw, 50)),
            "queue_wait_p95_s": float(np.percentile(qw, 95)),
            "decode_tok_s_p50": float(np.percentile(dec, 50)),
            "deadline_met_rate": float(met.mean()),
            "window_requests": int(len(self._records)),
        }
