"""Hyperparameter sweeps: `python -m trlx_tpu.sweep --config sweeps/x.yml examples/script.py`.

Parity: /root/reference/trlx/sweep.py:17-348 — same YAML schema (per-param
`strategy` + `values`, `tune_config` with metric/mode/search_alg/
scheduler/num_samples) and the same contract with examples
(`main(hparams)` with dotted-path overrides). The Ray Tune backend is
replaced by a first-party runner. By default trials run one after
another on the full mesh (a TPU slice is one shared resource); on
hardware that subdivides — a pod whose hosts can run independent
slices, or a CPU dev box — `tune_config.max_concurrent: N` fans trials
out over N subprocess slots (the reference fans out over Ray workers,
trlx/sweep.py:233-266). Each slot can pin its own device subset via
`tune_config.slot_env` (a list of env-var dicts, e.g. per-slot
TPU_VISIBLE_CHIPS or XLA_FLAGS), since one jax process must own its
devices exclusively.

Search algorithms (reference get_search_alg :102-134):
  random / grid   built-in sampling
  bayesopt, bohb  first-party TPE (Tree-structured Parzen Estimator):
                  after a few seed trials, model good vs bad observations
                  with Parzen windows per parameter and pick the
                  candidate maximizing the good/bad likelihood ratio —
                  the same ask/tell shape as Ray's BayesOptSearch/BOHB
                  without the skopt/hpbandster deps (absent in the image).

Scheduler (reference get_scheduler :136-159): `hyperband` runs successive
halving over `train.total_steps` budgets (eta=3): each rung reruns the
surviving configs at 3x the budget, keeping the top third.

Each trial's metrics come from the JSONL tracker (utils/trackers.py); the
JSON + markdown report includes per-parameter importance (|Spearman
correlation| with the objective), replacing the reference's W&B report
builder (:228-348)."""

from __future__ import annotations

import argparse
import importlib.util
import itertools
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import yaml

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


# ---------------------------------------------------------------------------
# param space sampling (reference get_param_space :17-100)
# ---------------------------------------------------------------------------


def _sample_strategy(rng: np.random.Generator, value: Dict[str, Any]):
    strategy, values = value["strategy"], value["values"]
    if strategy == "uniform":
        return float(rng.uniform(*values))
    if strategy == "quniform":
        lo, hi, q = values
        return float(np.round(rng.uniform(lo, hi) / q) * q)
    if strategy == "loguniform":
        lo, hi = values[:2]
        return float(np.exp(rng.uniform(np.log(lo), np.log(hi))))
    if strategy == "qloguniform":
        lo, hi, q = values[0], values[1], values[3] if len(values) > 3 else values[2]
        return float(np.round(np.exp(rng.uniform(np.log(lo), np.log(hi))) / q) * q)
    if strategy == "randn":
        mean, sd = values
        return float(rng.normal(mean, sd))
    if strategy == "qrandn":
        mean, sd, q = values
        return float(np.round(rng.normal(mean, sd) / q) * q)
    if strategy == "randint":
        lo, hi = values
        return int(rng.integers(lo, hi))
    if strategy == "qrandint":
        lo, hi, q = values
        return int(np.round(rng.integers(lo, hi) / q) * q)
    if strategy in ("lograndint", "qlograndint"):
        lo, hi = values[0], values[1]
        x = np.exp(rng.uniform(np.log(lo), np.log(hi)))
        q = values[3] if strategy == "qlograndint" else 1
        return int(np.round(x / q) * q)
    if strategy == "choice":
        return values[int(rng.integers(len(values)))]
    raise ValueError(f"unknown strategy {strategy!r}")


def generate_trials(param_space: Dict[str, Any], tune_config: Dict[str, Any], seed: int = 0) -> List[Dict[str, Any]]:
    """Expand grid axes × num_samples random draws into trial hparams."""
    rng = np.random.default_rng(seed)
    grid_axes = {
        k: v["values"] for k, v in param_space.items() if v["strategy"] == "grid"
    }
    sampled_axes = {k: v for k, v in param_space.items() if v["strategy"] != "grid"}

    grid_points: List[Dict[str, Any]] = [{}]
    if grid_axes:
        keys = list(grid_axes)
        grid_points = [
            dict(zip(keys, combo))
            for combo in itertools.product(*(grid_axes[k] for k in keys))
        ]

    num_samples = int(tune_config.get("num_samples", 1))
    trials = []
    for point in grid_points:
        for _ in range(num_samples if sampled_axes else 1):
            hparams = dict(point)
            for k, v in sampled_axes.items():
                hparams[k] = _sample_strategy(rng, v)
            trials.append(hparams)
    return trials


# ---------------------------------------------------------------------------
# search algorithms (ask/tell)
# ---------------------------------------------------------------------------


class RandomSearch:
    """Independent draws from the param space (reference search_alg=None)."""

    def __init__(self, param_space: Dict[str, Any], seed: int = 0):
        self.space = {
            k: v for k, v in param_space.items() if v["strategy"] != "grid"
        }
        self.rng = np.random.default_rng(seed)

    def ask(self) -> Dict[str, Any]:
        return {k: _sample_strategy(self.rng, v) for k, v in self.space.items()}

    def tell(self, hparams: Dict[str, Any], score) -> None:
        pass


class TPESearch(RandomSearch):
    """Tree-structured Parzen Estimator over the sampled axes.

    Observations are split at the `gamma` quantile into good/bad sets;
    each numeric axis gets a Parzen window (Gaussian KDE) per set, choice
    axes get add-one categorical frequencies. Ask draws `n_candidates`
    from the good model and returns the argmax of l_good/l_bad. Runs as
    pure numpy — this is what bayesopt/bohb resolve to."""

    def __init__(
        self,
        param_space: Dict[str, Any],
        mode: str = "max",
        seed: int = 0,
        n_initial: int = 5,
        gamma: float = 0.25,
        n_candidates: int = 32,
    ):
        super().__init__(param_space, seed)
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.obs: List[Tuple[Dict[str, Any], float]] = []

    def tell(self, hparams: Dict[str, Any], score) -> None:
        if score is not None and np.isfinite(score):
            self.obs.append((hparams, float(score)))

    def _split(self):
        scores = np.asarray([s for _, s in self.obs])
        order = np.argsort(scores)
        if self.mode == "max":
            order = order[::-1]
        n_good = max(1, int(np.ceil(self.gamma * len(order))))
        good = [self.obs[i][0] for i in order[:n_good]]
        bad = [self.obs[i][0] for i in order[n_good:]] or good
        return good, bad

    @staticmethod
    def _kde_logpdf(x: np.ndarray, data: np.ndarray) -> np.ndarray:
        sd = np.std(data) or 1.0
        bw = max(1.06 * sd * len(data) ** -0.2, 1e-6 * max(abs(sd), 1.0))
        d = (x[:, None] - data[None, :]) / bw
        return np.log(
            np.mean(np.exp(-0.5 * d * d), axis=1) / (bw * np.sqrt(2 * np.pi))
            + 1e-300
        )

    def ask(self) -> Dict[str, Any]:
        if len(self.obs) < self.n_initial:
            return super().ask()
        good, bad = self._split()
        cand = [super(TPESearch, self).ask() for _ in range(self.n_candidates)]
        ratio = np.zeros(len(cand))
        for k, spec in self.space.items():
            cvals = [c[k] for c in cand]
            if spec["strategy"] == "choice":
                choices = list(spec["values"])

                def cat_logp(vals, data):
                    counts = np.asarray(
                        [sum(d == c for d in data) + 1.0 for c in choices]
                    )
                    p = counts / counts.sum()
                    idx = [choices.index(v) for v in vals]
                    return np.log(p[idx])

                ratio += cat_logp(cvals, [g[k] for g in good])
                ratio -= cat_logp(cvals, [b[k] for b in bad])
            else:
                x = np.asarray(cvals, float)
                log = spec["strategy"] in (
                    "loguniform", "qloguniform", "lograndint", "qlograndint"
                )
                f = np.log if log else (lambda v: v)
                ratio += self._kde_logpdf(f(x), f(np.asarray([g[k] for g in good], float)))
                ratio -= self._kde_logpdf(f(x), f(np.asarray([b[k] for b in bad], float)))
        return cand[int(np.argmax(ratio))]


def make_search_alg(name, param_space, tune_config, seed: int = 0):
    mode = tune_config.get("mode", "max")
    if name in (None, "random", "grid"):
        return RandomSearch(param_space, seed)
    if name in ("bayesopt", "bohb", "tpe"):
        return TPESearch(param_space, mode=mode, seed=seed)
    raise ValueError(f"unknown search_alg {name!r}")


def hyperband_rungs(max_budget: int, eta: int = 3, min_budget: Optional[int] = None):
    """Successive-halving rungs [(n_configs_multiplier, budget), ...]:
    budgets grow by eta, survivors shrink by eta (reference
    HyperBandScheduler semantics on the total_steps resource)."""
    min_budget = min_budget or max(max_budget // (eta * eta), 1)
    budgets = []
    b = min_budget
    while b < max_budget:
        budgets.append(int(b))
        b *= eta
    budgets.append(int(max_budget))
    return budgets


# ---------------------------------------------------------------------------
# trial execution
# ---------------------------------------------------------------------------


def _load_main(script_path: str):
    spec = importlib.util.spec_from_file_location("sweep_target", script_path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["sweep_target"] = module
    spec.loader.exec_module(module)
    return module.main


def _spearman(x: np.ndarray, y: np.ndarray) -> float:
    """Rank correlation (no scipy in the hot path)."""

    def rank(a):
        order = np.argsort(a)
        r = np.empty(len(a))
        r[order] = np.arange(len(a))
        return r

    rx, ry = rank(x), rank(y)
    sx, sy = rx.std(), ry.std()
    if sx == 0 or sy == 0:
        return 0.0
    return float(np.corrcoef(rx, ry)[0, 1])


def param_importance(results: List[Dict], metric: str) -> Dict[str, float]:
    """|Spearman| of each numeric hparam vs the objective (the W&B
    report's parameter-importance panel, air-gapped)."""
    scored = [r for r in results if r[metric] is not None]
    if len(scored) < 3:
        return {}
    out = {}
    keys = {
        k for r in scored for k, v in r["hparams"].items()
        if isinstance(v, (int, float)) and not k.startswith("train.checkpoint")
        and not k.startswith("train.logging")
    }
    y = np.asarray([r[metric] for r in scored], float)
    for k in sorted(keys):
        x = np.asarray(
            [float(r["hparams"].get(k, np.nan)) for r in scored], float
        )
        ok = np.isfinite(x)
        if ok.sum() >= 3 and np.std(x[ok]) > 0:
            out[k] = abs(_spearman(x[ok], y[ok]))
    return out


def run_sweep(script_path: str, config: Dict[str, Any], output_dir: str) -> Dict[str, Any]:
    tune_config = config.pop("tune_config")
    metric = tune_config.get("metric", "reward/mean")
    mode = tune_config.get("mode", "max")
    num_samples = int(tune_config.get("num_samples", 1))
    seed = int(tune_config.get("seed", 0))
    alg = make_search_alg(tune_config.get("search_alg"), config, tune_config, seed)
    budget_key = tune_config.get("budget_key", "train.total_steps")

    grid_axes = {
        k: v["values"] for k, v in config.items() if v["strategy"] == "grid"
    }
    grid_points: List[Dict[str, Any]] = [{}]
    if grid_axes:
        keys = list(grid_axes)
        grid_points = [
            dict(zip(keys, combo))
            for combo in itertools.product(*(grid_axes[k] for k in keys))
        ]

    max_concurrent = int(tune_config.get("max_concurrent", 1))
    slot_envs = tune_config.get("slot_env") or [{}] * max_concurrent
    if len(slot_envs) < max_concurrent:
        raise ValueError(
            f"tune_config.slot_env has {len(slot_envs)} entries for "
            f"max_concurrent={max_concurrent}"
        )
    main = None if max_concurrent > 1 else _load_main(script_path)
    os.makedirs(output_dir, exist_ok=True)
    results: List[Dict[str, Any]] = []
    trial_counter = itertools.count()

    def _prepare(hparams: Dict[str, Any], budget: Optional[int]):
        i = next(trial_counter)
        trial_dir = os.path.join(output_dir, f"trial_{i:03d}")
        full = dict(
            hparams, **{
                "train.checkpoint_dir": trial_dir,
                "train.logging_dir": os.path.join(trial_dir, "logs"),
            }
        )
        if budget is not None:
            full[budget_key] = int(budget)
        return i, trial_dir, full

    def _score_of(trial_dir: str):
        metrics_fp = os.path.join(trial_dir, "logs", "metrics.jsonl")
        if os.path.exists(metrics_fp):
            values = [
                rec[metric]
                for rec in map(json.loads, open(metrics_fp))
                if metric in rec
            ]
            if values:
                return max(values) if mode == "max" else min(values)
        return None

    def _record(i, hparams, full, budget, status, score, t0):
        results.append(
            {"trial": i, "hparams": full, metric: score,
             "status": status, "budget": budget, "time": time.time() - t0}
        )
        alg.tell(hparams, score)

    def run_trial(hparams: Dict[str, Any], budget: Optional[int] = None):
        i, trial_dir, full = _prepare(hparams, budget)
        logger.info("trial %d: %s", i, full)
        t0 = time.time()
        status = "ok"
        try:
            main(full)
        except Exception as e:  # a failed trial shouldn't kill the sweep
            logger.warning("trial %d failed: %s", i, e)
            status = f"error: {e}"
        score = _score_of(trial_dir)
        _record(i, hparams, full, budget, status, score, t0)
        return score

    def run_batch(specs: List[Tuple[Dict[str, Any], Optional[int]]]):
        """Run (hparams, budget) specs; returns their scores in order.
        Sequential on the full mesh by default; with max_concurrent > 1
        each trial runs in its own subprocess slot with that slot's env
        overlay (device pinning is the operator's slot_env contract)."""
        if max_concurrent == 1:
            return [run_trial(hp, b) for hp, b in specs]
        scores: List[Any] = [None] * len(specs)
        pending = list(enumerate(specs))
        active: Dict[int, Tuple] = {}  # slot -> (j, i, proc, t0, hp, full, budget, dir)
        try:
            _drain(pending, active, scores)
        finally:
            # an exception (or Ctrl-C) must not orphan training children:
            # they would keep holding the slots' pinned devices — and a
            # retried sweep would collide with them, so WAIT for each to
            # actually exit (kill after a grace period)
            for _, _, proc, *_ in active.values():
                proc.terminate()
            for _, _, proc, *_ in active.values():
                try:
                    proc.wait(timeout=10)
                except Exception:
                    proc.kill()
                    proc.wait()
        return scores

    def _drain(pending, active, scores):
        import subprocess

        while pending or active:
            while pending and len(active) < max_concurrent:
                slot = next(
                    s for s in range(max_concurrent) if s not in active
                )
                j, (hp, budget) = pending.pop(0)
                i, trial_dir, full = _prepare(hp, budget)
                logger.info("trial %d (slot %d): %s", i, slot, full)
                os.makedirs(trial_dir, exist_ok=True)
                # stderr goes to a FILE, not a pipe: training children
                # write far more than a pipe buffer (absl/jax logging),
                # and an undrained pipe would block the child forever
                errf = open(os.path.join(trial_dir, "stderr.log"), "w")
                proc = subprocess.Popen(
                    [
                        sys.executable, "-m", "trlx_tpu.sweep",
                        "--run-trial", script_path, json.dumps(full),
                    ],
                    env={**os.environ, **slot_envs[slot]},
                    stdout=subprocess.DEVNULL,
                    stderr=errf,
                    text=True,
                )
                errf.close()
                active[slot] = (j, i, proc, time.time(), hp, full, budget, trial_dir)
            done = [s for s, a in active.items() if a[2].poll() is not None]
            if not done:
                time.sleep(0.2)
                continue
            for slot in done:
                j, i, proc, t0, hp, full, budget, trial_dir = active.pop(slot)
                status = "ok"
                if proc.returncode != 0:
                    err = ""
                    try:
                        with open(os.path.join(trial_dir, "stderr.log")) as f:
                            err = f.read().strip()[-300:]
                    except OSError:
                        pass
                    logger.warning("trial %d failed: %s", i, err)
                    status = f"error: rc={proc.returncode} {err}"
                score = _score_of(trial_dir)
                _record(i, hp, full, budget, status, score, t0)
                scores[j] = score
        return scores

    if tune_config.get("scheduler") == "hyperband":
        max_budget = int(tune_config.get("max_budget", 0))
        if not max_budget:
            raise ValueError(
                "scheduler=hyperband needs tune_config.max_budget (the "
                f"largest {budget_key} to train a surviving config for)"
            )
        eta = int(tune_config.get("eta", 3))
        budgets = hyperband_rungs(max_budget, eta)
        for point in grid_points:
            configs = [dict(point, **alg.ask()) for _ in range(num_samples)]
            for rung, budget in enumerate(budgets):
                logger.info(
                    "hyperband rung %d: %d configs at %s=%d",
                    rung, len(configs), budget_key, budget,
                )
                scored = list(zip(
                    configs, run_batch([(hp, budget) for hp in configs])
                ))
                if rung == len(budgets) - 1:
                    break
                ok = [(hp, s) for hp, s in scored if s is not None]
                ok.sort(key=lambda t: t[1], reverse=(mode == "max"))
                keep = max(1, int(np.ceil(len(ok) / eta)))
                configs = [hp for hp, _ in ok[:keep]]
                if not configs:
                    break
    else:
        n = num_samples if alg.space or not grid_axes else 1
        if max_concurrent == 1:
            # sequential keeps the strict ask/tell interleave (TPE
            # conditions each ask on every previous result)
            for point in grid_points:
                for _ in range(n):
                    run_trial(dict(point, **alg.ask()))
        else:
            # concurrent slots: flatten grid points x samples into one
            # stream so pure-grid sweeps parallelize too, asking in
            # waves of max_concurrent (the usual async-search tradeoff:
            # a wave's asks don't see each other's results)
            stream = [point for point in grid_points for _ in range(n)]
            while stream:
                wave, stream = stream[:max_concurrent], stream[max_concurrent:]
                run_batch([(dict(p, **alg.ask()), None) for p in wave])

    scored = [r for r in results if r[metric] is not None]
    best = (max if mode == "max" else min)(
        scored, key=lambda r: r[metric], default=None
    ) if scored else None
    importance = param_importance(results, metric)
    report = {
        "script": script_path,
        "metric": metric,
        "mode": mode,
        "search_alg": tune_config.get("search_alg") or "random",
        "scheduler": tune_config.get("scheduler") or "fifo",
        "best": best,
        "param_importance": importance,
        "trials": results,
    }
    with open(os.path.join(output_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=2)
    with open(os.path.join(output_dir, "report.md"), "w") as f:
        f.write(f"# Sweep report: {os.path.basename(script_path)}\n\n")
        f.write(f"metric: `{metric}` ({mode}) | search: "
                f"{report['search_alg']} | scheduler: {report['scheduler']}\n\n")
        f.write("| trial | " + metric + " | budget | time (s) | hparams |\n|---|---|---|---|---|\n")
        for r in results:
            f.write(
                f"| {r['trial']} | {r[metric]} | {r['budget'] or ''} | {r['time']:.0f} | "
                f"`{json.dumps({k: v for k, v in r['hparams'].items() if not k.startswith('train.checkpoint') and not k.startswith('train.logging')})}` |\n"
            )
        if best is not None:
            f.write(f"\nbest: trial {best['trial']} with {metric}={best[metric]}\n")
        if importance:
            f.write("\n## Parameter importance (|Spearman| vs objective)\n\n")
            for k, v in sorted(importance.items(), key=lambda kv: -kv[1]):
                f.write(f"- `{k}`: {v:.3f}\n")
    logger.info("sweep report written to %s", output_dir)
    return report


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--run-trial":
        # concurrent-slot child: run ONE trial in this process (its env
        # carries the slot's device pinning); the parent reads the score
        # from the trial's metrics.jsonl
        script_path, full = sys.argv[2], json.loads(sys.argv[3])
        _load_main(script_path)(full)
        return
    parser = argparse.ArgumentParser()
    parser.add_argument("script", help="path to an example with main(hparams)")
    parser.add_argument("--config", required=True, help="sweep YAML")
    parser.add_argument("--output", default="sweeps_out", help="report/trials directory")
    args = parser.parse_args()

    with open(args.config) as f:
        config = yaml.safe_load(f)
    run_sweep(args.script, config, args.output)


if __name__ == "__main__":
    main()
