"""Hyperparameter sweeps: `python -m trlx_tpu.sweep --config sweeps/x.yml examples/script.py`.

Parity: /root/reference/trlx/sweep.py:17-348 — same YAML schema (per-param
`strategy` + `values`, `tune_config` with metric/mode/search_alg/
num_samples) and the same contract with examples (`main(hparams)` with
dotted-path overrides). The Ray Tune backend is replaced by a first-party
sequential runner: a TPU slice is one shared resource, so trials run one
after another on the full mesh instead of fighting over device shards;
random + grid search are built in (bayesopt degrades to random with a
warning — no skopt dependency in the TPU image).

Each trial's metrics come from the JSONL tracker (utils/trackers.py); a
markdown + JSON report replaces the reference's W&B report builder.
"""

from __future__ import annotations

import argparse
import importlib.util
import itertools
import json
import os
import sys
import time
from typing import Any, Dict, List

import numpy as np
import yaml

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


# ---------------------------------------------------------------------------
# param space sampling (reference get_param_space :17-100)
# ---------------------------------------------------------------------------


def _sample_strategy(rng: np.random.Generator, value: Dict[str, Any]):
    strategy, values = value["strategy"], value["values"]
    if strategy == "uniform":
        return float(rng.uniform(*values))
    if strategy == "quniform":
        lo, hi, q = values
        return float(np.round(rng.uniform(lo, hi) / q) * q)
    if strategy == "loguniform":
        lo, hi = values[:2]
        return float(np.exp(rng.uniform(np.log(lo), np.log(hi))))
    if strategy == "qloguniform":
        lo, hi, q = values[0], values[1], values[3] if len(values) > 3 else values[2]
        return float(np.round(np.exp(rng.uniform(np.log(lo), np.log(hi))) / q) * q)
    if strategy == "randn":
        mean, sd = values
        return float(rng.normal(mean, sd))
    if strategy == "qrandn":
        mean, sd, q = values
        return float(np.round(rng.normal(mean, sd) / q) * q)
    if strategy == "randint":
        lo, hi = values
        return int(rng.integers(lo, hi))
    if strategy == "qrandint":
        lo, hi, q = values
        return int(np.round(rng.integers(lo, hi) / q) * q)
    if strategy in ("lograndint", "qlograndint"):
        lo, hi = values[0], values[1]
        x = np.exp(rng.uniform(np.log(lo), np.log(hi)))
        q = values[3] if strategy == "qlograndint" else 1
        return int(np.round(x / q) * q)
    if strategy == "choice":
        return values[int(rng.integers(len(values)))]
    raise ValueError(f"unknown strategy {strategy!r}")


def generate_trials(param_space: Dict[str, Any], tune_config: Dict[str, Any], seed: int = 0) -> List[Dict[str, Any]]:
    """Expand grid axes × num_samples random draws into trial hparams."""
    rng = np.random.default_rng(seed)
    grid_axes = {
        k: v["values"] for k, v in param_space.items() if v["strategy"] == "grid"
    }
    sampled_axes = {k: v for k, v in param_space.items() if v["strategy"] != "grid"}

    grid_points: List[Dict[str, Any]] = [{}]
    if grid_axes:
        keys = list(grid_axes)
        grid_points = [
            dict(zip(keys, combo))
            for combo in itertools.product(*(grid_axes[k] for k in keys))
        ]

    num_samples = int(tune_config.get("num_samples", 1))
    trials = []
    for point in grid_points:
        for _ in range(num_samples if sampled_axes else 1):
            hparams = dict(point)
            for k, v in sampled_axes.items():
                hparams[k] = _sample_strategy(rng, v)
            trials.append(hparams)
    return trials


# ---------------------------------------------------------------------------
# trial execution
# ---------------------------------------------------------------------------


def _load_main(script_path: str):
    spec = importlib.util.spec_from_file_location("sweep_target", script_path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["sweep_target"] = module
    spec.loader.exec_module(module)
    return module.main


def run_sweep(script_path: str, config: Dict[str, Any], output_dir: str) -> Dict[str, Any]:
    tune_config = config.pop("tune_config")
    metric = tune_config.get("metric", "reward/mean")
    mode = tune_config.get("mode", "max")
    if tune_config.get("search_alg") not in (None, "random", "grid"):
        logger.warning(
            "search_alg %r not available in the TPU runner; using random search",
            tune_config.get("search_alg"),
        )
    trials = generate_trials(config, tune_config)
    logger.info("Running %d trials sequentially on the full mesh", len(trials))

    main = _load_main(script_path)
    os.makedirs(output_dir, exist_ok=True)
    results = []
    for i, hparams in enumerate(trials):
        trial_dir = os.path.join(output_dir, f"trial_{i:03d}")
        hparams = dict(
            hparams, **{
                "train.checkpoint_dir": trial_dir,
                "train.logging_dir": os.path.join(trial_dir, "logs"),
            }
        )
        logger.info("trial %d/%d: %s", i + 1, len(trials), hparams)
        t0 = time.time()
        status = "ok"
        try:
            main(hparams)
        except Exception as e:  # a failed trial shouldn't kill the sweep
            logger.warning("trial %d failed: %s", i, e)
            status = f"error: {e}"
        score = None
        metrics_fp = os.path.join(trial_dir, "logs", "metrics.jsonl")
        if os.path.exists(metrics_fp):
            values = [
                rec[metric]
                for rec in map(json.loads, open(metrics_fp))
                if metric in rec
            ]
            if values:
                score = max(values) if mode == "max" else min(values)
        results.append(
            {"trial": i, "hparams": hparams, metric: score,
             "status": status, "time": time.time() - t0}
        )

    scored = [r for r in results if r[metric] is not None]
    best = (max if mode == "max" else min)(
        scored, key=lambda r: r[metric], default=None
    ) if scored else None
    report = {
        "script": script_path,
        "metric": metric,
        "mode": mode,
        "best": best,
        "trials": results,
    }
    with open(os.path.join(output_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=2)
    with open(os.path.join(output_dir, "report.md"), "w") as f:
        f.write(f"# Sweep report: {os.path.basename(script_path)}\n\n")
        f.write(f"metric: `{metric}` ({mode})\n\n")
        f.write("| trial | " + metric + " | time (s) | hparams |\n|---|---|---|---|\n")
        for r in results:
            f.write(
                f"| {r['trial']} | {r[metric]} | {r['time']:.0f} | "
                f"`{json.dumps({k: v for k, v in r['hparams'].items() if not k.startswith('train.checkpoint')})}` |\n"
            )
        if best is not None:
            f.write(f"\nbest: trial {best['trial']} with {metric}={best[metric]}\n")
    logger.info("sweep report written to %s", output_dir)
    return report


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("script", help="path to an example with main(hparams)")
    parser.add_argument("--config", required=True, help="sweep YAML")
    parser.add_argument("--output", default="sweeps_out", help="report/trials directory")
    args = parser.parse_args()

    with open(args.config) as f:
        config = yaml.safe_load(f)
    run_sweep(args.script, config, args.output)


if __name__ == "__main__":
    main()
