"""Branch benchmarking: `python -m trlx_tpu.reference <ref> --against <ref2>`.

Parity: /root/reference/trlx/reference.py:1-103 + scripts/benchmark.sh —
the reference clones a fork:branch, runs its benchmark matrix and diffs
metrics in a W&B report. Here each git ref is checked out into a
temporary worktree, `bench.py` runs in each, and the JSON metrics are
diffed locally (no W&B dependency; works air-gapped).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


def run_ref(repo_root: str, ref: str, bench_cmd: str) -> dict:
    """Run `bench_cmd` for `ref` inside a temporary git worktree."""
    with tempfile.TemporaryDirectory(prefix=f"trlx_bench_{ref.replace('/', '_')}_") as tmp:
        subprocess.run(
            ["git", "worktree", "add", "--detach", tmp, ref],
            cwd=repo_root, check=True, capture_output=True,
        )
        try:
            out = subprocess.run(
                bench_cmd, shell=True, cwd=tmp, capture_output=True, text=True,
                timeout=3600,
            )
            for line in reversed(out.stdout.strip().splitlines()):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
            raise RuntimeError(
                f"no JSON metric line in bench output for {ref}:\n{out.stdout}\n{out.stderr}"
            )
        finally:
            subprocess.run(
                ["git", "worktree", "remove", "--force", tmp],
                cwd=repo_root, capture_output=True,
            )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("ref", help="git ref (branch/commit) to benchmark")
    parser.add_argument("--against", default="main", help="baseline git ref")
    parser.add_argument(
        "--bench-cmd", default=f"{sys.executable} bench.py",
        help="command printing one JSON metric line",
    )
    parser.add_argument("--output", default=None, help="optional report path")
    args = parser.parse_args()

    repo_root = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"], capture_output=True, text=True, check=True
    ).stdout.strip()

    logger.info("benchmarking %s against %s", args.ref, args.against)
    candidate = run_ref(repo_root, args.ref, args.bench_cmd)
    baseline = run_ref(repo_root, args.against, args.bench_cmd)

    speedup = (
        candidate["value"] / baseline["value"] if baseline.get("value") else None
    )
    report = {
        "ref": args.ref,
        "against": args.against,
        "candidate": candidate,
        "baseline": baseline,
        "ratio": round(speedup, 4) if speedup else None,
    }
    print(json.dumps(report, indent=2))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2)


if __name__ == "__main__":
    main()
