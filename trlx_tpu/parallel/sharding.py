"""Path-rule PartitionSpecs for the stacked-layer param tree.

Megatron TP parity (reference modeling_nemo_ppo.py:67-127 Column/Row
ParallelLinear, configs/nemo_configs/*.yaml `tensor_model_parallel_size`)
expressed as data layout, not module classes:

  q/k/v kernels  [L, E, H, D]  heads over `tp`, E over `fsdp`   (column-parallel)
  o kernel       [L, H, D, E]  heads over `tp`, E over `fsdp`   (row-parallel)
  mlp fc_in      [L, E, F]     F over `tp`                      (column-parallel)
  mlp fc_out     [L, F, E]     F over `tp`                      (row-parallel)
  embedding      [V, E]        vocab over `tp` (vocab-parallel embedding)
  lm_head        [E, V]        vocab over `tp` (vocab-parallel logits)

Everything also shards over `fsdp` on a non-tp dim: that is ZeRO-3
(DeepSpeed zero3.yaml parity) — XLA all-gathers params per layer inside
the scan and reduce-scatters grads, which is exactly the ZeRO-3 schedule.

Rules match on the param path; unknown params fall back to replicated.
A spec axis is silently dropped when the dim size is not divisible by the
mesh axis (e.g. tiny test models on an 8-way mesh).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec) — first match wins. Paths look like
# "base/blocks/attn/q/kernel", "base/embed/wte", "heads/q_heads/0/fc_in/kernel".
_RULES: List[Tuple[str, P]] = [
    (r"(^|/)embed/wte$", P("tp", "fsdp")),
    (r"(^|/)embed/wpe$", P(None, "fsdp")),
    # stacked blocks [L, ...]: the leading layer axis shards over `pp` —
    # each pipeline stage owns a contiguous slice (parallel/pipeline.py);
    # with pp=1 (the default) the entry is a no-op
    (r"(^|/)blocks/attn/[qkv]/kernel$", P("pp", "fsdp", "tp", None)),
    (r"(^|/)blocks/attn/[qkv]/bias$", P("pp", "tp", None)),
    (r"(^|/)blocks/attn/o/kernel$", P("pp", "tp", None, "fsdp")),
    (r"(^|/)blocks/attn/o/bias$", P("pp", None)),
    (r"(^|/)blocks/mlp/fc_(in|gate)/kernel$", P("pp", "fsdp", "tp")),
    (r"(^|/)blocks/mlp/fc_(in|gate)/bias$", P("pp", "tp")),
    (r"(^|/)blocks/mlp/fc_out/kernel$", P("pp", "tp", "fsdp")),
    (r"(^|/)blocks/mlp/fc_out/bias$", P("pp", None)),
    # any other per-layer param (layer norms): layer axis over pp only
    (r"(^|/)blocks/", P("pp")),
    (r"(^|/)lm_head/kernel$", P("fsdp", "tp")),
    # aux heads (value / Q): small — shard the wide input dim over fsdp only
    (r"(^|/)(v_head|q_heads(/\d+)?|target_q_heads(/\d+)?)/fc_in/kernel$", P("fsdp", None)),
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def spec_for_path(path_str: str) -> P:
    for pattern, spec in _RULES:
        if re.search(pattern, path_str):
            return spec
    return P()


def _fit_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Pad/trim a spec to the array rank and drop axes that don't divide
    the corresponding dim (tiny models on big meshes stay replicated)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    entries = entries[: len(shape)]
    fitted = []
    for dim, axis in zip(shape, entries):
        if axis is None:
            fitted.append(None)
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        size = int(np.prod([mesh.shape[a] for a in axes]))
        fitted.append(axis if dim % size == 0 else None)
    return P(*fitted)


def infer_param_pspecs(params: Dict, mesh: Optional[Mesh] = None) -> Dict:
    """PartitionSpec tree for a param tree (shape-fitted if mesh given)."""

    def leaf_spec(path, leaf):
        spec = spec_for_path(_path_str(path))
        if mesh is not None:
            spec = _fit_spec(spec, np.shape(leaf), mesh)
        return spec

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def param_shardings(mesh: Mesh, params: Dict) -> Dict:
    """NamedSharding tree for a param tree."""
    specs = infer_param_pspecs(params, mesh)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                  is_leaf=lambda x: isinstance(x, P))


def shard_params(mesh: Mesh, params: Dict) -> Dict:
    """device_put the tree with its inferred shardings (host numpy in,
    committed sharded device arrays out)."""
    shardings = param_shardings(mesh, params)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), params, shardings
    )


def unshard_axis(params: Dict, mesh: Mesh, axis: str = "pp") -> Dict:
    """Re-lay out a param tree with `axis` dropped from every spec
    (all-gathering each leaf's shards over that mesh axis).

    Decode under pipeline parallelism is the use case: the sequential
    KV-cache scan reads every layer's weights each step, and with the
    stacked layer axis sharded over `pp` each step would gather the
    remote stages' slices — across DCN on a dcn_pp2-style mesh. Calling
    this once on the decode param copy (inside the sampler jit, before
    the while_loop) turns per-step cross-stage traffic into ONE gather
    per generate call; the loop then reads stage-local weights. Costs
    pp× block-param memory per device for the duration of the call —
    the decode copy is already materialized by `cast_params_for_decode`,
    so this re-shards that copy rather than duplicating params again.

    Implemented as `jax.lax.with_sharding_constraint` on every leaf:
    under jit this is a layout constraint the partitioner satisfies with
    an all-gather; called eagerly it relies on
    with_sharding_constraint's eager semantics (an immediate reshard).
    """

    def strip(spec_axis):
        if isinstance(spec_axis, tuple):
            rest = tuple(a for a in spec_axis if a != axis)
            return rest if len(rest) > 1 else (rest[0] if rest else None)
        return None if spec_axis == axis else spec_axis

    def constrain(path, x):
        spec = _fit_spec(spec_for_path(_path_str(path)), np.shape(x), mesh)
        stripped = P(*[strip(a) for a in spec])
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, stripped))

    return jax.tree_util.tree_map_with_path(constrain, params)


def unshard_for_decode(params: Dict, mesh: Optional[Mesh], axis: str = "pp") -> Dict:
    """The sampler-side gate for `unshard_axis`: no-op unless the mesh
    carries a real pp axis. Both samplers (models/generation.py and
    models/seq2seq.py:generate_seq2seq) share this so the decode-unshard
    condition can't drift between them."""
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return params
    return unshard_axis(params, mesh, axis)


def init_sharded_opt_state(mesh: Mesh, tx, params: Dict):
    """Initialize optimizer state with mu/nu sharded like their params.

    This is the distributed-optimizer half of ZeRO-3 parity (reference
    megatron_20b.yaml `distributed_fused_adam`): optimizer moments follow
    the same path rules as the params they track (opt-state tree paths end
    with the param path, so the same regexes match). Without explicit
    out_shardings, `jax.jit(tx.init)` commits the whole state to one
    device — fully replicated optimizer memory and a retrace of the train
    step when GSPMD later re-lays it out.
    """
    abstract = jax.eval_shape(tx.init, params)

    def leaf_sharding(path, leaf):
        spec = _fit_spec(spec_for_path(_path_str(path)), leaf.shape, mesh)
        return NamedSharding(mesh, spec)

    shardings = jax.tree_util.tree_map_with_path(leaf_sharding, abstract)
    return jax.jit(tx.init, out_shardings=shardings)(params)
