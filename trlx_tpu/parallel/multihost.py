"""Multi-host (multi-process) SPMD execution.

The reference scales out with torch.distributed/NCCL choreography:
rank-0 scatters rollout chunks, gathers decoded strings and scores, and
re-broadcasts tensors (accelerate_ppo_trainer.py:292-341,
nemo_ppo_trainer.py:344-362). The TPU-native shape of the same thing is
data-parallel SPMD over a global mesh: every process runs the SAME
program; jitted computation sees GLOBAL arrays (GSPMD inserts the
collectives); only host-side work (tokenize, decode, reward fns) is
per-process, operating on the rows whose device shards live on this
host.

The helpers here are the complete host<->global bridge:

  initialize()            wire up jax.distributed (no-op single-host)
  data_group_info(mesh)   (group, count): processes sharing the same
                          (dp, fsdp) row blocks — e.g. pp stages — form
                          one group and hold identical host rows
  shard_list(xs, mesh)    this data group's strided slice of a host list
  global_from_local(t, s) per-process local rows -> one global array
  local_rows(arr)         this process's rows of a global batch array
  allgather(x)            host-side values -> full np array everywhere
  consensus(values)       all-gather a dict of host scalars and verify
                          every process agrees (the cross-host
                          consistency-watchdog primitive)
  is_main()               gate for tracker/checkpoint-metadata writes

Mesh layout note: jax.devices() orders devices process-major, and
make_mesh lays axes (pp, dp, fsdp, tp, sp) major-to-minor, so batch rows
land on data groups in contiguous blocks — `local_rows` of a
(dp, fsdp)-sharded batch is exactly the group's row block, matching what
`global_from_local` assembled. tp/sp shards of the same rows stay
host-local, riding ICI not DCN; with pp spanning processes, stages hold
replica shards of their group's rows.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Wire up jax.distributed. On TPU pods with the standard launcher
    env (TPU_WORKER_HOSTNAMES etc.) all arguments auto-detect; pass them
    explicitly for manual/CPU-simulated launches. No-op when already
    initialized or when running single-process."""
    if num_processes is not None and num_processes <= 1:
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:  # already initialized
        if "already" not in str(e).lower():
            raise


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_multihost() -> bool:
    return jax.process_count() > 1


def is_main() -> bool:
    return jax.process_index() == 0


def data_group_info(mesh=None):
    """(group_index, group_count) for batch-row distribution.

    Processes whose devices cover the same (dp, fsdp) row blocks form one
    DATA GROUP and must hold identical host rows (their device shards are
    replicas — e.g. different `pp` stages of the same rows). Without a
    mesh (or when every process covers distinct blocks, the pp=1 layout)
    this degenerates to (process_index, process_count) — the historical
    behavior. Row distribution must key on groups, not processes: keying
    on processes under pp>1 would feed different data to different
    pipeline stages of the same rows.
    """
    info, _reps = _group_data(mesh)
    return info


def _group_data(mesh):
    """((group_index, group_count), representatives) — computed together
    so the reps can never be a stale/guessed fallback of the info."""
    if not is_multihost():
        return (0, 1), [0]
    if mesh is None:
        return (jax.process_index(), jax.process_count()), list(
            range(jax.process_count())
        )
    try:
        key = mesh  # jax Mesh is hashable; keeps a live ref (no id reuse)
        if key in _GROUP_DATA_CACHE:
            return _GROUP_DATA_CACHE[key]
    except TypeError:
        key = None
    axis = dict(zip(mesh.axis_names, range(len(mesh.axis_names))))
    fsdp_size = mesh.devices.shape[axis["fsdp"]]
    blocks_by_proc: dict = {}
    for idx in np.ndindex(*mesh.devices.shape):
        d = mesh.devices[idx]
        block = idx[axis["dp"]] * fsdp_size + idx[axis["fsdp"]]
        blocks_by_proc.setdefault(d.process_index, set()).add(block)
    mine = blocks_by_proc.get(jax.process_index())
    if mine is None:
        # this process owns no mesh devices (shouldn't happen in SPMD)
        return (jax.process_index(), jax.process_count()), list(
            range(jax.process_count())
        )
    groups = sorted(
        {tuple(sorted(v)) for v in blocks_by_proc.values()},
        key=lambda t: t[0],
    )
    # groups must partition the block space: any overlap between
    # non-identical block sets means a (dp, fsdp) shard would receive
    # conflicting rows from two groups
    total = sum(len(g) for g in groups)
    union = set().union(*(set(g) for g in groups))
    if total != len(union):
        raise ValueError(
            "mesh device layout maps processes to OVERLAPPING but "
            f"non-identical (dp, fsdp) row blocks ({groups}); batch "
            "rows cannot be distributed consistently — keep each "
            "process's devices within whole data shards"
        )
    info = (groups.index(tuple(sorted(mine))), len(groups))
    # one representative process per group (the lowest), for deduping
    # per-process host gathers when groups replicate rows
    reps = [
        min(p for p, v in blocks_by_proc.items() if tuple(sorted(v)) == g)
        for g in groups
    ]
    if key is not None:
        _GROUP_DATA_CACHE[key] = (info, reps)
    return info, reps


_GROUP_DATA_CACHE: dict = {}


def group_representatives(mesh=None) -> list:
    """Process indices (one per data group) whose per-process gather
    contributions to keep; with pp>1 the other stages' entries are
    replicas of the same rows."""
    _info, reps = _group_data(mesh)
    return reps


def data_group_count(mesh=None) -> int:
    return data_group_info(mesh)[1]


def shard_list(items: Sequence[Any], mesh=None) -> list:
    """This data group's strided slice of a host-side list (prompts, eval
    rows). Strided (not blocked) so truncated datasets stay balanced;
    padded by wrap-around so every group holds the same count (SPMD
    programs must run in lockstep — a short process would deadlock the
    collectives). Processes in the same group (pp stages) get identical
    slices."""
    p, n = data_group_info(mesh)
    if n == 1:
        return list(items)
    local = list(items[p::n])
    want = (len(items) + n - 1) // n
    i = 0
    while len(local) < want:
        local.append(items[(p + i * n) % len(items)])
        i += 1
    return local


def shard_pipeline(pipeline, mesh=None):
    """Per-data-group view of an indexable pipeline: this group's strided
    slice of the rows, same collate/loader behavior. No-op single-host."""
    if not is_multihost():
        return pipeline
    import copy

    clone = copy.copy(pipeline)
    if hasattr(pipeline, "prompts"):
        clone.prompts = shard_list(pipeline.prompts, mesh)
        return clone
    idxs = shard_list(list(range(len(pipeline))), mesh)

    class _View(type(pipeline)):
        def __init__(self):  # bypass the parent tokenizing __init__
            self.__dict__.update(clone.__dict__)
            self._idxs = idxs

        def __len__(self):
            return len(self._idxs)

        def __getitem__(self, i):
            return pipeline[self._idxs[i]]

    return _View()


def global_from_local(tree, sharding):
    """Per-process local row blocks -> one global array per leaf.

    `sharding` is the target NamedSharding for the GLOBAL batch (e.g.
    data_sharding(mesh)); each process contributes len(global)/P rows."""
    if not is_multihost():
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(np.asarray(x), sharding), tree
        )
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        tree,
    )


def local_rows(arr) -> np.ndarray:
    """This process's contiguous row block of a global [B, ...] batch
    array (the rows whose data lives on this host's devices)."""
    if not isinstance(arr, jax.Array):
        return np.asarray(arr)
    if arr.is_fully_replicated or not is_multihost():
        return np.asarray(arr)
    shards = {}
    for s in arr.addressable_shards:
        start = s.index[0].start or 0
        if start not in shards:
            shards[start] = np.asarray(s.data)
    rows = [shards[k] for k in sorted(shards)]
    out = np.concatenate(rows, axis=0)
    # replicated-over-(tp, sp) shards can still cover full columns; when
    # the batch dim is the only sharded one this is simply the row block
    return out


def allgather(x) -> np.ndarray:
    """Host-side numeric values -> the full global np array, on every
    process. For global jax Arrays this is an all-gather to replicated;
    for host arrays it concatenates per-process contributions in process
    order."""
    if not is_multihost():
        return np.asarray(x)
    from jax.experimental import multihost_utils

    if isinstance(x, jax.Array):
        if x.is_fully_replicated:
            return np.asarray(x)
        from jax.sharding import NamedSharding, PartitionSpec

        return np.asarray(
            jax.jit(
                lambda a: a,
                out_shardings=NamedSharding(x.sharding.mesh, PartitionSpec()),
            )(x)
        )
    return np.asarray(multihost_utils.process_allgather(np.asarray(x)))


def allgather_group_rows(x, mesh=None) -> np.ndarray:
    """Host-side per-DATA-GROUP row blocks -> the full global rows (in
    group order), on every process. Unlike `allgather`, which
    concatenates per-PROCESS contributions, this keeps one block per
    group: with pp>1 the same group's stages hold identical rows and a
    per-process concat would duplicate them. Every group must
    contribute the same row count (shard_list guarantees that for
    prompt/eval distribution)."""
    if not is_multihost():
        return np.asarray(x)
    from jax.experimental import multihost_utils

    blocks = np.asarray(multihost_utils.process_allgather(np.asarray(x)))
    reps = group_representatives(mesh)
    return np.concatenate([blocks[r] for r in reps], axis=0)


class ConsensusResult:
    """Outcome of a cross-host fingerprint comparison: ``agree`` is the
    fleet-wide verdict, ``reference`` the agreed values (process 0's
    row), ``detail`` a human-readable mismatch description ('' when all
    rows agree)."""

    __slots__ = ("agree", "reference", "detail")

    def __init__(self, agree: bool, reference: dict, detail: str = ""):
        self.agree = agree
        self.reference = reference
        self.detail = detail


def values_agree(a, b, atol: float = 0.0) -> bool:
    """THE consistency-watchdog equality predicate (one place, used by
    both the cross-host row compare and the trainer's local-vs-
    reference drift check): bit-identical values agree — including
    identical NaN, which is a fleet-wide health problem the loss
    guards own, not a divergence — otherwise both must be finite and
    within ``atol``."""
    a, b = float(a), float(b)
    if a == b or (np.isnan(a) and np.isnan(b)):
        return True
    return bool(np.isfinite(a) and np.isfinite(b) and abs(a - b) <= atol)


def _consensus_rows(rows, keys, atol: float):
    """Pure comparison core (unit-testable without multiple processes):
    rows[p][i] is process p's value for keys[i]; rows agree when every
    row is within ``atol`` of row 0 elementwise. Returns (agree, detail
    listing the first few divergent (process, key, value, reference))."""
    rows = np.asarray(rows, np.float64)
    ref = rows[0]
    mismatches = []
    for p in range(1, rows.shape[0]):
        for i, k in enumerate(keys):
            a, b = rows[p, i], ref[i]
            if not values_agree(a, b, atol):
                mismatches.append(f"process {p}: {k}={a!r} != {b!r}")
    detail = "; ".join(mismatches[:8]) + (
        f" (+{len(mismatches) - 8} more)" if len(mismatches) > 8 else ""
    )
    return not mismatches, detail


def consensus(values, atol: float = 0.0) -> ConsensusResult:
    """All-gather a dict of host-side scalars and check every process
    holds the same values (within ``atol``) — the cross-host consistency
    watchdog primitive. Keys must be identical on every process (SPMD:
    they derive from the same control flow). Single-host degenerates to
    trivial agreement with ``reference == values``.

    Values ride the gather as float32: callers must fold hashes into
    the exactly-representable range (e.g. ``% 2**20``)."""
    keys = sorted(values)
    vec = np.asarray([float(values[k]) for k in keys], np.float32)
    if not is_multihost():
        return ConsensusResult(True, {k: float(values[k]) for k in keys})
    from jax.experimental import multihost_utils

    rows = np.asarray(multihost_utils.process_allgather(vec))
    agree, detail = _consensus_rows(rows, keys, atol)
    reference = {k: float(rows[0, i]) for i, k in enumerate(keys)}
    return ConsensusResult(agree, reference, detail)


def cursor_consensus(
    name: str, epoch: int, cursor: int
) -> ConsensusResult:
    """Agreement check for a (epoch, cursor) position of a shared
    stream — the experience transport's consumer cursor foremost: every
    host must have committed exactly the same chunks, or the fleet is
    silently training different data. Runs on the :func:`consensus`
    gather (exact compare; positions are integers in lockstep control
    flow, so any tolerance would paper over a real divergence). The
    trainer calls this at the guardrails consistency cadence and routes
    disagreement onto the escalation ladder."""
    return consensus(
        {f"{name}_epoch": float(epoch), f"{name}_cursor": float(cursor)},
        atol=0.0,
    )


def any_flag(value: bool) -> bool:
    """True on every process iff ANY process passed True. The preemption
    path needs this rather than `broadcast_flag`: a SIGTERM lands on
    whichever host the scheduler is reclaiming — not necessarily process
    0 — and every host must agree to stop and join the final collective
    checkpoint save, or the survivors deadlock in it."""
    if not is_multihost():
        return bool(value)
    from jax.experimental import multihost_utils

    flags = np.asarray(
        multihost_utils.process_allgather(
            np.asarray([1 if value else 0], np.int32)
        )
    )
    return bool(flags.any())


def broadcast_flag(value: bool) -> bool:
    """Process 0's bool, agreed on every process (keeps data-dependent
    control flow deterministic across hosts)."""
    if not is_multihost():
        return bool(value)
    from jax.experimental import multihost_utils

    return bool(
        multihost_utils.broadcast_one_to_all(np.int32(1 if value else 0))
    )


def barrier(name: str) -> None:
    """Host-level sync point (coordination service, not a device
    collective). Placed around host-divergent sections (checkpoint file
    IO, exports) so one process can't race ahead and enqueue device
    collectives that interleave with the laggard's — XLA dispatch is
    async, so python-thread position and in-flight collectives are
    otherwise unordered across hosts."""
    if not is_multihost():
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


class BarrierTimeout(RuntimeError):
    """A :func:`timed_barrier` blew its deadline: a peer host never
    arrived. The barrier name identifies WHERE the fleet stalled; each
    host's own hang-doctor log (phase timeline + stacks) says what that
    host was doing instead."""


def timed_barrier(
    name: str,
    timeout_s: float,
    barrier_fn: Optional[Any] = None,
) -> None:
    """:func:`barrier` with a deadline (the hang-doctor barrier): the
    sync runs in a worker thread and :class:`BarrierTimeout` is raised
    if it does not complete within ``timeout_s`` — a healthy host
    waiting on a dead peer becomes a diagnosable error instead of an
    indefinite hang. The abandoned worker stays parked in the
    collective, so callers MUST treat the timeout as a stall and exit
    (trainer ``_stalled_exit``) rather than keep enqueueing device
    collectives that would interleave with it. ``timeout_s <= 0``
    degrades to the plain barrier. ``barrier_fn`` is injectable for
    tests; without it, single-host is a no-op like :func:`barrier`."""
    if barrier_fn is None:
        if not is_multihost():
            return
        barrier_fn = lambda: barrier(name)  # noqa: E731
    if timeout_s is None or timeout_s <= 0:
        barrier_fn()
        return
    from trlx_tpu.utils.resilient import DeadlineExceeded, call_with_deadline

    try:
        call_with_deadline(barrier_fn, timeout_s)
    except DeadlineExceeded:
        raise BarrierTimeout(
            f"barrier {name!r} did not complete within {timeout_s:.3g}s "
            "— a peer host is stalled (check each host's hang-doctor "
            "stall report for the wedged phase)"
        ) from None


# a host is a straggler on a phase when its cumulative wall time there
# exceeds BOTH factor * the fleet median AND median + slack — the slack
# floor keeps sub-second phases from tripping on scheduler jitter
STRAGGLER_FACTOR = 2.0
STRAGGLER_SLACK_S = 10.0


def _straggler_rows(rows, keys):
    """Pure straggler-attribution core (unit-testable without multiple
    processes): ``rows[p][i]`` is process p's value for ``keys[i]``.

    The detection signal is ``time/<phase>`` — cumulative wall seconds
    each host spent in the phase. The gather itself runs at a lockstep
    control-flow point, so every host arrives having executed the SAME
    iterations (``beats/<phase>`` counts are equal by construction —
    the slow host simply delays the gather); what differs is how LONG
    that identical work took, and the host whose wall total exceeds
    both ``STRAGGLER_FACTOR`` x the fleet median and median +
    ``STRAGGLER_SLACK_S`` is the one the fleet is waiting on. A beat-
    count mismatch — impossible in lockstep — additionally flags a host
    whose control flow diverged outright. Returns (straggler process
    indices, detail naming which host/phase and by how much)."""
    rows = np.asarray(rows, np.float64)
    keys = [str(k) for k in keys]
    stragglers = set()
    details = []
    for i, key in enumerate(keys):
        if key.startswith("time/"):
            phase = key[len("time/"):]
            col = rows[:, i]
            med = float(np.median(col))
            bound = max(STRAGGLER_FACTOR * med, med + STRAGGLER_SLACK_S)
            for p in np.flatnonzero(col > bound):
                stragglers.add(int(p))
                details.append(
                    f"host {int(p)} spent {col[p]:.1f}s in phase "
                    f"{phase!r} vs fleet median {med:.1f}s"
                )
        elif key.startswith("beats/"):
            phase = key[len("beats/"):]
            col = rows[:, i]
            top = col.max()
            for p in np.flatnonzero(col < top):
                stragglers.add(int(p))
                details.append(
                    f"host {int(p)} diverged on phase {phase!r} "
                    f"(beats {int(col[p])} vs fleet max {int(top)})"
                )
    return sorted(stragglers), "; ".join(details[:8]) + (
        f" (+{len(details) - 8} more)" if len(details) > 8 else ""
    )


def straggler_report(values: dict) -> Any:
    """All-gather each host's heartbeat counters
    (``HangWatchdog.phase_ages``) and name which host/phase is behind —
    the cross-host half of the hang doctor, built on the same gather
    path as :func:`consensus`. Run it at a lockstep point while
    collectives still work (a fully wedged fleet can't gather; there
    the per-host deadline abort takes over). Returns a
    :class:`ConsensusResult`: ``agree`` False when a straggler exists,
    ``detail`` naming it."""
    keys = sorted(values)
    vec = np.asarray([float(values[k]) for k in keys], np.float32)
    if not is_multihost():
        return ConsensusResult(True, {k: float(values[k]) for k in keys})
    from jax.experimental import multihost_utils

    rows = np.asarray(multihost_utils.process_allgather(vec))
    stragglers, detail = _straggler_rows(rows, keys)
    reference = {k: float(rows[0, i]) for i, k in enumerate(keys)}
    return ConsensusResult(not stragglers, reference, detail)


def allgather_object(obj) -> list:
    """Gather one JSON-serializable host object per process; every
    process receives the list in process order (the reference's
    torch.distributed all_gather_object, e.g. RFT generations —
    accelerate_rft_trainer.py:127-144)."""
    if not is_multihost():
        return [obj]
    import json

    from jax.experimental import multihost_utils

    data = np.frombuffer(json.dumps(obj).encode("utf-8"), np.uint8)
    lengths = np.asarray(
        multihost_utils.process_allgather(np.asarray([len(data)], np.int32))
    ).reshape(-1)
    padded = np.zeros(int(lengths.max()), np.uint8)
    padded[: len(data)] = data
    rows = np.asarray(multihost_utils.process_allgather(padded))
    return [
        json.loads(bytes(row[:n]).decode("utf-8"))
        for row, n in zip(rows, lengths)
    ]


def gather_params(tree):
    """Materialize a (possibly fsdp/tp-sharded) param tree as host numpy
    on EVERY process (collective: all processes must call). Used by the
    HF-export path, which needs full tensors to write."""
    if not is_multihost():
        return jax.device_get(tree)
    from jax.sharding import NamedSharding, PartitionSpec

    meshes = {
        x.sharding.mesh
        for x in jax.tree_util.tree_leaves(tree)
        if isinstance(x, jax.Array) and not x.is_fully_addressable
    }
    if not meshes:
        return jax.device_get(tree)
    mesh = meshes.pop()
    # ONE jitted identity program replicating every leaf: the collectives
    # ride a single deterministic XLA executable on all processes (a
    # per-leaf host gather would issue N independent collectives, which
    # is slower and fragile against interleaving with other collectives)
    rep = jax.jit(
        lambda t: t, out_shardings=NamedSharding(mesh, PartitionSpec())
    )(tree)
    return jax.device_get(rep)
