"""Parallelism: one device mesh, sharding as config.

Replaces the reference's three parallelism facades (Accelerate
DDP/DeepSpeed ZeRO, raw torch.distributed, Apex `parallel_state` —
SURVEY.md §2.7/2.8) with a single `jax.sharding.Mesh` carrying named axes:

  dp    replicated data parallel            (DDP parity)
  fsdp  param/opt-state sharded data parallel (ZeRO-3 parity)
  tp    tensor parallel                     (Megatron TP parity)
  sp    sequence/context parallel           (long-context upgrade path)

XLA emits the collectives (psum / all-gather / reduce-scatter) over
ICI/DCN from sharding annotations; there is no NCCL-style call-site code
to port.
"""

from trlx_tpu.parallel.mesh import (  # noqa: F401
    MeshAxes,
    batch_pspec,
    data_sharding,
    local_batch_size,
    make_mesh,
    replicated_sharding,
)
from trlx_tpu.parallel.sharding import (  # noqa: F401
    infer_param_pspecs,
    init_sharded_opt_state,
    param_shardings,
    shard_params,
)
