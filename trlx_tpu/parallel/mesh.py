"""Mesh construction from the `TrainConfig.mesh` axis-size dict.

The reference picks parallelism by choosing a backend + YAML
(configs/accelerate/zero*.yaml vs configs/nemo_configs/megatron_*.yaml);
here `{"dp": -1, "fsdp": 8, "tp": 4, "sp": 1}` is the whole story: one
axis may be -1 to absorb the remaining devices.

Device order: axes are laid out (pp, dp, fsdp, tp, sp) major-to-minor so
tp (the chattiest axis: per-matmul all-reduces) maps to physically
adjacent devices on the ICI torus, while pp (one neighbor ppermute per
microbatch, latency hidden by the pipeline schedule) takes the outermost
— possibly DCN-crossing — dimension. Same reasoning as Megatron's
tensor-parallel-innermost / pipeline-outermost group layout.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = ("pp", "dp", "fsdp", "tp", "sp")


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all) from an axis-size dict.

    Any single axis set to -1 absorbs the remaining device count; absent
    axes default to 1 (dp defaults to -1).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = {"pp": 1, "dp": -1, "fsdp": 1, "tp": 1, "sp": 1}
    sizes.update(axis_sizes or {})
    unknown = set(sizes) - set(MeshAxes)
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {MeshAxes}")

    if sizes.get("pp", 1) > 1 and sizes.get("sp", 1) > 1:
        # enforced here (the one chokepoint every config path goes
        # through) because downstream gating can't see both worlds: sp>1
        # flips attention to ring, which would silently bypass the
        # pipelined path while params stay pp-sharded — duplicated
        # compute, no error
        raise ValueError(
            f"pp and sp are mutually exclusive: ring attention shards the "
            f"sequence inside each layer, pipelining shards the layers ({sizes})"
        )

    fill = [ax for ax, s in sizes.items() if s == -1]
    if len(fill) > 1:
        raise ValueError(f"only one mesh axis may be -1, got {fill}")
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    if fill:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes product {fixed}")
        sizes[fill[0]] = n // fixed
    elif fixed > n:
        raise ValueError(f"mesh {sizes} needs {fixed} devices, have {n}")

    shape = tuple(sizes[ax] for ax in MeshAxes)
    # a mesh smaller than the host's device count is allowed (tests pin
    # dp=1 on an 8-device CPU host); the first prod(shape) devices serve
    used = int(np.prod(shape))
    if used < n:
        import warnings

        warnings.warn(
            f"mesh {sizes} uses {used} of {n} available devices; "
            "set one axis to -1 to absorb the rest",
            stacklevel=2,
        )
    dev_array = np.asarray(devices[:used]).reshape(shape)
    return Mesh(dev_array, MeshAxes)


def batch_pspec(shard_seq: bool = False) -> P:
    """PartitionSpec for a [batch, seq, ...] array: batch over (dp, fsdp)
    — fsdp devices are data-parallel for activations, ZeRO-style — and
    optionally seq over sp."""
    return P(("dp", "fsdp"), "sp" if shard_seq else None)


def data_sharding(mesh: Mesh, shard_seq: bool = False) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec(shard_seq))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def vector_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a rank-1 [batch] array (per-sample scores/sums)."""
    return NamedSharding(mesh, P(("dp", "fsdp")))


def local_batch_size(mesh: Mesh, global_batch: int) -> int:
    """Per-data-shard batch (dp*fsdp ways)."""
    ways = mesh.shape["dp"] * mesh.shape["fsdp"]
    if global_batch % ways:
        raise ValueError(f"batch {global_batch} not divisible by dp*fsdp={ways}")
    return global_batch // ways
