"""GPipe-style pipeline parallelism over a `pp` mesh axis.

The reference's model-parallel backend pipelines NeMo/Megatron stages
across nodes (ref: configs/nemo_configs/megatron_20b.yaml
`pipeline_model_parallel_size`, trainer/nemo_ppo_trainer.py) with
point-to-point sends choreographed by Megatron's schedules. The TPU
analogue here exploits the repo's scan-stacked layer layout: layer
params already live in one array with a leading `n_layer` axis, so a
pipeline stage is just a shard of that axis.

Mechanics (microbatch pipelining, the classic GPipe schedule):
- `jax.shard_map` manual over ONLY the `pp` axis (`axis_names={"pp"}`)
  — dp/fsdp/tp stay under GSPMD, so FSDP gathers and tensor-parallel
  all-reduces compose with pipelining without manual collectives.
- Each stage holds `n_layer/pp` consecutive layers (its slice of the
  stacked params). The batch is split into M microbatches; a scan runs
  M + pp - 1 ticks. Per tick every stage applies its layers to one
  microbatch and `ppermute`s the activation to the next stage — a
  neighbor-to-neighbor ICI hop, the cheapest collective on the torus.
- Stage 0 feeds fresh microbatches; the last stage accumulates outputs,
  broadcast back with a masked `psum` (zeros elsewhere) so downstream
  ops (final norm, logits) run under plain GSPMD again.
- Hydra/value-branch captures (hidden entering layer g) accumulate on
  whichever stage owns layer g via a one-hot mask inside the stage scan
  and merge in the same masked-psum step.

The bubble fraction is (pp-1)/(M+pp-1): raise `pp_microbatches` to
amortize. Backward works through the `lax.scan`-of-`ppermute` transpose
(reverse-direction permutes), which is exactly the 1F1B-ish reversed
schedule; `remat=True` checkpoints each layer body so only per-tick
stage inputs are stored, as in the sequential path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array


def pp_microbatch_count(
    mesh,
    n_layer: int,
    batch: int,
    pp_microbatches: int = 0,
    stacklevel: int = 4,
) -> int:
    """Shared trace-time pp gate: the microbatch count to pipeline a
    stack with, or 0 for the sequential scan. One definition so the
    causal and seq2seq models cannot drift on eligibility rules, and so
    the divisibility check guards the exact value `pipelined_layers`
    receives."""
    if mesh is None:
        return 0
    m = dict(mesh.shape)
    pp = m.get("pp", 1)
    if pp <= 1:
        return 0
    if m.get("sp", 1) > 1:
        raise ValueError(
            "pp and sp are mutually exclusive: ring attention shards the "
            f"sequence inside each layer, pipelining shards the layers (mesh {m})"
        )
    n_mb = pp_microbatches or pp
    if n_layer % pp or batch % n_mb:
        import warnings

        warnings.warn(
            f"pipeline parallelism requested (pp={pp}) but n_layer={n_layer} "
            f"or batch={batch} don't divide (microbatches={n_mb}); falling "
            "back to the sequential scan",
            stacklevel=stacklevel,
        )
        return 0
    return n_mb


def _microbatch_flags(tree, batch: int):
    """Static per-leaf decision: leaves with leading dim == batch get
    split per microbatch; broadcast-shaped aux (e.g. [1, 1, T, S] biases)
    is passed whole to every layer call."""
    return jax.tree_util.tree_map(
        lambda x: jnp.ndim(x) > 0 and x.shape[0] == batch, tree
    )


def _split_microbatches(tree, flags, n_mb: int):
    return jax.tree_util.tree_map(
        lambda x, f: x.reshape((n_mb, x.shape[0] // n_mb) + x.shape[1:]) if f else x,
        tree,
        flags,
    )


def _index_microbatch(tree, flags, m: Array):
    return jax.tree_util.tree_map(
        lambda x, f: x[m] if f else x, tree, flags
    )


def pipelined_layers(
    mesh: Mesh,
    layer_apply: Callable[[Dict, Array, Any], Array],
    xs: Dict,
    h: Array,
    ctx: Any,
    *,
    n_microbatch: int,
    capture_points: Sequence[int] = (),
    remat: bool = False,
) -> Tuple[Array, Tuple[Array, ...]]:
    """Run L stacked layers over the mesh's `pp` axis, pipelined.

    Args:
      layer_apply: (layer_xs_slice, h, ctx_microbatch) -> h for ONE layer.
      xs: pytree whose every leaf has leading axis L (stacked layer
        params + any per-layer scalars). L must divide by mesh pp size.
      h: [B, ...] activations entering layer 0. B must divide by
        n_microbatch (and B/n_microbatch by dp*fsdp for good layouts).
      ctx: pytree of batch-shaped aux inputs (attention bias, positions,
        key masks). Leaves with leading dim B are split per microbatch;
        other leaves are passed whole to every layer call.
      capture_points: global layer indices g; returns the hidden state
        ENTERING layer g for each (the hydra/value-branch fork inputs).

    Returns (h_out [B, ...], captures tuple aligned with capture_points).
    """
    n_stages = mesh.shape["pp"]
    leaves = jax.tree_util.tree_leaves(xs)
    n_layer = leaves[0].shape[0]
    if n_layer % n_stages:
        raise ValueError(
            f"n_layer={n_layer} not divisible by pp={n_stages}"
        )
    B = h.shape[0]
    M = n_microbatch
    if B % M:
        raise ValueError(f"batch {B} not divisible by pp microbatches {M}")
    points = tuple(capture_points)
    n_pts = len(points)
    # XLA's CPU backend crashes (AllReducePromotion CHECK) on bf16
    # all-reduces, which both the masked-psum broadcast and the shard_map
    # transpose of replicated inputs emit. Carry boundary activations in
    # f32 on CPU meshes: bf16<->f32 round-trips are bit-exact, so the
    # numerics match the sequential scan; TPU keeps bf16 on the wire.
    compute_dtype = h.dtype
    on_cpu = mesh.devices.flat[0].platform == "cpu"
    io_dtype = (
        jnp.float32 if (on_cpu and compute_dtype == jnp.bfloat16) else compute_dtype
    )

    xs = dict(xs, __g__=jnp.arange(n_layer))  # global layer index per slice row

    def stage(xs_local, h, ctx_mb):
        """Apply this stage's layer slice; accumulate capture hiddens."""

        def body(carry, layer):
            h, caps = carry
            if n_pts:
                g = layer["__g__"]
                onehot = jnp.stack(
                    [(g == p).astype(caps.dtype) for p in points]
                ).reshape((n_pts,) + (1,) * h.ndim)
                caps = caps + onehot * h[None].astype(caps.dtype)
            h = layer_apply(
                {k: v for k, v in layer.items() if k != "__g__"}, h, ctx_mb
            )
            return (h, caps), None

        from trlx_tpu.ops.remat import wrap_remat

        body = wrap_remat(body, remat)
        caps0 = jnp.zeros((n_pts,) + h.shape, io_dtype)
        (h, caps), _ = jax.lax.scan(body, (h.astype(compute_dtype), caps0), xs_local)
        return h.astype(io_dtype), caps

    def pipelined(xs_local, h_mb, ctx_mb):
        s = jax.lax.axis_index("pp")
        last = n_stages - 1
        buf = jnp.zeros_like(h_mb[0])
        outs = jnp.zeros_like(h_mb)
        caps_store = jnp.zeros((M, n_pts) + h_mb.shape[1:], h_mb.dtype)

        def tick(carry, t):
            buf, outs, caps_store = carry
            # stage s works on microbatch t - s this tick (GPipe schedule)
            m = t - s
            m_c = jnp.clip(m, 0, M - 1)
            valid = (m >= 0) & (m < M)
            ctx_t = _index_microbatch(ctx_mb, ctx_flags, m_c)
            # restore boundary-promoted ctx leaves to their compute dtype
            # (bf16<->f32 round-trips are bit-exact)
            ctx_t = jax.tree_util.tree_map(
                lambda x, d: x.astype(d) if x.dtype != d else x, ctx_t, ctx_dtypes
            )
            h_in = jnp.where(s == 0, h_mb[jnp.clip(t, 0, M - 1)], buf)
            y, caps = stage(xs_local, h_in, ctx_t)
            if n_pts:
                caps_store = caps_store.at[m_c].add(
                    jnp.where(valid, caps, jnp.zeros_like(caps))
                )
            outs = outs.at[m_c].add(
                jnp.where(valid & (s == last), y, jnp.zeros_like(y))
            )
            buf = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            return (buf, outs, caps_store), None

        (buf, outs, caps_store), _ = jax.lax.scan(
            tick, (buf, outs, caps_store), jnp.arange(M + n_stages - 1)
        )
        # only the last stage holds real outputs / the owning stage holds
        # each capture; masked psum broadcasts both to every pp rank
        outs = jax.lax.psum(outs, "pp")
        caps_store = jax.lax.psum(caps_store, "pp")
        return outs, caps_store

    h_mb = h.reshape((M, B // M) + h.shape[1:]).astype(io_dtype)
    # keep microbatch rows spread over the data axes, not gathered onto pp
    h_mb = jax.lax.with_sharding_constraint(
        h_mb, NamedSharding(mesh, P(None, ("dp", "fsdp")))
    )
    ctx_flags = _microbatch_flags(ctx, B)
    # the bf16-all-reduce CPU workaround applies to ctx leaves too: the
    # shard_map transpose of a replicated-in bf16 leaf (e.g. a T5
    # encoder_hidden) emits a bf16 psum over pp for its cotangent
    ctx_dtypes = jax.tree_util.tree_map(lambda x: x.dtype, ctx)
    if on_cpu:
        ctx = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x,
            ctx,
        )
    ctx_mb = _split_microbatches(ctx, ctx_flags, M)

    f = jax.shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(P("pp"), P(), P()),
        out_specs=(P(), P()),
        axis_names={"pp"},
        check_vma=False,
    )
    outs, caps_store = f(xs, h_mb, ctx_mb)
    h_out = outs.reshape((B,) + h.shape[1:]).astype(compute_dtype)
    # caps_store: [M, n_pts, B/M, ...] -> per point [B, ...]
    captures = tuple(
        jnp.moveaxis(caps_store, 1, 0)[i]
        .reshape((B,) + h.shape[1:])
        .astype(compute_dtype)
        for i in range(n_pts)
    )
    return h_out, captures
